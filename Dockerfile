# OpsAgent-TPU agent/API image.
#
# Two-stage build: a toolchain stage compiles the native constrained-decoding
# FSM matcher (opsagent_tpu/native/fsm_matcher.cc), then a slim runtime image
# carries the Python package, kubectl + jq for the tool layer, and the
# dedicated python-tool venv (the `python` tool execs scripts inside
# /app/k8s/python-cli/k8s-env; reference parity: /root/reference/Dockerfile:30-44,
# pkg/tools/python.go:31).
#
# This image runs the AGENT layers (CLI / REST server / tools). The TPU
# serving engine runs on TPU nodes from the same image via
#   `opsagent serve-engine` (see deploy/kubernetes/serving-engine.yaml);
# on CPU-only pods the agent talks to it over the OpenAI wire format.

FROM python:3.12-slim AS builder

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY pyproject.toml ./
COPY opsagent_tpu ./opsagent_tpu

# Pre-build the native FSM matcher so the runtime image needs no compiler.
RUN python -c "from opsagent_tpu.native import build_native; build_native()"


FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        ca-certificates curl bash jq tzdata \
    && rm -rf /var/lib/apt/lists/*

# kubectl for the kubectl tool (pinned; the tool shells out via `bash -c`).
ARG KUBECTL_VERSION=v1.30.0
RUN curl --retry 3 -fsSLo /usr/local/bin/kubectl \
        "https://dl.k8s.io/release/${KUBECTL_VERSION}/bin/linux/amd64/kubectl" \
    && chmod +x /usr/local/bin/kubectl

WORKDIR /app
COPY pyproject.toml ./
COPY opsagent_tpu ./opsagent_tpu
COPY configs ./configs
COPY --from=builder /src/opsagent_tpu/native/_native.so ./opsagent_tpu/native/_native.so

# Agent runtime deps. jax[cpu] serves the agent layers; TPU pods get the
# TPU jaxlib from their node image / a requirements overlay.
RUN pip install --no-cache-dir "jax[cpu]" numpy && \
    pip install --no-cache-dir ".[tokens]"

# Sandbox venv for the `python` tool (kept separate from the app runtime so
# model-generated scripts cannot import the server's own dependencies).
RUN python -m venv /app/k8s/python-cli/k8s-env && \
    /app/k8s/python-cli/k8s-env/bin/pip install --no-cache-dir \
        kubernetes==29.0.0 pyyaml==6.0.1 pandas==2.2.1 && \
    ln -s /app/k8s /root/k8s

RUN useradd -u 1000 -m opsagent && mkdir -p /app/logs && \
    chown -R opsagent:opsagent /app/logs

ENV PYTHONUNBUFFERED=1
EXPOSE 8080
ENTRYPOINT ["opsagent"]
CMD ["server", "--port", "8080"]
