"""Native (C++) runtime components, loaded via ctypes.

The compute path of this framework is JAX/XLA/Pallas; the host runtime
around it uses native code where Python would sit on a hot or
latency-sensitive path. First component: the constrained-decoding FSM
matcher (fsm_matcher.cc) — eagerly precomputed [states x vocab] token
admissibility + destination tables with O(row-copy) per-step cost.

Build story: no pybind11 in this image, so the module is a flat C ABI
compiled on first use with g++ into ``_native.so`` next to the sources
(skipped when already fresh). Everything degrades gracefully: if g++ or the
build is unavailable, callers fall back to the pure-Python implementations
(`OPSAGENT_NATIVE=0` forces the fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..utils.logger import get_logger

log = get_logger("native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fsm_matcher.cc")
_SO = os.path.join(_DIR, "_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
        "-o", _SO, _SRC,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable (%s); using Python fallback", e)
        return False
    if proc.returncode != 0:
        log.warning(
            "native build failed; using Python fallback: %s",
            proc.stderr[-500:],
        )
        return False
    return True


def build_native() -> None:
    """Ahead-of-time build entry point (Docker image build / CI): compile
    ``_native.so`` now and fail loudly, instead of the lazy build-on-first-
    use with graceful fallback that ``get_lib`` does at runtime."""
    if not _build():
        raise RuntimeError("native build failed (see log for compiler output)")


def get_lib():
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _build_failed
    if os.environ.get("OPSAGENT_NATIVE", "1") == "0":
        return None
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        fresh = os.path.exists(_SO) and (
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        )
        if not fresh and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native load failed (%s); using Python fallback", e)
            _build_failed = True
            return None
        lib.opsagent_fsm_build.restype = ctypes.c_void_p
        lib.opsagent_fsm_build.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
        ]
        lib.opsagent_fsm_num_states.restype = ctypes.c_int32
        lib.opsagent_fsm_num_states.argtypes = [ctypes.c_void_p]
        lib.opsagent_fsm_mask.restype = None
        lib.opsagent_fsm_mask.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p
        ]
        lib.opsagent_fsm_advance.restype = ctypes.c_int32
        lib.opsagent_fsm_advance.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32
        ]
        lib.opsagent_fsm_free.restype = None
        lib.opsagent_fsm_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        log.info("native runtime loaded (%s)", _SO)
        return _lib


class NativeFSMTables:
    """Precomputed token-admissibility tables over a byte DFA (C++ owned).

    Mirrors the lazy Python TokenFSM interface: ``mask_for_state`` and
    ``advance``. Construction runs the full [states x vocab] precompute in
    parallel native threads."""

    def __init__(
        self,
        dfa_next: np.ndarray,     # [num_states*256] int32
        dfa_accept: np.ndarray,   # [num_states] bool
        token_bytes: list[bytes],
        eos_id: int,
    ):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self.vocab = len(token_bytes)
        self.num_states = len(dfa_accept)
        self._next = np.ascontiguousarray(dfa_next, np.int32)
        self._accept = np.ascontiguousarray(
            np.asarray(dfa_accept), np.uint8
        )
        blob = b"".join(token_bytes)
        offsets = np.zeros((self.vocab + 1,), np.int32)
        offsets[1:] = np.cumsum([len(tb) for tb in token_bytes])
        self._blob = np.frombuffer(blob, np.uint8) if blob else np.zeros(
            (1,), np.uint8
        )
        self._offsets = np.ascontiguousarray(offsets)
        self._handle = lib.opsagent_fsm_build(
            self._next.ctypes.data, self._accept.ctypes.data,
            ctypes.c_int32(self.num_states),
            self._blob.ctypes.data, self._offsets.ctypes.data,
            ctypes.c_int32(self.vocab), ctypes.c_int32(eos_id),
            ctypes.c_int32(0),
        )
        if not self._handle:
            raise RuntimeError("native FSM build returned null")

    def mask_for_state(self, state: int) -> np.ndarray:
        out = np.empty((self.vocab,), np.uint8)
        self._lib.opsagent_fsm_mask(
            self._handle, ctypes.c_int32(state), out.ctypes.data
        )
        return out.astype(bool)

    def advance(self, state: int, token_id: int) -> int:
        return int(
            self._lib.opsagent_fsm_advance(
                self._handle, ctypes.c_int32(state), ctypes.c_int32(token_id)
            )
        )

    def __del__(self):
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.opsagent_fsm_free(handle)
            self._handle = None
