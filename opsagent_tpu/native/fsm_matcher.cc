// Native FSM token-mask matcher for constrained decoding.
//
// The Python TokenFSM (serving/constrained.py) computes per-DFA-state token
// admissibility masks lazily with vectorized numpy. This C++ component
// precomputes the FULL [num_states x vocab] mask table and the
// [num_states x vocab] destination table eagerly and in parallel, so the
// engine's per-step cost is a row memcpy — no Python in the sampling path
// beyond the ctypes call, and no cold-state latency at all.
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image). All
// memory is owned by the handle; the Python side copies rows out.
//
// There is no counterpart in the reference (a Go agent calling a remote
// LLM over HTTPS, pkg/llms/openai.go); this is part of the TPU-native
// serving runtime that replaces it.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct FsmTables {
  int32_t num_states = 0;
  int32_t vocab = 0;
  int32_t words_per_row = 0;           // 64-bit words per mask row
  std::vector<uint64_t> masks;         // [num_states][words_per_row]
  std::vector<int32_t> dest;           // [num_states][vocab], -1 = dead
  std::vector<uint8_t> accept;         // [num_states]
};

// Walk one token's bytes from `state`; returns final state or -1.
inline int32_t run_bytes(const int32_t* dfa_next, int32_t state,
                         const uint8_t* bytes, int32_t len) {
  for (int32_t j = 0; j < len; ++j) {
    state = dfa_next[static_cast<int64_t>(state) * 256 + bytes[j]];
    if (state < 0) return -1;
  }
  return state;
}

}  // namespace

extern "C" {

// Build the full tables.
//   dfa_next:      [num_states * 256] int32, -1 = dead
//   dfa_accept:    [num_states] uint8
//   token_bytes:   concatenated token byte strings
//   token_offsets: [vocab + 1] int32 offsets into token_bytes
//   eos_id:        token admissible exactly in accepting states
//   num_threads:   0 = hardware concurrency
void* opsagent_fsm_build(const int32_t* dfa_next, const uint8_t* dfa_accept,
                         int32_t num_states, const uint8_t* token_bytes,
                         const int32_t* token_offsets, int32_t vocab,
                         int32_t eos_id, int32_t num_threads) {
  auto* t = new FsmTables();
  t->num_states = num_states;
  t->vocab = vocab;
  t->words_per_row = (vocab + 63) / 64;
  t->masks.assign(static_cast<size_t>(num_states) * t->words_per_row, 0);
  t->dest.assign(static_cast<size_t>(num_states) * vocab, -1);
  t->accept.assign(dfa_accept, dfa_accept + num_states);

  int n_threads = num_threads > 0
                      ? num_threads
                      : static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads < 1) n_threads = 1;

  std::atomic<int32_t> next_state{0};
  auto worker = [&]() {
    for (;;) {
      int32_t s = next_state.fetch_add(1);
      if (s >= num_states) return;
      uint64_t* mask_row = &t->masks[static_cast<size_t>(s) * t->words_per_row];
      int32_t* dest_row = &t->dest[static_cast<size_t>(s) * vocab];
      for (int32_t tok = 0; tok < vocab; ++tok) {
        int32_t off = token_offsets[tok];
        int32_t len = token_offsets[tok + 1] - off;
        if (len == 0) {
          // Special token (no output bytes): never admissible via the mask,
          // but advancing over it leaves the state unchanged — matching the
          // Python dfa.run(state, b"") semantics.
          dest_row[tok] = s;
          continue;
        }
        int32_t end = run_bytes(dfa_next, s, token_bytes + off, len);
        dest_row[tok] = end;
        if (end >= 0) mask_row[tok >> 6] |= (1ULL << (tok & 63));
      }
      if (t->accept[s] && eos_id >= 0 && eos_id < vocab) {
        mask_row[eos_id >> 6] |= (1ULL << (eos_id & 63));
      }
    }
  };
  std::vector<std::thread> threads;
  for (int i = 0; i < n_threads; ++i) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  return t;
}

int32_t opsagent_fsm_num_states(void* handle) {
  return static_cast<FsmTables*>(handle)->num_states;
}

// Copy state's mask row as unpacked bytes (0/1) into out[vocab].
void opsagent_fsm_mask(void* handle, int32_t state, uint8_t* out) {
  auto* t = static_cast<FsmTables*>(handle);
  if (state < 0 || state >= t->num_states) {
    std::memset(out, 0, t->vocab);
    return;
  }
  const uint64_t* row = &t->masks[static_cast<size_t>(state) * t->words_per_row];
  for (int32_t tok = 0; tok < t->vocab; ++tok) {
    out[tok] = (row[tok >> 6] >> (tok & 63)) & 1;
  }
}

// Advance by one token id; -1 if dead/invalid.
int32_t opsagent_fsm_advance(void* handle, int32_t state, int32_t token) {
  auto* t = static_cast<FsmTables*>(handle);
  if (state < 0 || state >= t->num_states || token < 0 || token >= t->vocab)
    return -1;
  return t->dest[static_cast<size_t>(state) * t->vocab + token];
}

void opsagent_fsm_free(void* handle) { delete static_cast<FsmTables*>(handle); }

}  // extern "C"
