from .client import K8sClient, KubeConfig, get_kube_config, get_yaml, apply_yaml

__all__ = ["K8sClient", "KubeConfig", "get_kube_config", "get_yaml", "apply_yaml"]
