"""Kubernetes API client: kubeconfig loading, discovery, GET-as-YAML, and
server-side apply.

Capability parity with the reference's pkg/kubernetes/: ``GetKubeConfig``
(in-cluster then ~/.kube/config fallback, apply.go:24-35), ``GetYaml``
(discovery + RESTMapper -> dynamic GET -> YAML, get.go:30-89) and
``ApplyYaml`` (multi-doc decode -> server-side apply with field manager
"application/apply-patch", apply.go:55-100) — implemented directly over the
Kubernetes REST API with stdlib HTTP, no client library.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Any

import yaml

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sError(Exception):
    pass


@dataclass
class KubeConfig:
    server: str = ""
    token: str = ""
    ca_cert_path: str = ""
    client_cert_path: str = ""
    client_key_path: str = ""
    insecure: bool = False
    namespace: str = "default"
    _tempfiles: list[str] = field(default_factory=list, repr=False)


def _write_temp(data: bytes, cfg: KubeConfig, suffix: str) -> str:
    f = tempfile.NamedTemporaryFile(delete=False, suffix=suffix)
    f.write(data)
    f.close()
    cfg._tempfiles.append(f.name)
    return f.name


def _load_kubeconfig_file(path: str) -> KubeConfig:
    with open(path, "r", encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    ctx_name = doc.get("current-context", "")
    contexts = {c["name"]: c.get("context", {}) for c in doc.get("contexts", [])}
    clusters = {c["name"]: c.get("cluster", {}) for c in doc.get("clusters", [])}
    users = {u["name"]: u.get("user", {}) for u in doc.get("users", [])}
    ctx = contexts.get(ctx_name) or (next(iter(contexts.values())) if contexts else {})
    cluster = clusters.get(ctx.get("cluster", "")) or (
        next(iter(clusters.values())) if clusters else {}
    )
    user = users.get(ctx.get("user", "")) or (next(iter(users.values())) if users else {})

    cfg = KubeConfig(
        server=cluster.get("server", ""),
        insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
        namespace=ctx.get("namespace", "default"),
    )
    if "certificate-authority" in cluster:
        cfg.ca_cert_path = cluster["certificate-authority"]
    elif "certificate-authority-data" in cluster:
        cfg.ca_cert_path = _write_temp(
            base64.b64decode(cluster["certificate-authority-data"]), cfg, ".crt"
        )
    cfg.token = user.get("token", "")
    if "client-certificate" in user:
        cfg.client_cert_path = user["client-certificate"]
    elif "client-certificate-data" in user:
        cfg.client_cert_path = _write_temp(
            base64.b64decode(user["client-certificate-data"]), cfg, ".crt"
        )
    if "client-key" in user:
        cfg.client_key_path = user["client-key"]
    elif "client-key-data" in user:
        cfg.client_key_path = _write_temp(
            base64.b64decode(user["client-key-data"]), cfg, ".key"
        )
    return cfg


def get_kube_config() -> KubeConfig:
    """In-cluster service account first, then $KUBECONFIG / ~/.kube/config."""
    if os.path.isfile(os.path.join(_SA_DIR, "token")) and os.environ.get(
        "KUBERNETES_SERVICE_HOST"
    ):
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(_SA_DIR, "token"), "r", encoding="utf-8") as f:
            token = f.read().strip()
        ns = "default"
        ns_path = os.path.join(_SA_DIR, "namespace")
        if os.path.isfile(ns_path):
            with open(ns_path, "r", encoding="utf-8") as f:
                ns = f.read().strip() or "default"
        return KubeConfig(
            server=f"https://{host}:{port}",
            token=token,
            ca_cert_path=os.path.join(_SA_DIR, "ca.crt"),
            namespace=ns,
        )
    path = os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
    if not os.path.isfile(path):
        raise K8sError(f"no kubeconfig found at {path} and not running in-cluster")
    return _load_kubeconfig_file(path)


class K8sClient:
    """Thin typed REST client with API discovery."""

    def __init__(self, config: KubeConfig | None = None):
        self.cfg = config or get_kube_config()
        self._discovery: dict[str, tuple[str, str, str, bool]] | None = None

    # -- transport ---------------------------------------------------------
    def _ssl_context(self) -> ssl.SSLContext | None:
        if not self.cfg.server.startswith("https"):
            return None
        if self.cfg.insecure:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        else:
            ctx = ssl.create_default_context(
                cafile=self.cfg.ca_cert_path or None
            )
        if self.cfg.client_cert_path:
            ctx.load_cert_chain(self.cfg.client_cert_path, self.cfg.client_key_path)
        return ctx

    def request(
        self,
        method: str,
        path: str,
        body: bytes | None = None,
        content_type: str = "application/json",
        query: dict[str, str] | None = None,
    ) -> Any:
        url = self.cfg.server.rstrip("/") + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        headers = {"Accept": "application/json", "Content-Type": content_type}
        if self.cfg.token:
            headers["Authorization"] = f"Bearer {self.cfg.token}"
        req = urllib.request.Request(url, data=body, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30, context=self._ssl_context()) as r:
                raw = r.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")[:800]
            raise K8sError(f"{method} {path}: HTTP {e.code}: {detail}") from e
        except urllib.error.URLError as e:
            raise K8sError(f"{method} {path}: {e.reason}") from e
        if not raw:
            return None
        return json.loads(raw)

    # -- discovery (RESTMapper equivalent) ---------------------------------
    def _discover(self) -> dict[str, tuple[str, str, str, bool]]:
        """Build {alias -> (group, version, plural, namespaced)} from the
        apiserver's discovery endpoints."""
        if self._discovery is not None:
            return self._discovery
        mapping: dict[str, tuple[str, str, str, bool]] = {}

        def add(group: str, version: str, res: dict[str, Any]) -> None:
            plural = res.get("name", "")
            if "/" in plural:  # subresource like pods/log
                return
            entry = (group, version, plural, bool(res.get("namespaced")))
            aliases = {plural, res.get("singularName", ""), res.get("kind", "").lower()}
            aliases.update(res.get("shortNames", []) or [])
            for a in aliases:
                if a and a not in mapping:
                    mapping[a] = entry

        core = self.request("GET", "/api/v1") or {}
        for res in core.get("resources", []):
            add("", "v1", res)
        groups = (self.request("GET", "/apis") or {}).get("groups", [])
        for g in groups:
            pref = g.get("preferredVersion", {}).get("groupVersion") or ""
            if not pref:
                continue
            gv = self.request("GET", f"/apis/{pref}") or {}
            grp, _, ver = pref.partition("/")
            for res in gv.get("resources", []):
                add(grp, ver, res)
        self._discovery = mapping
        return mapping

    def _resource_path(
        self, resource: str, namespace: str, name: str = ""
    ) -> str:
        mapping = self._discover()
        key = resource.lower()
        if key not in mapping:
            raise K8sError(f"unknown resource type: {resource}")
        group, version, plural, namespaced = mapping[key]
        base = f"/api/{version}" if not group else f"/apis/{group}/{version}"
        if namespaced:
            path = f"{base}/namespaces/{namespace or self.cfg.namespace}/{plural}"
        else:
            path = f"{base}/{plural}"
        if name:
            path += f"/{name}"
        return path

    # -- public ------------------------------------------------------------
    def get_yaml(self, resource: str, name: str, namespace: str = "") -> str:
        """Fetch a live object and render it as YAML."""
        obj = self.request("GET", self._resource_path(resource, namespace, name))
        return yaml.safe_dump(obj, sort_keys=False, allow_unicode=True)

    def apply_yaml(self, manifests: str) -> list[str]:
        """Server-side apply every document in a multi-doc YAML string.
        Returns a list of "kind/name" applied."""
        applied: list[str] = []
        for doc in yaml.safe_load_all(manifests):
            if not doc:
                continue
            api_version = doc.get("apiVersion", "")
            kind = doc.get("kind", "")
            meta = doc.get("metadata", {}) or {}
            name = meta.get("name", "")
            namespace = meta.get("namespace", "")
            if not api_version or not kind or not name:
                raise K8sError(
                    f"manifest missing apiVersion/kind/metadata.name: {doc}"
                )
            path = self._resource_path(kind.lower(), namespace, name)
            self.request(
                "PATCH",
                path,
                body=yaml.safe_dump(doc).encode("utf-8"),
                content_type="application/apply-patch+yaml",
                query={
                    "fieldManager": "application/apply-patch",
                    "force": "true",
                },
            )
            applied.append(f"{kind}/{name}")
        return applied


# Module-level conveniences mirroring the reference's package functions.
def get_yaml(resource: str, name: str, namespace: str = "") -> str:
    return K8sClient().get_yaml(resource, name, namespace)


def apply_yaml(manifests: str) -> list[str]:
    return K8sClient().apply_yaml(manifests)
