"""search tool: Google Custom Search.

Capability parity with the reference's pkg/tools/googlesearch.go:28-44
(GOOGLE_API_KEY / GOOGLE_CSE_ID env credentials; registered as "search").
"""

from __future__ import annotations

import json
import os
import urllib.parse
import urllib.request

from . import ToolError


def google_search(query: str, timeout: float = 15.0) -> str:
    api_key = os.environ.get("GOOGLE_API_KEY")
    cse_id = os.environ.get("GOOGLE_CSE_ID")
    if not api_key or not cse_id:
        raise ToolError(
            "search tool requires GOOGLE_API_KEY and GOOGLE_CSE_ID environment variables"
        )
    url = "https://customsearch.googleapis.com/customsearch/v1?" + urllib.parse.urlencode(
        {"key": api_key, "cx": cse_id, "q": query, "num": 5}
    )
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
    except Exception as e:  # noqa: BLE001 - network errors become observations
        raise ToolError(f"search failed: {e}") from e
    items = payload.get("items") or []
    if not items:
        return "(no results)"
    lines = []
    for it in items:
        lines.append(f"{it.get('title', '')}\n{it.get('link', '')}\n{it.get('snippet', '')}")
    return "\n\n".join(lines)
