"""trivy tool: container image vulnerability scanning.

Capability parity with the reference's pkg/tools/trivy.go: strips an optional
``image `` prefix (trivy.go:29-31) and runs ``trivy image <img> --scanners
vuln`` (trivy.go:37).
"""

from __future__ import annotations

import subprocess

from . import ToolError, proc

# Conveyor launch readiness (agent/conveyor.py): the image name is the
# only argument the scanner needs to start.
LAUNCH_FIELDS = ("image",)


def trivy(image: str, timeout: float = 300.0) -> str:
    img = image.strip()
    if img.startswith("image "):
        img = img[len("image ") :].strip()
    if not img:
        raise ToolError("no image name given to trivy")
    try:
        res = proc.run(
            ["trivy", "image", img, "--scanners", "vuln"], timeout=timeout
        )
    except FileNotFoundError as e:
        raise ToolError(f"trivy not available: {e}") from e
    except subprocess.TimeoutExpired as e:
        raise ToolError(f"trivy timed out after {timeout}s") from e
    if res.returncode != 0:
        raise ToolError(res.stderr.strip() or f"trivy exited with {res.returncode}")
    return res.stdout.strip() or "(no output)"
