"""trivy tool: container image vulnerability scanning.

Capability parity with the reference's pkg/tools/trivy.go: strips an optional
``image `` prefix (trivy.go:29-31) and runs ``trivy image <img> --scanners
vuln`` (trivy.go:37).
"""

from __future__ import annotations

import subprocess

from . import ToolError


def trivy(image: str, timeout: float = 300.0) -> str:
    img = image.strip()
    if img.startswith("image "):
        img = img[len("image ") :].strip()
    if not img:
        raise ToolError("no image name given to trivy")
    try:
        proc = subprocess.run(
            ["trivy", "image", img, "--scanners", "vuln"],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except FileNotFoundError as e:
        raise ToolError(f"trivy not available: {e}") from e
    except subprocess.TimeoutExpired as e:
        raise ToolError(f"trivy timed out after {timeout}s") from e
    if proc.returncode != 0:
        raise ToolError(proc.stderr.strip() or f"trivy exited with {proc.returncode}")
    return proc.stdout.strip() or "(no output)"
