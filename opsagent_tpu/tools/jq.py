"""jq tool: apply a jq expression to JSON data.

Input convention (reference pkg/tools/jq.go:39-45): ``"<JSON> | <jq-expr>"``
split on the first top-level pipe; the JSON is validated first (jq.go:52) and
piped to the ``jq`` binary via stdin (jq.go:73-74). An expression-complexity
metric is recorded (jq.go:108-118). When the jq binary is missing we fall back
to a small built-in evaluator covering the common path/filter forms the agent
emits.
"""

from __future__ import annotations

import json
import re
import subprocess
from typing import Any

from . import ToolError, proc
from ..utils.perf import get_perf_stats

# Conveyor launch readiness (agent/conveyor.py): jq needs BOTH halves of
# its piped input — the JSON document and the expression — before a
# launch makes sense (the single ReAct ``input`` string carries both).
LAUNCH_FIELDS = ("data", "expr")


def _split_input(s: str) -> tuple[str, str]:
    depth = 0
    in_str = False
    esc = False
    for i, c in enumerate(s):
        if esc:
            esc = False
            continue
        if c == "\\":
            esc = True
            continue
        if c == '"':
            in_str = not in_str
            continue
        if in_str:
            continue
        if c in "[{(":
            depth += 1
        elif c in ")}]":
            depth -= 1
        elif c == "|" and depth == 0:
            return s[:i].strip(), s[i + 1 :].strip()
    raise ToolError(
        'jq input must be "<JSON> | <jq-expression>" (no top-level pipe found)'
    )


def _complexity(expr: str) -> int:
    return len(re.findall(r"[.\[\]|()]|select|map|test", expr))


def _eval_path(obj: Any, expr: str) -> Any:
    """Tiny jq subset: .a.b[0], .[], .items[].metadata.name, length."""
    expr = expr.strip()
    if expr == "length":
        return len(obj)
    if not expr.startswith("."):
        raise ToolError(f"built-in jq fallback cannot evaluate: {expr}")
    results = [obj]
    for part in re.finditer(r"\.([A-Za-z_][\w-]*)?(\[\d*\])?", expr):
        key, idx = part.group(1), part.group(2)
        nxt: list[Any] = []
        for cur in results:
            if key is not None:
                if not isinstance(cur, dict) or key not in cur:
                    raise ToolError(f"key not found: {key}")
                cur = cur[key]
            if idx is not None:
                if idx == "[]":
                    if not isinstance(cur, list):
                        raise ToolError("cannot iterate non-array")
                    nxt.extend(cur)
                    continue
                i = int(idx[1:-1])
                if not isinstance(cur, list) or i >= len(cur):
                    raise ToolError(f"index out of range: {i}")
                cur = cur[i]
            nxt.append(cur)
        results = nxt
    if len(results) == 1:
        return results[0]
    return results


def jq(input_str: str, timeout: float = 30.0) -> str:
    data, expr = _split_input(input_str)
    try:
        parsed = json.loads(data)
    except json.JSONDecodeError as e:
        raise ToolError(f"invalid JSON passed to jq: {e}") from e
    ps = get_perf_stats()
    ps.record_metric("tool.jq.complexity", _complexity(expr), "ops")
    with ps.timer("tool.jq"):
        try:
            res = proc.run(
                ["jq", expr], input_text=json.dumps(parsed), timeout=timeout
            )
            if res.returncode != 0:
                raise ToolError(res.stderr.strip() or "jq failed")
            return res.stdout.strip()
        except FileNotFoundError:
            result = _eval_path(parsed, expr)
            if isinstance(result, list):
                return "\n".join(json.dumps(r, ensure_ascii=False) for r in result)
            return json.dumps(result, ensure_ascii=False)
        except subprocess.TimeoutExpired as e:
            raise ToolError(f"jq timed out after {timeout}s") from e
