"""Shared subprocess spawn/timeout plumbing for the tool layer.

Every tool (``kubectl``, ``python``, ``trivy``, ``jq``) used to call
``subprocess.run`` directly; the four copies disagreed on kill behavior
(none of them killed the child's *descendants*, so a ``bash -c`` pipeline
that out-lived its timeout kept running detached). This module unifies
them:

- ``run(...)`` — the drop-in blocking helper all tools call. Same
  CompletedProcess contract as ``subprocess.run(capture_output=True,
  text=True)``, but the child runs in its own **process group**
  (``start_new_session=True``) and a timeout kills the whole group:
  SIGTERM, a short grace window, then SIGKILL. ``FileNotFoundError``
  propagates from spawn exactly like ``subprocess.run`` (kubectl/trivy
  turn it into "not available", jq falls back to its built-in
  evaluator).

- ``ToolProcess`` — the async form used by conveyor tool launches
  (agent/conveyor.py): Popen + an output-capture thread + a timeout
  watchdog + ``cancel()``. The blocking ``run`` is implemented on top of
  it, so both paths share one kill discipline.

- ``cancel_scope(...)`` — a thread-local registry: processes spawned on
  a thread inside the scope are killable from *another* thread. The
  conveyor launch worker wraps the tool callable in one so a
  mismatch-cancel can reap a subprocess it never got a handle to.
"""

from __future__ import annotations

import contextlib
import os
import signal
import subprocess
import threading
import time
from typing import Iterator

# Grace between the group SIGTERM and the follow-up SIGKILL. Short: tool
# children are bash pipelines / scanners, not databases with shutdown
# hooks, and the agent turn is latency-bound on this path.
KILL_GRACE_S = float(os.environ.get("OPSAGENT_TOOL_KILL_GRACE_S", "1.0"))

_scope_tls = threading.local()


@contextlib.contextmanager
def cancel_scope(procs: list["ToolProcess"]) -> Iterator[list["ToolProcess"]]:
    """Register every ToolProcess spawned on this thread into ``procs``."""
    prev = getattr(_scope_tls, "procs", None)
    _scope_tls.procs = procs
    try:
        yield procs
    finally:
        _scope_tls.procs = prev


def _kill_group(pid: int, grace_s: float = KILL_GRACE_S) -> None:
    """SIGTERM the child's process group, wait ``grace_s``, SIGKILL what
    survives. Tolerates the group being gone already at every step."""
    try:
        pgid = os.getpgid(pid)
    except (ProcessLookupError, PermissionError):
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    # Existence-poll with signal 0 (never waitpid here: reaping belongs
    # to the Popen owner's communicate/wait). A zombie keeps the group
    # alive until reaped, so the fallback SIGKILL may hit an already-dead
    # group — harmless.
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        try:
            os.killpg(pgid, 0)
        except (ProcessLookupError, PermissionError):
            return
        time.sleep(0.02)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


class ToolProcess:
    """One tool subprocess with captured output, a timeout watchdog, and
    group-kill cancel — the async executor conveyor launches run on.

    Spawn errors (``FileNotFoundError`` for a missing binary) raise
    synchronously from the constructor, preserving each tool's
    "not available" / built-in-fallback handling.
    """

    def __init__(
        self,
        argv: list[str],
        input_text: str | None = None,
        timeout: float | None = None,
        cwd: str | None = None,
    ) -> None:
        self.argv = argv
        self.timeout = timeout
        self.stdout = ""
        self.stderr = ""
        self.returncode: int | None = None
        self.timed_out = False
        self.cancelled = False
        self._done = threading.Event()
        self.proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE if input_text is not None else None,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=cwd,
            start_new_session=True,
        )
        scope = getattr(_scope_tls, "procs", None)
        if scope is not None:
            scope.append(self)
        # communicate() owns stdin write + both pipe drains; the thread
        # exists so wait() callers get a timeout watchdog that group-kills
        # instead of subprocess.run's pipe-leaking TimeoutExpired.
        self._reader = threading.Thread(
            target=self._communicate, args=(input_text,), daemon=True
        )
        self._reader.start()

    def _communicate(self, input_text: str | None) -> None:
        try:
            try:
                out, err = self.proc.communicate(input_text, self.timeout)
            except subprocess.TimeoutExpired:
                self.timed_out = True
                _kill_group(self.proc.pid)
                out, err = self.proc.communicate()
            except BaseException:
                _kill_group(self.proc.pid)
                raise
            self.stdout, self.stderr = out or "", err or ""
            self.returncode = self.proc.returncode
        finally:
            self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def cancel(self) -> None:
        """Group-kill the child; the capture thread reaps it."""
        self.cancelled = True
        _kill_group(self.proc.pid, grace_s=0.2)

    def result(self) -> subprocess.CompletedProcess:
        """Block for completion; CompletedProcess on exit, TimeoutExpired
        after the group was killed for overrunning its budget."""
        self._done.wait()
        if self.timed_out:
            raise subprocess.TimeoutExpired(self.argv, self.timeout or 0.0)
        return subprocess.CompletedProcess(
            self.argv, self.returncode or 0, self.stdout, self.stderr
        )


def run(
    argv: list[str],
    input_text: str | None = None,
    timeout: float | None = None,
    cwd: str | None = None,
) -> subprocess.CompletedProcess:
    """Blocking spawn with group-kill timeout — the shared helper every
    tool calls in place of ``subprocess.run(capture_output=True,
    text=True)``."""
    return ToolProcess(
        argv, input_text=input_text, timeout=timeout, cwd=cwd
    ).result()
