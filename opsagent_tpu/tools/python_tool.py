"""python tool: execute a Python script and return stdout.

Capability parity with the reference's pkg/tools/python.go:30-32 which runs
``python3 -c "<script>"`` inside a dedicated k8s ops venv. We execute the
script with the venv's interpreter when the conventional layout exists
(~/k8s/python-cli/k8s-env, matching the reference Dockerfile:34-44), else the
current interpreter; the script is passed via argv so no shell quoting/escaping
of the script body is needed.
"""

from __future__ import annotations

import os
import subprocess
import sys

from . import ToolError, proc

_VENV_PY = os.path.expanduser("~/k8s/python-cli/k8s-env/bin/python3")

# Conveyor launch readiness (agent/conveyor.py): the script body is the
# only argument needed to start the interpreter.
LAUNCH_FIELDS = ("script",)


def interpreter() -> str:
    return _VENV_PY if os.path.isfile(_VENV_PY) else sys.executable


def python_repl(script: str, timeout: float = 120.0) -> str:
    try:
        res = proc.run(
            [interpreter(), "-c", script],
            timeout=timeout,
            cwd=os.path.expanduser("~"),
        )
    except subprocess.TimeoutExpired as e:
        raise ToolError(f"python script timed out after {timeout}s") from e
    if res.returncode != 0:
        raise ToolError(res.stderr.strip() or f"python exited with {res.returncode}")
    out = res.stdout.strip()
    return out if out else "(no output)"
