"""kubectl tool: run kubectl command lines through a shell.

Capability parity with the reference's pkg/tools/kubectl.go: prepends
``kubectl`` when missing (kubectl.go:75-77), executes via ``bash -c`` so pipes
work (kubectl.go:32), classifies the verb for metrics (kubectl.go:119-131) and
filters noisy apiserver/klog error lines from output (kubectl.go:145-194).
"""

from __future__ import annotations

import re
import subprocess

from . import ToolError, proc
from ..utils.perf import get_perf_stats

# Conveyor launch readiness (agent/conveyor.py): the shell line is the
# only argument kubectl needs to start.
LAUNCH_FIELDS = ("command",)

_VERBS = ("get", "describe", "logs", "exec", "apply", "delete", "top", "create", "patch")

# klog-style lines (E0307 12:34:56.789 ...) and known-noisy apiserver chatter.
_NOISE = re.compile(
    r"^[EWIF]\d{4} \d{2}:\d{2}:\d{2}\.\d+"
    r"|couldn't get current server API group list"
    r"|metrics\.k8s\.io.*(unavailable|error)"
    r"|the server is currently unable to handle the request \(get .*metrics"
)


def _classify(cmd: str) -> str:
    for verb in _VERBS:
        if re.search(rf"\bkubectl(\s+\S+)*\s+{verb}\b", cmd) or cmd.strip().startswith(verb):
            return verb
    return "other"


def filter_noise(output: str) -> str:
    kept = [ln for ln in output.splitlines() if not _NOISE.search(ln.strip())]
    return "\n".join(kept).strip()


def kubectl(command: str, timeout: float = 90.0) -> str:
    cmd = command.strip()
    if not cmd.startswith("kubectl"):
        cmd = "kubectl " + cmd
    ps = get_perf_stats()
    ps.record_metric(f"tool.kubectl.{_classify(cmd)}", 1, "calls")
    with ps.timer("tool.kubectl"):
        try:
            res = proc.run(["bash", "-c", cmd], timeout=timeout)
        except FileNotFoundError as e:
            raise ToolError(f"kubectl not available: {e}") from e
        except subprocess.TimeoutExpired as e:
            raise ToolError(f"kubectl timed out after {timeout}s: {cmd}") from e
    out = filter_noise(res.stdout)
    err = filter_noise(res.stderr)
    if res.returncode != 0:
        raise ToolError(err or out or f"kubectl exited with {res.returncode}")
    result = out or err
    return result if result else "(no output)"
