"""kubectl replay shims: canned cluster output without a cluster.

The tool layer runs commands through ``bash -c`` (tools/kubectl.py), so a
script named ``kubectl`` earlier on PATH serves recorded transcripts —
the hermetic-testing answer to the reference's untested live-cluster
dependency (SURVEY.md §4). Shared by the e2e scripts
(scripts/run_real_checkpoint.py, scripts/train_tiny_agent.py) and tests
so the replay contract cannot drift between them.
"""

from __future__ import annotations

import os
import stat
import tempfile

# Three namespaces; `... --no-headers | wc -l` pipelines yield "3".
NAMESPACES_SCRIPT = (
    "#!/bin/bash\n"
    "printf 'default\\nkube-system\\nmonitoring\\n'\n"
)

# Wider surface for freeform questions (namespace + pod verbs).
CLUSTER_SCRIPT = """#!/bin/bash
args="$*"
case "$args" in
  *namespace*)
    printf 'default\\nkube-system\\nkube-public\\nmonitoring\\n' ;;
  *pod*)
    printf 'web-1   Running\\nweb-2   CrashLoopBackOff\\n' ;;
  *)
    printf 'replay: no canned output for: %s\\n' "$args" >&2; exit 1 ;;
esac
"""

# Fixed mini-cluster for the MULTI-instruction trained-agent demo
# (scripts/train_tiny_agent.py --tasks multi): namespaces, pods (all/-n
# default), nodes, and version — every command the task corpus trains
# on answers byte-exactly here, so served observations match training.
MULTI_TASK_SCRIPT = """#!/bin/bash
args="$*"
case "$args" in
  *"get namespaces"*)
    printf 'default\\nkube-system\\nmonitoring\\n' ;;
  *"get nodes"*)
    printf 'node-a   Ready\\nnode-b   Ready\\nnode-c   NotReady\\n' ;;
  *version*)
    printf 'Server Version: v1.29.3\\n' ;;
  *"get pods -n default"*)
    printf 'web-1   Running\\napi-1   Running\\n' ;;
  *"get pods"*)
    printf 'web-1   Running\\nweb-2   CrashLoopBackOff\\n' ;;
  *)
    printf 'replay: no canned output for: %s\\n' "$args" >&2; exit 1 ;;
esac
"""


def install_replay_kubectl(
    script: str = NAMESPACES_SCRIPT, tooldir: str | None = None
) -> str:
    """Write a replay ``kubectl`` and prepend its dir to PATH.

    Returns the tool dir. Mutates ``os.environ['PATH']`` for the current
    process (subprocesses spawned by the tool layer inherit it).
    """
    tooldir = tooldir or tempfile.mkdtemp(prefix="opsagent-replay-")
    os.makedirs(tooldir, exist_ok=True)
    path = os.path.join(tooldir, "kubectl")
    with open(path, "w", encoding="utf-8") as f:
        f.write(script)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    os.environ["PATH"] = tooldir + os.pathsep + os.environ["PATH"]
    return tooldir
