"""Tool layer: the agent's action dispatch surface.

A tool is ``Callable[[str], str]`` that raises ``ToolError`` on failure; the
registry maps tool names to callables and is the framework's extension hook
(capability parity with the reference's pkg/tools/tool.go:17-26).

``ToolPrompt`` is the ReAct wire format the agent and the model exchange
(reference pkg/tools/tool.go:29-38): a JSON object with keys question /
thought / action{name,input} / observation / final_answer.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Any

from ..utils.jsonrepair import parse_json


class ToolError(Exception):
    """Raised by a tool on failure; the message becomes the observation."""


Tool = Callable[[str], str]


@dataclass
class ToolAction:
    name: str = ""
    input: str = ""


@dataclass
class ToolPrompt:
    """The ReAct JSON wire format."""

    question: str = ""
    thought: str = ""
    action: ToolAction = field(default_factory=ToolAction)
    observation: str = ""
    final_answer: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "question": self.question,
            "thought": self.thought,
            "action": {"name": self.action.name, "input": self.action.input},
            "observation": self.observation,
            "final_answer": self.final_answer,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), ensure_ascii=False)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ToolPrompt":
        if not isinstance(d, dict):
            raise ValueError("ToolPrompt payload is not an object")
        act = d.get("action") or {}
        if isinstance(act, str):
            # Some models emit "action": "kubectl get ns" — treat as name.
            act = {"name": act, "input": d.get("action_input", "")}

        def _s(v: Any) -> str:
            if v is None:
                return ""
            if isinstance(v, str):
                return v
            return json.dumps(v, ensure_ascii=False)

        return cls(
            question=_s(d.get("question")),
            thought=_s(d.get("thought")),
            action=ToolAction(name=_s(act.get("name")), input=_s(act.get("input"))),
            observation=_s(d.get("observation")),
            final_answer=_s(d.get("final_answer")),
        )

    @classmethod
    def from_json(cls, s: str) -> "ToolPrompt":
        return cls.from_dict(parse_json(s))


def default_registry() -> dict[str, Tool]:
    """The built-in tool registry: {search, python, trivy, kubectl, jq}
    (reference pkg/tools/tool.go:20-26)."""
    from .kubectl import kubectl
    from .python_tool import python_repl
    from .trivy import trivy
    from .jq import jq
    from .search import google_search

    return {
        "kubectl": kubectl,
        "python": python_repl,
        "trivy": trivy,
        "jq": jq,
        "search": google_search,
    }


# Mutable module-level registry, mirroring the reference's CopilotTools map.
copilot_tools: dict[str, Tool] = {}


def get_tools() -> dict[str, Tool]:
    if not copilot_tools:
        copilot_tools.update(default_registry())
    return copilot_tools


# -- conveyor launch readiness ---------------------------------------------
#
# Each tool module declares LAUNCH_FIELDS: the logical argument names that
# suffice to START its subprocess (conveyor partial execution,
# agent/conveyor.py). The ReAct wire format carries every tool's arguments
# in the single ``action.input`` string, so each logical field maps onto
# the ``input`` wire field here; a richer wire format (native tool_calls)
# would map them onto distinct JSON fields instead.

LAUNCH_READY: dict[str, tuple[str, ...]] = {
    "kubectl": ("command",),
    "python": ("script",),
    "trivy": ("image",),
    "jq": ("data", "expr"),
}

_WIRE_FIELD = "input"


def launch_ready_fields(name: str) -> tuple[str, ...]:
    """Logical argument fields that must have closed before ``name`` may
    launch. Unknown-but-registered tools conservatively require their
    full input."""
    return LAUNCH_READY.get(name, (_WIRE_FIELD,))


def wire_fields_for(name: str) -> frozenset[str]:
    """The JSON wire fields carrying the launch-ready arguments — on the
    ReAct single-input wire every logical field rides in ``input``."""
    return frozenset(
        _WIRE_FIELD for _ in launch_ready_fields(name)
    ) or frozenset((_WIRE_FIELD,))
