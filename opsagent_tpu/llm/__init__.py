from .client import ChatClient, LLMError, register_provider
from .tokens import (
    get_token_limits,
    num_tokens_from_messages,
    constrict_messages,
    constrict_prompt,
)

__all__ = [
    "ChatClient",
    "LLMError",
    "register_provider",
    "get_token_limits",
    "num_tokens_from_messages",
    "constrict_messages",
    "constrict_prompt",
]
