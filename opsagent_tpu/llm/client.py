"""OpenAI-compatible chat client with provider routing.

Capability parity with the reference's pkg/llms/openai.go: pluggable base URL
(openai.go:46-47), Azure autodetection when the base URL contains "azure" with
model-name mapping that strips ``[.:]`` (openai.go:49-55), near-greedy
temperature (openai.go:70-75), and retry x5 with doubling backoff on HTTP
429/5xx while failing fast on 401 (openai.go:77-103).

New capability beyond the reference: a ``tpu://`` provider scheme that routes
chat calls to the in-tree TPU serving engine (in-process or over localhost
HTTP), so the agent loop runs with zero external API calls (BASELINE.json
north_star).
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.error
import urllib.request
from typing import Any, Callable

from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats

log = get_logger("llm")


class LLMError(Exception):
    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


# A provider takes a fully-formed chat.completions request dict and returns a
# chat.completions response dict. Schemes (e.g. "tpu") register factories here.
Provider = Callable[[dict[str, Any]], dict[str, Any]]
_provider_factories: dict[str, Callable[[str], Provider]] = {}

# Schemes whose registration lives in a module imported on first use, so the
# agent CLI paths (which never import the serving stack up front) still
# resolve --model tpu://<name> without paying the JAX import cost otherwise.
_LAZY_SCHEME_MODULES = {"tpu": "opsagent_tpu.serving.api"}


def register_provider(scheme: str, factory: Callable[[str], Provider]) -> None:
    """Register a provider factory for a model/baseURL scheme (e.g. "tpu").
    The factory receives the target (the part after ``scheme://``)."""
    _provider_factories[scheme] = factory


def _resolve_scheme(model: str, base_url: str) -> tuple[str, str] | None:
    for candidate in (model, base_url):
        m = re.match(r"^([a-z][a-z0-9+-]*)://(.*)$", candidate or "")
        if m and m.group(1) not in ("http", "https"):
            return m.group(1), m.group(2)
    return None


class ChatClient:
    """Minimal chat.completions client over urllib (no extra deps), with the
    reference's retry ladder and Azure quirks."""

    MAX_RETRIES = 5

    def __init__(self, api_key: str = "", base_url: str = ""):
        self.api_key = api_key or os.environ.get("OPENAI_API_KEY", "")
        self.base_url = (base_url or os.environ.get("OPENAI_API_BASE", "") or
                         "https://api.openai.com/v1").rstrip("/")
        self.is_azure = "azure" in self.base_url.lower()
        self.azure_api_version = "2024-06-01"

    # -- low level ---------------------------------------------------------
    def _endpoint(self, model: str) -> str:
        if self.is_azure:
            deployment = re.sub(r"[.:]", "", model)
            return (
                f"{self.base_url}/openai/deployments/{deployment}"
                f"/chat/completions?api-version={self.azure_api_version}"
            )
        return f"{self.base_url}/chat/completions"

    def _post(self, url: str, body: dict[str, Any], timeout: float) -> dict[str, Any]:
        data = json.dumps(body).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if self.is_azure:
            headers["api-key"] = self.api_key
        else:
            headers["Authorization"] = f"Bearer {self.api_key}"
        req = urllib.request.Request(url, data=data, headers=headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:500]
            except Exception:  # noqa: BLE001
                pass
            raise LLMError(f"HTTP {e.code}: {detail}", status=e.code) from e
        except urllib.error.URLError as e:
            raise LLMError(f"connection error: {e.reason}", status=0) from e

    # -- public ------------------------------------------------------------
    def chat_completion(
        self,
        model: str,
        messages: list[dict[str, Any]],
        max_tokens: int = 2048,
        temperature: float = 1e-45,
        tools: list[dict[str, Any]] | None = None,
        tool_choice: Any = None,
        response_format: dict[str, Any] | None = None,
        timeout: float = 300.0,
    ) -> dict[str, Any]:
        """Full chat.completions round trip returning the raw response dict.

        Retries up to 5 times with doubling backoff on 429/5xx and transient
        connection errors; 401 and other 4xx fail immediately.
        """
        body: dict[str, Any] = {
            "model": model,
            "messages": messages,
            "max_tokens": max_tokens,
            "temperature": temperature,
        }
        if tools:
            body["tools"] = tools
            if tool_choice is not None:
                body["tool_choice"] = tool_choice
        if response_format:
            body["response_format"] = response_format

        scheme = _resolve_scheme(model, self.base_url)
        if scheme is not None:
            name, target = scheme
            factory = _provider_factories.get(name)
            if factory is None and name in _LAZY_SCHEME_MODULES:
                import importlib

                importlib.import_module(_LAZY_SCHEME_MODULES[name])
                factory = _provider_factories.get(name)
            if factory is None:
                raise LLMError(f"no provider registered for scheme {name}://")
            body["model"] = target or model
            provider = factory(target)
            with get_perf_stats().timer(f"llm.chat.{name}"):
                return provider(body)

        url = self._endpoint(model)
        backoff = 1.0
        last: LLMError | None = None
        for attempt in range(self.MAX_RETRIES):
            try:
                with get_perf_stats().timer("llm.chat"):
                    return self._post(url, body, timeout)
            except LLMError as e:
                if e.status == 401:
                    raise
                if e.status == 429 or e.status >= 500 or e.status == 0:
                    last = e
                    log.warning(
                        "chat attempt %d/%d failed (%s); retrying in %.0fs",
                        attempt + 1,
                        self.MAX_RETRIES,
                        e,
                        backoff,
                    )
                    time.sleep(backoff)
                    backoff *= 2
                    continue
                raise
        raise last if last else LLMError("chat failed")

    def chat(
        self,
        model: str,
        max_tokens: int,
        messages: list[dict[str, Any]],
        **kw: Any,
    ) -> str:
        """Convenience wrapper returning the assistant message content."""
        resp = self.chat_completion(model, messages, max_tokens=max_tokens, **kw)
        choices = resp.get("choices") or []
        if not choices:
            raise LLMError("empty choices in chat response")
        return choices[0].get("message", {}).get("content") or ""


def new_client_from_env() -> ChatClient:
    """Provider selection from env, mirroring the reference's NewSwarm
    (pkg/workflows/swarm.go:80-103): OPENAI_API_KEY / OPENAI_API_BASE, or
    AZURE_OPENAI_API_KEY / AZURE_OPENAI_API_BASE."""
    if os.environ.get("AZURE_OPENAI_API_KEY"):
        return ChatClient(
            api_key=os.environ["AZURE_OPENAI_API_KEY"],
            base_url=os.environ.get("AZURE_OPENAI_API_BASE", ""),
        )
    return ChatClient()
