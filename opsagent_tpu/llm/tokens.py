"""Token accounting: context-window table, message counting, shrinking.

Capability parity with the reference's pkg/llms/tokens.go: static
context-window table including qwen-plus (tokens.go:26-46, default 4096 at
tokens.go:55), OpenAI-cookbook message counting (tokens.go:60-107),
``constrict_messages`` evicting the oldest non-system message until the
conversation fits (tokens.go:110-125), and ``constrict_prompt`` dropping the
leading third of lines until under the limit (tokens.go:128-144).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any

# model-name prefix -> context window (tokens)
TOKEN_LIMITS: dict[str, int] = {
    "gpt-4o": 128000,
    "gpt-4-turbo": 128000,
    "gpt-4-32k": 32768,
    "gpt-4": 8192,
    "gpt-3.5-turbo-16k": 16384,
    "gpt-3.5-turbo": 16384,
    "qwen-plus": 131072,
    "qwen-turbo": 131072,
    # Native window (the in-tree presets enforce it at admission; YaRN
    # to 128k is an upstream opt-in config edit).
    "qwen2.5": 32768,
    "deepseek": 65536,
    "llama-3": 8192,
    "llama3": 8192,
    "tpu": 131072,
}

DEFAULT_TOKEN_LIMIT = 4096


def get_token_limits(model: str) -> int:
    m = model.lower()
    if m.startswith("tpu://"):
        # In-tree models: the engine's max_position is authoritative (it
        # REJECTS prompts beyond it at admission, so the agent-side
        # constrictor must budget against the same number). Stacks are
        # installed under ARBITRARY names (tpu://real, tpu://tiny-agent),
        # so ask the installed-stack registry first — but only if the
        # serving module is already loaded: importing it costs a jax
        # import the agent CLI path must not pay, and if it was never
        # imported no stack can be installed in this process anyway.
        import sys

        name = m[len("tpu://"):]
        api_mod = sys.modules.get("opsagent_tpu.serving.api")
        if api_mod is not None:
            # The registry lookup is case-insensitive on its side.
            installed = api_mod.installed_stack_max_position(name)
            if installed is not None:
                return installed
        # Fall back to the preset table (dataclass-only, no jax import).
        from ..models.config import PRESETS

        preset = PRESETS.get(name)
        if preset is not None:
            return preset.max_position
        m = "tpu"
    best = 0
    limit = DEFAULT_TOKEN_LIMIT
    for prefix, window in TOKEN_LIMITS.items():
        if m.startswith(prefix) and len(prefix) > best:
            best = len(prefix)
            limit = window
    return limit


@lru_cache(maxsize=4)
def _encoding(name: str = "cl100k_base"):
    try:
        import tiktoken

        return tiktoken.get_encoding(name)
    except Exception:  # pragma: no cover - tiktoken is baked in
        return None


def count_tokens(text: str) -> int:
    enc = _encoding()
    if enc is None:
        # ~4 chars/token heuristic fallback
        return max(1, len(text) // 4)
    return len(enc.encode(text, disallowed_special=()))


def num_tokens_from_messages(messages: list[dict[str, Any]]) -> int:
    """Count chat tokens per the OpenAI cookbook rules: 3 tokens of overhead
    per message, +1 per name field, +3 for the assistant priming."""
    total = 0
    for msg in messages:
        total += 3
        for key, value in msg.items():
            if isinstance(value, str):
                total += count_tokens(value)
            elif value is not None:
                import json

                total += count_tokens(json.dumps(value, ensure_ascii=False))
            if key == "name":
                total += 1
    return total + 3


def constrict_messages(
    messages: list[dict[str, Any]], model: str, max_tokens: int
) -> list[dict[str, Any]]:
    """Evict the oldest non-system messages until the conversation plus the
    reply budget fits the model's context window.

    The system prompt(s) and the LAST message are never evicted: dropping the
    newest turn would send the model a conversation with no question in it.
    """
    limit = get_token_limits(model) - max_tokens
    msgs = list(messages)
    while msgs and num_tokens_from_messages(msgs) > limit:
        evicted = False
        for i, m in enumerate(msgs[:-1]):
            if m.get("role") != "system":
                del msgs[i]
                evicted = True
                break
        if not evicted:
            break
    return msgs


def constrict_prompt(prompt: str, max_tokens: int) -> str:
    """Shrink a prompt to fit ``max_tokens`` by repeatedly dropping the
    leading third of its lines (keeping the tail, where the recent/salient
    output is)."""
    if max_tokens <= 0:
        return ""
    text = prompt
    while text and count_tokens(text) > max_tokens:
        lines = text.splitlines()
        if len(lines) <= 1:
            # Single huge line: cut by characters from the front.
            keep = max(1, len(text) // 3 * 2)
            text = text[-keep:]
            if count_tokens(text) <= max_tokens:
                return text
            # Force convergence on pathological content.
            approx = max_tokens * 4
            return text[-approx:]
        text = "\n".join(lines[len(lines) // 3 :])
    return text
