"""Snapshot manifest: the on-disk contract of an engine snapshot.

A snapshot directory is::

    <path>/
      manifest.json        # written LAST: its presence marks completion
      weights/leaf-00000.bin ...   # raw leaf bytes in spec-tree order
      compile-cache/...    # the persistent XLA compile cache, copied

The manifest records a blake2b **fingerprint** over the (model config,
engine config) pair plus a per-leaf digest, so ``opsagent snapshot
verify`` and the restore path can refuse a snapshot that does not match
what the restoring engine would compile and shard — a mismatched page
geometry or quantize mode would otherwise surface as a shape error deep
inside ``shard_params`` (or worse, as silent recompiles that void the
zero-post-warmup-compiles invariant).

This module is deliberately jax-free (stdlib only) so ``opsagent
snapshot verify`` runs on any CI box, exactly like ``perf-check``.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any

MANIFEST_NAME = "manifest.json"
WEIGHTS_DIR = "weights"
COMPILE_CACHE_DIR = "compile-cache"
FORMAT_VERSION = 1

_DIGEST_SIZE = 16
_CHUNK = 1 << 22  # 4 MiB digest read chunks


class SnapshotError(RuntimeError):
    """A snapshot is missing, corrupt, or mismatched with this engine."""


def digest_bytes(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=_DIGEST_SIZE).hexdigest()


def digest_file(path: str) -> str:
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def fingerprint(model: dict[str, Any], engine: dict[str, Any]) -> str:
    """Identity of the (model architecture, engine shape-config) pair:
    canonical-JSON blake2b over the two serialized dicts. Anything that
    changes compiled program shapes or the sharded weight layout must be
    in one of them; anything that does not (seed, checkpoint path,
    warmup flag) must not — else identical snapshots would refuse to
    restore over an irrelevant knob."""
    payload = json.dumps(
        {"model": model, "engine": engine},
        sort_keys=True, ensure_ascii=False,
    )
    return digest_bytes(payload.encode("utf-8"))


def write_manifest(path: str, man: dict[str, Any]) -> None:
    """Atomic write (tmp + rename): a torn manifest must never exist —
    its presence is the snapshot-complete marker."""
    target = os.path.join(path, MANIFEST_NAME)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1, sort_keys=True)
    os.replace(tmp, target)


def read_manifest(path: str) -> dict[str, Any]:
    target = os.path.join(path, MANIFEST_NAME)
    try:
        with open(target) as f:
            man = json.load(f)
    except OSError as e:
        raise SnapshotError(
            f"not a snapshot directory (no readable {MANIFEST_NAME}): "
            f"{path} ({e})"
        ) from e
    except json.JSONDecodeError as e:
        raise SnapshotError(f"corrupt manifest {target}: {e}") from e
    if not isinstance(man, dict) or "fingerprint" not in man:
        raise SnapshotError(f"malformed manifest {target}")
    if man.get("format") != FORMAT_VERSION:
        raise SnapshotError(
            f"snapshot format {man.get('format')!r} != supported "
            f"{FORMAT_VERSION} ({target})"
        )
    return man


def verify_snapshot(path: str, quick: bool = False) -> dict[str, Any]:
    """Integrity report for ``opsagent snapshot verify``: recompute the
    config fingerprint and every leaf file's size (+ content digest
    unless ``quick``). Pure-host check — no jax, no device."""
    man = read_manifest(path)
    errors: list[str] = []
    fp = fingerprint(man.get("model", {}), man.get("engine", {}))
    fingerprint_ok = fp == man["fingerprint"]
    if not fingerprint_ok:
        errors.append(
            f"fingerprint mismatch: manifest says {man['fingerprint']}, "
            f"configs hash to {fp} (manifest edited or corrupt)"
        )
    leaves = man.get("leaves", [])
    leaves_ok = 0
    for rec in leaves:
        fpath = os.path.join(path, rec["file"])
        if not os.path.exists(fpath):
            errors.append(f"missing leaf file {rec['file']}")
            continue
        size = os.path.getsize(fpath)
        if size != rec["nbytes"]:
            errors.append(
                f"{rec['file']}: {size} bytes on disk, manifest says "
                f"{rec['nbytes']}"
            )
            continue
        if not quick and digest_file(fpath) != rec["digest"]:
            errors.append(f"{rec['file']}: content digest mismatch")
            continue
        leaves_ok += 1
    cache = man.get("compile_cache", {})
    cache_entries_found = 0
    cache_dir = os.path.join(path, COMPILE_CACHE_DIR)
    if os.path.isdir(cache_dir):
        for _root, _dirs, files in os.walk(cache_dir):
            cache_entries_found += len(files)
    if cache.get("entries", 0) and cache_entries_found == 0:
        errors.append(
            f"manifest records {cache['entries']} compile-cache entries "
            f"but {COMPILE_CACHE_DIR}/ is missing or empty"
        )
    return {
        "ok": not errors,
        "path": path,
        "fingerprint": man["fingerprint"],
        "fingerprint_ok": fingerprint_ok,
        "model": man.get("model", {}).get("name", ""),
        "leaves": len(leaves),
        "leaves_ok": leaves_ok,
        "compile_cache_entries": cache_entries_found,
        "errors": errors,
    }
