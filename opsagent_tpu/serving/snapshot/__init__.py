"""Engine snapshot/restore: cold start as a subsystem (ROADMAP item 4).

A snapshot packages a fully-warmed engine — weights in device layout,
the persistent XLA compile cache, and the paged-KV allocation plan —
into a directory artifact that a new replica restores from in a
fraction of fresh-init time (``Engine.from_snapshot`` / ``serve-engine
--restore-snapshot``), which is what makes the fleet autoscaler's
standby launches (serving/fleet/autoscale.py) fast enough to absorb a
traffic spike instead of shedding it.

``manifest`` is jax-free on purpose: ``opsagent snapshot verify`` runs
on any CI box. ``writer``/``restore`` import jax and are pulled in
lazily by the Engine methods.
"""

from .manifest import (
    FORMAT_VERSION,
    MANIFEST_NAME,
    SnapshotError,
    fingerprint,
    read_manifest,
    verify_snapshot,
)

__all__ = [
    "FORMAT_VERSION",
    "MANIFEST_NAME",
    "SnapshotError",
    "fingerprint",
    "read_manifest",
    "verify_snapshot",
]
