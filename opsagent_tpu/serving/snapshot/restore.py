"""Snapshot restore: mmap'd weights -> device, pre-seeded compile cache.

The restore path is the cold-start fix (ROADMAP item 4): instead of the
checkpoint-parse -> host-layout -> quantize -> device_put pipeline in
``models/loader.py``, each weight leaf file is ``np.memmap``'d read-only
and handed straight to ``shard_params`` (one ``jax.device_put`` per leaf
with the recorded sharding — the OS pages bytes in as the transfer
consumes them). The spec tree is NOT serialized: it is re-derived from
the manifest's configs via ``llama.param_specs`` (+ ``quantize_specs``),
and its deterministic flatten order is the leaf-file contract — checked
leaf-by-leaf against the manifest's recorded key paths before anything
touches a device.

Mismatched snapshots are refused up front with the offending field
spelled out (fingerprint, device count, leaf order), because the
failure modes past this point are shape errors deep in ``shard_params``
or silent recompiles that void the zero-post-warmup-compiles invariant.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ... import obs
from ...models.config import (
    MLAConfig,
    MoEConfig,
    ModelConfig,
    RopeScalingConfig,
)
from ...utils.logger import get_logger
from ..engine import Engine, EngineConfig, enable_compilation_cache
from .manifest import (
    COMPILE_CACHE_DIR,
    SnapshotError,
    fingerprint,
    read_manifest,
)
from .writer import spec_leaf_paths

log = get_logger("snapshot")


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype from a manifest dtype name. np.dtype("bfloat16")
    raises TypeError — the ml_dtypes-backed names resolve through jnp."""
    try:
        return np.dtype(name)
    except TypeError:
        return np.dtype(getattr(jnp, name))


def model_config_from_manifest(man: dict[str, Any]) -> ModelConfig:
    m = dict(man["model"])
    moe = m.pop("moe", None)
    mla = m.pop("mla", None)
    rs = m.pop("rope_scaling", None)
    return ModelConfig(
        **m,
        moe=MoEConfig(**moe) if moe else None,
        mla=MLAConfig(**mla) if mla else None,
        rope_scaling=RopeScalingConfig(**rs) if rs else None,
    )


def engine_config_from_manifest(man: dict[str, Any]) -> EngineConfig:
    e = dict(man["engine"])
    e["dtype"] = _np_dtype(e["dtype"]).type
    e["prefill_buckets"] = tuple(e["prefill_buckets"])
    e["mixed_buckets"] = tuple(e["mixed_buckets"])
    return EngineConfig(**e, warmup=False)


def preseed_compile_cache(path: str, active_dir: str | None) -> int:
    """Copy the snapshot's compile-cache entries into the active cache
    directory (never overwriting — entries already present belong to
    this host). Returns entries copied. The snapshot stays read-only."""
    if not active_dir:
        return 0
    src = os.path.join(path, COMPILE_CACHE_DIR)
    if not os.path.isdir(src):
        return 0
    copied = 0
    for root, _dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        dst_root = os.path.join(active_dir, rel) if rel != "." else active_dir
        os.makedirs(dst_root, exist_ok=True)
        for f in files:
            dst = os.path.join(dst_root, f)
            if os.path.exists(dst):
                continue
            shutil.copy2(os.path.join(root, f), dst)
            copied += 1
    return copied


def restore_params(
    man: dict[str, Any], path: str, model_cfg: ModelConfig,
    cfg: EngineConfig,
) -> Any:
    """Host params pytree of read-only memmaps, assembled by unflattening
    the leaf files through the re-derived spec tree."""
    from jax.sharding import PartitionSpec

    from ...models import llama

    specs = llama.param_specs(model_cfg)
    if cfg.quantize:
        from ...models.quant import quantize_specs

        specs = quantize_specs(specs, mode=cfg.quantize)
    _, treedef = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    recs = man["leaves"]
    if treedef.num_leaves != len(recs):
        raise SnapshotError(
            f"snapshot has {len(recs)} weight leaves but this build's "
            f"param spec tree has {treedef.num_leaves} — param_specs "
            "changed since the snapshot was written"
        )
    leaves = []
    for rec in recs:
        fpath = os.path.join(path, rec["file"])
        if not os.path.exists(fpath):
            raise SnapshotError(f"missing weight leaf file {rec['file']}")
        size = os.path.getsize(fpath)
        if size != rec["nbytes"]:
            raise SnapshotError(
                f"{rec['file']}: {size} bytes on disk, manifest says "
                f"{rec['nbytes']} (truncated or corrupt snapshot)"
            )
        leaves.append(np.memmap(
            fpath, dtype=_np_dtype(rec["dtype"]), mode="r",
            shape=tuple(rec["shape"]),
        ))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def restore_engine(
    path: str,
    warmup: bool | str | None = None,
    tokenizer: Any = None,
) -> Engine:
    """``Engine.from_snapshot`` body. ``warmup``: None/False = no warmup
    sweep (tests), True = "full", or a WARMUP_LEVELS name. With the
    snapshot's compile cache pre-seeded the sweep is a cache-hit replay,
    which is exactly what makes restore request-ready fast."""
    t0 = time.perf_counter()
    man = read_manifest(path)
    fp = fingerprint(man["model"], man["engine"])
    if fp != man["fingerprint"]:
        obs.SNAPSHOT_OPS.inc(op="refused")
        raise SnapshotError(
            f"refusing restore from {path}: config fingerprint {fp} != "
            f"manifest fingerprint {man['fingerprint']} (the manifest's "
            "model/engine configs were edited after the snapshot was "
            "written, or the file is corrupt)"
        )
    n_dev = len(jax.devices())
    want_dev = int(man["jax"]["n_devices"])
    if want_dev != n_dev:
        obs.SNAPSHOT_OPS.inc(op="refused")
        raise SnapshotError(
            f"refusing restore from {path}: snapshot was written on "
            f"{want_dev} devices, this host has {n_dev} — shardings "
            "would not match"
        )
    backend = jax.default_backend()
    if man["jax"]["backend"] != backend:
        log.warning(
            "snapshot %s was written on backend %s, restoring on %s: "
            "weights restore fine but the packaged compile cache will "
            "not hit", path, man["jax"]["backend"], backend,
        )

    # Pre-seed BEFORE the engine exists: warmup compiles (and therefore
    # cache lookups) happen inside Engine.__init__ when warmup is on.
    active_cache = enable_compilation_cache()
    preseeded = preseed_compile_cache(path, active_cache)

    model_cfg = model_config_from_manifest(man)
    cfg = engine_config_from_manifest(man)
    expect = [rec["path"] for rec in man["leaves"]]
    got = spec_leaf_paths(model_cfg, cfg.quantize)
    if got != expect:
        obs.SNAPSHOT_OPS.inc(op="refused")
        drift = next(
            (i for i, (a, b) in enumerate(zip(expect, got)) if a != b),
            min(len(expect), len(got)),
        )
        raise SnapshotError(
            f"refusing restore from {path}: weight leaf order drifted "
            f"at index {drift} (snapshot: "
            f"{expect[drift] if drift < len(expect) else '<end>'}, this "
            f"build: {got[drift] if drift < len(got) else '<end>'})"
        )

    params = restore_params(man, path, model_cfg, cfg)
    eng = Engine(
        cfg, model_cfg=model_cfg, params=params, tokenizer=tokenizer,
        params_quantized=bool(cfg.quantize),
    )
    eng.init_stats["restore_source"] = os.path.abspath(path)
    eng.init_stats["snapshot_fingerprint"] = man["fingerprint"]
    eng.init_stats["compile_cache_preseeded"] = preseeded
    if warmup:
        eng.warmup("full" if warmup is True else str(warmup))

    dt = time.perf_counter() - t0
    obs.SNAPSHOT_OPS.inc(op="restore")
    obs.SNAPSHOT_RESTORE_SECONDS.observe(dt)
    obs.flight.record(
        "snapshot_restore", path=path, seconds=round(dt, 3),
        leaves=len(man["leaves"]), compile_cache_preseeded=preseeded,
        fingerprint=man["fingerprint"],
    )
    log.info(
        "engine restored from %s in %.1f s (%d leaves mmap'd, %d "
        "compile-cache entries pre-seeded) [fp=%s]",
        path, dt, len(man["leaves"]), preseeded, man["fingerprint"],
    )
    return eng
