"""Snapshot writer: capture a fully-warmed engine as a restart artifact.

What gets captured (ISSUE 10 / ROADMAP item 4, HydraServe-style):

- **Weights in device layout**: every params-tree leaf pulled once and
  written as raw bytes in *spec-tree order* — the deterministic flatten
  of ``llama.param_specs(model_cfg)`` (quantized when the engine is).
  Restore rebuilds the same spec tree, memory-maps each file, and
  ``tree_unflatten`` reassembles the exact pytree ``shard_params``
  expects; no checkpoint parse, no host-side dtype/layout round trip
  through ``models/loader.py``.
- **The persistent XLA compile cache**: the engine's cache directory is
  copied wholesale, so a restoring engine's warmup is a cache-hit sweep
  instead of an XLA invocation per program.
- **The paged-KV allocation plan**: page geometry + cache leaf shapes —
  enough for an operator (or ``snapshot verify``) to see what the
  restore will allocate; the cache itself is rebuilt empty (KV content
  is per-request state, not artifact state).

The manifest is written last: its presence marks a complete snapshot,
so a crashed writer leaves a recognizably-partial directory instead of
a restorable-looking lie.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import time
from typing import Any

import jax
import numpy as np

from ... import obs
from ...models import llama
from ...utils.logger import get_logger
from .manifest import (
    COMPILE_CACHE_DIR,
    FORMAT_VERSION,
    MANIFEST_NAME,
    WEIGHTS_DIR,
    digest_bytes,
    fingerprint,
    write_manifest,
)

log = get_logger("snapshot")

# EngineConfig fields that determine compiled-program shapes or the
# sharded weight layout — the fingerprint's engine half. Checkpoint
# path, seed, warmup flag, host pool capacity etc. deliberately excluded:
# they change neither programs nor layout, and a snapshot must restore
# regardless of where its weights originally came from.
_ENGINE_FINGERPRINT_FIELDS = (
    "model", "tokenizer", "tp", "dp", "sp", "ep",
    "speculative_k", "speculative_ngram", "prefill_batch",
    "page_size", "num_pages", "max_pages_per_seq", "max_batch_size",
    "decode_block", "pipeline_depth", "prefill_buckets",
    "mixed_batching", "max_step_tokens", "mixed_buckets", "async_depth",
    "prefix_cache", "offload", "offload_copy_pages",
    "quantize", "kv_quantize",
)


def model_config_dict(model_cfg: Any) -> dict[str, Any]:
    """ModelConfig -> JSON-safe dict (nested MoE/MLA/rope-scaling
    dataclasses included). The engine snapshots its POST-pin model_cfg
    (MoE grouped_dispatch_min_tokens=0), so restoring it through
    Engine.__init__'s pin is idempotent and the fingerprint is stable."""
    return dataclasses.asdict(model_cfg)


def engine_config_dict(cfg: Any) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for name in _ENGINE_FINGERPRINT_FIELDS:
        v = getattr(cfg, name)
        out[name] = list(v) if isinstance(v, tuple) else v
    out["dtype"] = np.dtype(cfg.dtype).name
    return out


def _spec_tree(engine: Any) -> Any:
    specs = llama.param_specs(engine.model_cfg)
    if engine.cfg.quantize:
        from ...models.quant import quantize_specs

        specs = quantize_specs(specs, mode=engine.cfg.quantize)
    return specs


def spec_leaf_paths(model_cfg: Any, quantize: str) -> list[str]:
    """Keystr per spec-tree leaf, in flatten order — the leaf-file
    naming/ordering contract shared by writer and restore."""
    from jax.sharding import PartitionSpec

    specs = llama.param_specs(model_cfg)
    if quantize:
        from ...models.quant import quantize_specs

        specs = quantize_specs(specs, mode=quantize)
    paths, _ = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    return [jax.tree_util.keystr(kp) for kp, _ in paths]


def write_snapshot(engine: Any, path: str) -> dict[str, Any]:
    """Write ``engine``'s restart snapshot under ``path`` (created if
    needed). Returns the manifest dict. The engine keeps serving — only
    immutable state (params, compile cache, config) is read."""
    from jax.sharding import PartitionSpec

    t0 = time.perf_counter()
    os.makedirs(path, exist_ok=True)
    weights_dir = os.path.join(path, WEIGHTS_DIR)
    os.makedirs(weights_dir, exist_ok=True)

    # Leaf order contract: the spec tree's flatten (PartitionSpec leaves)
    # and the params tree's flatten walk the same structure, so index i
    # of one is index i of the other. Restore re-derives the spec tree
    # from configs alone and unflattens the leaf files through it.
    spec_leaves, _ = jax.tree_util.tree_flatten(
        _spec_tree(engine), is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    param_paths = jax.tree_util.tree_flatten_with_path(engine.params)[0]
    if len(param_paths) != len(spec_leaves):
        raise RuntimeError(
            f"params tree has {len(param_paths)} leaves but the spec "
            f"tree has {len(spec_leaves)} — param_specs drifted from "
            "the params structure; snapshot would not restore"
        )

    leaves = []
    weights_bytes = 0
    for i, (kp, leaf) in enumerate(param_paths):
        arr = np.asarray(jax.device_get(leaf))
        fname = os.path.join(WEIGHTS_DIR, f"leaf-{i:05d}.bin")
        data = arr.tobytes()
        with open(os.path.join(path, fname), "wb") as f:
            f.write(data)
        weights_bytes += len(data)
        leaves.append({
            "path": jax.tree_util.keystr(kp),
            "file": fname,
            "dtype": arr.dtype.name,
            "shape": list(arr.shape),
            "nbytes": len(data),
            "digest": digest_bytes(data),
        })

    # Persistent XLA compile cache -> build artifact. Entries land there
    # at COMPILE time, so `snapshot create` warms the engine under
    # OPSAGENT_COMPILE_CACHE_MIN_S=0 before calling this.
    cache_entries = 0
    cache_bytes = 0
    src_cache = getattr(engine, "compile_cache_dir", None)
    dst_cache = os.path.join(path, COMPILE_CACHE_DIR)
    os.makedirs(dst_cache, exist_ok=True)
    if src_cache and os.path.isdir(src_cache):
        shutil.copytree(src_cache, dst_cache, dirs_exist_ok=True)
        for root, _dirs, files in os.walk(dst_cache):
            for f in files:
                cache_entries += 1
                cache_bytes += os.path.getsize(os.path.join(root, f))
    else:
        log.warning(
            "engine has no active compile cache dir: snapshot carries "
            "weights only (restore will recompile; set "
            "OPSAGENT_COMPILE_CACHE_DIR)"
        )

    cfg = engine.cfg
    cache_leaves = jax.tree_util.tree_flatten_with_path(engine.cache)[0]
    kv_plan = {
        "num_pages": cfg.num_pages,
        "page_size": cfg.page_size,
        "max_pages_per_seq": cfg.max_pages_per_seq,
        "kv_quantize": cfg.kv_quantize,
        "leaves": [
            {
                "path": jax.tree_util.keystr(kp),
                "dtype": np.dtype(leaf.dtype).name,
                "shape": list(leaf.shape),
            }
            for kp, leaf in cache_leaves
        ],
    }

    model = model_config_dict(engine.model_cfg)
    eng_dict = engine_config_dict(cfg)
    man = {
        "format": FORMAT_VERSION,
        "created_unix": time.time(),
        "fingerprint": fingerprint(model, eng_dict),
        "model": model,
        "engine": eng_dict,
        "leaves": leaves,
        "kv_plan": kv_plan,
        "compile_cache": {"entries": cache_entries, "bytes": cache_bytes},
        "jax": {
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "n_devices": len(jax.devices()),
            "mesh": {k: int(v) for k, v in dict(engine.mesh.shape).items()},
        },
    }
    write_manifest(path, man)

    dt = time.perf_counter() - t0
    obs.SNAPSHOT_OPS.inc(op="write")
    obs.SNAPSHOT_WRITE_SECONDS.observe(dt)
    obs.SNAPSHOT_BYTES.set(float(weights_bytes), part="weights")
    obs.SNAPSHOT_BYTES.set(float(cache_bytes), part="compile_cache")
    obs.flight.record(
        "snapshot_write", path=path, seconds=round(dt, 3),
        leaves=len(leaves), weights_bytes=weights_bytes,
        compile_cache_entries=cache_entries,
        fingerprint=man["fingerprint"],
    )
    log.info(
        "snapshot written to %s: %d weight leaves (%.1f MiB), %d "
        "compile-cache entries (%.1f MiB) in %.1f s [fp=%s]",
        path, len(leaves), weights_bytes / 2**20, cache_entries,
        cache_bytes / 2**20, dt, man["fingerprint"],
    )
    return man
