"""Continuous-batching scheduler.

Maps concurrent agent sessions onto the engine's fixed decode batch
(BASELINE.json config 5: 32 concurrent execute sessions): an admission queue
feeds prefill as pages free up; all running sequences advance together in
decode steps; finished sequences release pages immediately, letting queued
requests enter mid-flight. Runs in a dedicated thread — JAX dispatch is
blocking — with asyncio-friendly completion events.

With ``EngineConfig.mixed_batching`` (default on) a tick with admitting
prompts runs ONE mixed dispatch — every decode lane plus up to
``max_step_tokens`` of chunked-prefill tokens in the same batch
(``Scheduler._mixed_tick`` -> ``Engine.step_mixed``) — instead of the
serialized prefill-chunk dispatch followed by a decode-block dispatch,
which streams the model weights twice per tick. The split path remains
the fallback for host-stepped rows and the flag-off configuration.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from .. import obs
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats
from . import faults
from .engine import Engine
from .kvcache import InvalidRequest, OutOfPages, PromptTooLong
from .sampler import SamplingParams

log = get_logger("scheduler")


class RequestError(RuntimeError):
    """A failed request with an HTTP-ish status classification (400 = the
    request can never succeed, 500 = engine-side failure)."""

    def __init__(self, message: str, status: int = 500):
        super().__init__(message)
        self.status = status


@dataclass
class Request:
    prompt_ids: list[int]
    sampling: SamplingParams
    mask_fn: Callable[[list[int]], np.ndarray] | None = None
    on_token: Callable[[int], None] | None = None
    # filled by the scheduler:
    seq_id: int | None = None
    tokens: list[int] = field(default_factory=list)
    finish_reason: str = ""
    error: str = ""
    error_status: int = 500  # meaningful only when error is set
    done = None  # threading.Event, set in __post_init__
    enqueued_s: float = field(default_factory=time.perf_counter)
    # Tokens generated before an engine restart (slice-restart tolerance):
    # re-admission folds them into the prompt, and the final result is
    # generated_prefix + the post-restart generation.
    generated_prefix: list[int] = field(default_factory=list)
    # Per-token logprob entries (engine Sequence.logprob_data), populated
    # at reap when sampling.logprobs was requested; accumulates across
    # engine restarts like generated_prefix.
    logprob_data: list[dict] = field(default_factory=list)
    # Set when the request was pressure-parked to the host KV tier: its
    # re-admission is EXPECTED to restore from the host pool, so a
    # restore that falls back to re-prefill is a flight-ring anomaly.
    parked: bool = False
    # Observability: the request's span handle (obs.trace.Span). The
    # scheduler thread has no ambient contextvar from the submitting
    # thread, so the span rides the Request explicitly; queue-wait is
    # recorded here and the handle is passed into engine.begin_request
    # for the prefill/decode phase children.
    trace: Any = None

    def __post_init__(self) -> None:
        self.done = threading.Event()


# Admission precedence by SLO class: lower admits first. Unclassed
# requests rank as interactive (the default class everywhere else).
_CLASS_ADMIT_RANK = {"interactive": 0, "batch": 1, "background": 2}


def _admit_rank(req: Request) -> int:
    return _CLASS_ADMIT_RANK.get(
        obs.trace.class_of(req.trace, "interactive"), 0
    )


class Scheduler:
    def __init__(
        self,
        engine: Engine,
        admission_timeout_s: float = 120.0,
        engine_factory: Callable[[], Engine] | None = None,
        max_restarts: int = 3,
    ):
        """``engine_factory`` enables slice-restart tolerance (SURVEY §5):
        when the engine fails persistently (a restarted TPU slice, a wedged
        device runtime), the scheduler rebuilds the engine via the factory
        and RE-ADMITS every in-flight request from its retained prompt +
        tokens generated so far, instead of failing the batch. At most
        ``max_restarts`` rebuilds per scheduler lifetime; without a
        factory, persistent failure fails the in-flight requests (the
        reference's equivalent is k8s probe-driven pod restart, reference
        deploy/kubernetes/deployment-prod.yaml probes — here recovery is
        in-process and keeps queued work)."""
        self.engine = engine
        self.admission_timeout_s = admission_timeout_s
        self._engine_factory = engine_factory
        self._max_restarts = max_restarts
        self._restarts = 0
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._waiting: list[Request] = []
        self._prefilling: dict[int, Request] = {}  # begun, chunks pending
        self._running: dict[int, Request] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # Graceful-drain mode (serving/fleet): the loop exits WITHOUT
        # failing in-flight requests — drain_for_migration() then parks
        # and returns them for re-admission on another replica.
        self._draining = False

    # -- public ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def submit(self, req: Request) -> Request:
        self._queue.put(req)
        self._wake.set()
        return req

    def drain_for_migration(self) -> list[Request]:
        """Graceful replica drain (serving/fleet): stop the loop WITHOUT
        failing anything, park every running session's KV to the host
        tier, and return every request that still needs tokens so the
        fleet router can re-admit them on another replica. Token loss is
        zero by construction: running sequences salvage their generated
        tokens through ``_requeue_salvaged`` (prompt += salvage, budget -=
        salvage, FSM/penalty state carried — the slice-restart flow), so
        the re-admission elsewhere continues exactly where this replica
        stopped; streaming clients keep their callbacks and are never
        re-sent a token. Requests that finished while the pipeline
        settled are reaped normally (their clients see a clean result).

        Engines without the offload tier still drain correctly — the
        salvage folds into the prompt and the target replica re-prefills
        it — they just cannot ship KV pages, so the detour costs a
        re-prefill instead of a page copy."""
        self._draining = True
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        out: list[Request] = []
        self._drain_queue()
        for sid, req in list(self._running.items()):
            parked = None
            if getattr(self.engine, "offload", None) is not None:
                try:
                    parked = self.engine.park_sequence(sid)
                except Exception:  # noqa: BLE001 - fall back to salvage
                    log.exception("drain parking of seq %d failed", sid)
            if parked is not None:
                self._running.pop(sid)
                if self._requeue_salvaged(
                    req, parked.tokens, parked.logprob_data, parked=True
                ):
                    out.append(req)
                continue
            seq = self.engine.sequences.get(sid)
            if seq is not None and seq.done:
                continue  # reaped below with full results
            # No offload tier (or parking raced): salvage host state and
            # re-admit whole; the pages are simply freed.
            partial: list[int] = []
            lp: list[dict] = []
            if seq is not None:
                lp = list(seq.logprob_data)
            try:
                partial = self.engine.finish(sid)
            except Exception:  # noqa: BLE001 - device state may be gone
                pass
            self._running.pop(sid, None)
            if self._requeue_salvaged(req, partial, lp):
                out.append(req)
        self._reap()
        for sid, req in list(self._prefilling.items()):
            try:
                self.engine.abort_request(sid)
            except Exception:  # noqa: BLE001
                pass
            req.seq_id = None
            req.enqueued_s = time.perf_counter()
            out.append(req)
        self._prefilling.clear()
        out.extend(self._waiting)
        self._waiting = []
        flush = getattr(self.engine, "offload_flush", None)
        if flush is not None:
            try:
                flush()  # land the parked pages in the host pool
            except Exception:  # noqa: BLE001 - best-effort
                pass
        return out

    def complete(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        mask_fn=None,
        on_token=None,
        timeout_s: float = 600.0,
    ) -> list[int]:
        """Blocking convenience: submit and wait for the generated tokens."""
        req = Request(prompt_ids, sampling, mask_fn=mask_fn, on_token=on_token)
        self.submit(req)
        if not req.done.wait(timeout_s):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RequestError(req.error, req.error_status)
        return req.tokens

    # -- loop --------------------------------------------------------------
    def _drain_queue(self) -> None:
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                return

    def _try_admit(self) -> None:
        """Move waiting requests into the prefilling state while page
        budget and batch slots allow. Only the cheap page allocation
        happens here (engine.begin_request); the device work is advanced
        one chunk per loop tick by ``_advance_prefill`` so long prompts
        cannot stall running decodes.

        Admission is class-fair, not FIFO: interactive requests admit
        ahead of batch (and batch ahead of background) within the same
        tick, so a fan-out's thousand batch children queued an instant
        before an interactive request cannot inflate its TTFT by the
        whole wave. The sort is stable — arrival order is preserved
        within a class."""
        if len(self._waiting) > 1:
            self._waiting.sort(key=_admit_rank)
        still: list[Request] = []
        now = time.perf_counter()
        for req in self._waiting:
            occupied = len(self._running) + len(self._prefilling)
            if occupied >= self.engine.cfg.max_batch_size:
                still.append(req)
                continue
            if now - req.enqueued_s > self.admission_timeout_s:
                req.error = "admission timed out (engine saturated)"
                obs.ENGINE_REQUESTS.inc(outcome="timeout")
                obs.CLASS_REQUESTS.inc(**{
                    "class": obs.trace.class_of(req.trace, "interactive"),
                    "outcome": "timeout",
                })
                obs.flight.anomaly(
                    "request_error", error=req.error,
                    request_id=obs.flight.request_id_of(req.trace),
                )
                req.done.set()
                continue
            def _begin(r: Request) -> int:
                faults.maybe_raise(
                    "sched.out_of_pages", OutOfPages,
                    "injected OutOfPages storm",
                )
                return self.engine.begin_request(
                    r.prompt_ids,
                    r.sampling,
                    mask_fn=r.mask_fn,
                    stream=r.on_token,
                    trace=r.trace,
                    expect_restore=r.parked,
                )

            try:
                try:
                    seq_id = _begin(req)
                except OutOfPages:
                    # Offload tier: instead of queueing the new prompt
                    # behind pages a cold session is pinning, park the
                    # coldest running session to host RAM (it restores
                    # instead of re-prefilling when it comes back) and
                    # retry the admission once.
                    if not self._park_coldest():
                        raise
                    seq_id = _begin(req)
            except OutOfPages:
                # Transient: pages will free as running sequences finish.
                still.append(req)
                continue
            except PromptTooLong as e:
                # Permanent: reject immediately with a clear error.
                req.error = str(e)
                req.error_status = 400
                req.done.set()
                continue
            except InvalidRequest as e:
                # Malformed request (e.g. empty prompt): the client's fault.
                # Plain ValueErrors from engine internals stay 500 below.
                req.error = f"admission failed: {e}"
                req.error_status = 400
                req.done.set()
                continue
            except Exception as e:  # noqa: BLE001 - surfaced on the request
                req.error = f"admission failed: {e}"
                req.done.set()
                continue
            req.seq_id = seq_id
            self._prefilling[seq_id] = req
            wait_s = now - req.enqueued_s
            get_perf_stats().record_metric(
                "scheduler.queue_wait", wait_s * 1e3, "ms"
            )
            obs.QUEUE_WAIT_SECONDS.observe(wait_s)
            obs.attribution.record_goodput(
                wait_s, "queued",
                slo_class=obs.trace.class_of(req.trace),
            )
            if req.trace is not None:
                req.trace.child("queue_wait", req.enqueued_s, now)
        self._waiting = still

    def _advance_prefill(self) -> None:
        """Run ONE prefill chunk for a BATCH of admitting requests: the
        oldest one plus up to prefill_batch-1 more whose next chunk
        compiles into the same bucket, in a single dispatch
        (engine.prefill_batch). One chunk-batch per tick means long
        prompts interleave with decode blocks instead of monopolizing the
        device, while concurrent admissions (BASELINE config 5) share
        dispatches instead of queueing one per tick."""
        if not self._prefilling:
            return
        first = next(iter(self._prefilling))
        batch = [first]
        try:
            bucket = self.engine.next_prefill_bucket(first)
            for sid in self._prefilling:
                if len(batch) >= self.engine.cfg.prefill_batch:
                    break
                if sid != first and (
                    self.engine.next_prefill_bucket(sid) == bucket
                ):
                    batch.append(sid)
            results = self.engine.prefill_batch(batch)
        except Exception as e:  # noqa: BLE001 - engine cleaned up already
            for sid in batch:
                self._fail_admission(sid, e)
            return
        for sid, res in results.items():
            if isinstance(res, Exception):
                # Row-local failure (raising stream callback / mask_fn):
                # only this request fails, matching the decode path's
                # one-bad-apple isolation.
                self._fail_admission(sid, res)
            elif res:
                self._running[sid] = self._prefilling.pop(sid)

    def _mixed_tick(self) -> bool:
        """The unified mixed prefill+decode tick (EngineConfig
        .mixed_batching): ONE engine dispatch advances every running
        decode lane by a token AND seats prefill chunks for the oldest
        admitting prompts, under a token budget — decode lanes are funded
        first (1 token each), the remaining ``max_step_tokens`` budget
        goes to admitting prompts in arrival order. Versus the split
        ``_advance_prefill(); step_block()`` tick this streams the model
        weights ONCE per tick instead of twice, and an admitting prompt
        advances every tick instead of waiting out a full decode block —
        TTFT is no longer quantized to decode-block boundaries.

        Returns True when a mixed dispatch ran (the caller skips the split
        tick); False routes the tick to the split path — no admitting
        prompts, some involved row needs host-side per-token work
        (constrained mask / logprobs / logit bias), or the budget left no
        room for a chunk.

        With ``EngineConfig.async_depth`` > 1 the tick goes through the
        one-step-lookahead pipeline instead (``_async_mixed_tick``):
        dispatches run ahead, results lag, and this method's synchronous
        body remains the depth-1 behavior."""
        eng = self.engine
        if not getattr(eng.cfg, "mixed_batching", False):
            return False
        if getattr(eng.cfg, "async_depth", 1) > 1:
            if self._async_mixed_tick():
                return True
            # The lookahead lane passed on the tick (pure decode, hosted
            # rows, nothing admitting): rows falling back to the block
            # pipeline still get the grammar fast-forward below — the
            # async planner only covers rows IT dispatches.
            if getattr(eng.cfg, "grammar_ffwd", False) and self._running:
                eng.ffwd_step(sorted(self._running))
            return False
        # Grammar fast-forward (depth-1 sync lane): splice forced-token
        # runs for constrained rows BEFORE the hosted-row bail below routes
        # the tick to the split path — constrained rows are always
        # mixed_hosted, so this is their only mixed-family entry point.
        # The engine pre-scans without touching the block pipeline: rows
        # with device-resident in-flight tokens are left alone (their host
        # token lists are stale), so the fast-forward engages at
        # settle/admission boundaries; the async lane (depth > 1) engages
        # at every plan point it dispatches.
        if getattr(eng.cfg, "grammar_ffwd", False) and self._running:
            eng.ffwd_step(sorted(self._running))
        if not self._prefilling:
            return False
        for sid in list(self._running) + list(self._prefilling):
            if eng.mixed_hosted(sid):
                return False
        decode_ids = sorted(
            sid for sid in self._running
            if sid in eng.sequences and not eng.sequences[sid].done
        )
        budget = eng.cfg.max_step_tokens - len(decode_ids)
        rows_left = eng.cfg.max_batch_size - len(decode_ids)
        cap = eng.cfg.mixed_buckets[-1]
        chunks: dict[int, int] = {}
        # dict order is admission order: oldest admitting prompts first.
        for sid in self._prefilling:
            if budget <= 0 or rows_left <= 0:
                break
            try:
                done, total = eng.prefill_progress(sid)
            except KeyError:
                continue  # raced with a failure path; reaped elsewhere
            c = min(total - done, budget, cap)
            if c <= 0:
                continue
            chunks[sid] = c
            budget -= c
            rows_left -= 1
        if not chunks:
            return False
        try:
            _, prefill_out = eng.step_mixed(decode_ids, chunks)
        except Exception as e:  # noqa: BLE001 - engine cleaned up already
            # The engine dropped every chunk admission before re-raising;
            # fail those requests, then let the loop's failure accounting
            # see the dispatch error (persistent engine failures must
            # still trigger recovery).
            for sid in chunks:
                self._fail_admission(sid, e)
            raise
        for sid, res in prefill_out.items():
            if isinstance(res, Exception):
                self._fail_admission(sid, res)
            elif res:
                self._running[sid] = self._prefilling.pop(sid)
        return True

    def _fold_async_prefill(self, prefill_out: dict) -> None:
        """Apply committed async admission outcomes: completed prompts
        move to running, row-local failures fail just their request.
        ``False`` entries (chunk landed, prompt unfinished) are no-ops."""
        for sid, res in prefill_out.items():
            if isinstance(res, Exception):
                self._fail_admission(sid, res)
            elif res:
                req = self._prefilling.pop(sid, None)
                if req is not None:
                    self._running[sid] = req

    def _async_mixed_tick(self) -> bool:
        """The one-step-lookahead mixed tick (EngineConfig.async_depth > 1,
        serving/async_runtime.py): plan and DISPATCH tick t+1 before tick
        t's tokens are pulled — the engine keeps decode-lane feedback
        device-resident, so the host work this loop does between
        dispatches (reaping, admission planning, and the engine-side
        detokenize/stop-scan/streaming at commit) overlaps device compute.
        Results returned by the engine lag the dispatch by up to depth-1
        ticks; admission completions are folded in whenever they surface.

        Returns True when the tick was consumed by the async lane
        (dispatch or pipeline settle); False routes to the sync paths —
        no admitting work and nothing in flight (pure decode belongs to
        the block pipeline), or an involved row needs a hosted lane."""
        eng = self.engine
        # Pick up results committed by internal pipeline settles
        # (parking, warmup, sync-lane entry points) since the last tick.
        _, p_out = eng.async_take_results()
        self._fold_async_prefill(p_out)
        # Grammar fast-forward keeps dense-table constrained rows in the
        # async lane even for PURE decode: the planner splices forced
        # runs at every dispatch point, which the block pipeline (host
        # token lists stale behind in-flight blocks) cannot do. Per-tick
        # host overhead loses to block batching only when forced states
        # are rare — a schema-constrained row is exactly where they are
        # not.
        ffwd_decode = (
            getattr(eng.cfg, "grammar_ffwd", False)
            and any(
                sid in eng.sequences and not eng.sequences[sid].done
                and eng.async_row_fsm(sid) is not None
                for sid in self._running
            )
        )
        if not self._prefilling and not eng.async_pending() \
                and not ffwd_decode:
            return False
        # Hosted rows (and mixed-schema constrained batches) route the
        # tick to the sync lanes — settle the pipeline first so the split
        # path sees current host state.
        fsm_seen = None
        for sid in list(self._running) + list(self._prefilling):
            hosted = eng.mixed_async_hosted(sid)
            mismatch = False
            if not hosted:
                f = eng.async_row_fsm(sid)
                if f is not None:
                    if fsm_seen is not None and f is not fsm_seen:
                        mismatch = True
                    fsm_seen = f
            if hosted or mismatch:
                obs.ASYNC_FALLBACKS.inc(
                    reason="hosted" if hosted else "fsm_mismatch"
                )
                if hosted and getattr(eng.cfg, "grammar_ffwd", False):
                    # A hosted row (host mask / no dense tables /
                    # logprobs / bias) also cannot fast-forward; the
                    # distinct reason label separates "can't ffwd" from
                    # "can't async" (counted once per sequence).
                    eng.note_ffwd_ineligible(sid)
                _, p_out = eng.async_drain()
                self._fold_async_prefill(p_out)
                return False
        decode_ids = sorted(
            sid for sid in self._running
            if sid in eng.sequences and not eng.sequences[sid].done
        )
        budget = eng.cfg.max_step_tokens - len(decode_ids)
        rows_left = eng.cfg.max_batch_size - len(decode_ids)
        cap = eng.cfg.mixed_buckets[-1]
        chunks: dict[int, int] = {}
        for sid in self._prefilling:
            if budget <= 0 or rows_left <= 0:
                break
            try:
                # Progress here is PLAN progress: chunks already in
                # flight count as done, so a prompt is never re-offered.
                done, total = eng.prefill_progress(sid)
            except KeyError:
                continue  # completion still in flight, or a failure path
            c = min(total - done, budget, cap)
            if c <= 0:
                continue
            chunks[sid] = c
            budget -= c
            rows_left -= 1
        if not chunks:
            if not ffwd_decode:
                if not eng.async_pending():
                    return False
                # Every admitting prompt is fully planned (or the budget
                # is spent) and only commits remain: settle the pipeline
                # so the completions land, then let the next tick route
                # pure decode to the block pipeline.
                _, p_out = eng.async_drain()
                self._fold_async_prefill(p_out)
                return True
            if not decode_ids:
                return False
        try:
            _, p_out = eng.step_mixed_async(decode_ids, chunks)
        except Exception as e:  # noqa: BLE001 - engine cleaned up already
            # The engine dropped THIS tick's chunk admissions before
            # re-raising (earlier in-flight ticks were salvaged); fail
            # those requests, then let the loop's failure accounting see
            # the dispatch error.
            for sid in chunks:
                self._fail_admission(sid, e)
            raise
        self._fold_async_prefill(p_out)
        return True

    def _park_coldest(self) -> bool:
        """Pressure-eviction policy (offload tier): pick the coldest
        running session — LRU by last produced token — park its KV to the
        host pool (engine.park_sequence), and re-queue its request with
        the generated tokens salvaged into the prompt, exactly like the
        slice-restart flow. The re-admission restores the pages from the
        host pool, so the detour costs two page copies, not a re-prefill.
        Returns True when a session was parked (the caller retries its
        admission against the freed pages)."""
        eng = self.engine
        if getattr(eng, "offload", None) is None or not self._running:
            return False
        best: tuple[float, int] | None = None
        for sid in self._running:
            seq = eng.sequences.get(sid)
            if seq is None or seq.done:
                continue
            last = seq.last_tok_s or seq.started_s
            if best is None or last < best[0]:
                best = (last, sid)
        if best is None:
            return False
        sid = best[1]
        try:
            parked = eng.park_sequence(sid)
        except Exception:  # noqa: BLE001 - parking is best-effort
            log.exception("pressure parking of seq %d failed", sid)
            return False
        if parked is None:
            return False  # finished while the pipeline settled: reap it
        req = self._running.pop(sid)
        if self._requeue_salvaged(req, parked.tokens, parked.logprob_data,
                                  parked=True):
            # Cold sessions go to the BACK of the queue: the new prompt
            # the parking made room for admits first (that is the point).
            self._waiting.append(req)
        return True

    def _requeue_salvaged(
        self,
        req: Request,
        partial: list[int],
        logprob_data: list[dict],
        parked: bool = False,
    ) -> bool:
        """Fold a salvaged generation into the request so its re-admission
        continues where it stopped: prompt += salvage, budget -= salvage,
        penalties keep counting the salvage as output, a constrained
        mask_fn keeps walking its FSM from where it was, and streaming
        clients notice nothing (delivered tokens are not re-sent). Shared
        by engine-restart recovery and pressure parking. Returns False
        when the budget is exhausted (the request was finished instead of
        re-queued)."""
        from dataclasses import replace as dc_replace

        req.logprob_data = req.logprob_data + logprob_data[: len(partial)]
        req.generated_prefix = req.generated_prefix + partial
        # sampling.max_tokens was already reduced by earlier salvages;
        # subtract only THIS one's.
        budget = req.sampling.max_tokens - len(partial)
        if budget <= 0:
            req.tokens = req.generated_prefix
            req.finish_reason = "length"
            req.done.set()
            return False
        req.prompt_ids = req.prompt_ids + partial
        req.sampling = dc_replace(
            req.sampling,
            max_tokens=budget,
            # Salvaged tokens fold into the prompt, but penalty counting
            # must keep treating them as generated output.
            penalty_history=tuple(req.generated_prefix),
        )
        if req.mask_fn is not None and partial:
            # Wrap with only THIS salvage: the inner fn already prepends
            # earlier salvages, so prepending the cumulative prefix would
            # feed the FSM earlier tokens twice.
            inner = req.mask_fn
            req.mask_fn = (
                lambda toks, _p=list(partial), _f=inner: _f(_p + toks)
            )
        req.seq_id = None
        req.parked = parked or req.parked
        # Time already spent generating must not count against the
        # ADMISSION timeout of the re-admission.
        req.enqueued_s = time.perf_counter()
        return True

    def _fail_admission(self, sid: int, e: Exception) -> None:
        req = self._prefilling.pop(sid, None)
        if req is None:
            return
        req.error = f"admission failed: {e}"
        if isinstance(e, (InvalidRequest, PromptTooLong)):
            req.error_status = 400
        obs.ENGINE_REQUESTS.inc(outcome="admission_failed")
        obs.CLASS_REQUESTS.inc(**{
            "class": obs.trace.class_of(req.trace, "interactive"),
            "outcome": "admission_failed",
        })
        obs.flight.anomaly(
            "request_error", seq_id=sid, error=str(e),
            request_id=obs.flight.request_id_of(req.trace),
        )
        req.done.set()

    def _reap(self) -> None:
        finished = [
            sid for sid, req in self._running.items()
            if self.engine.sequences[sid].done
        ]
        for sid in finished:
            req = self._running.pop(sid)
            seq = self.engine.sequences[sid]
            req.finish_reason = seq.finish_reason
            req.logprob_data = req.logprob_data + seq.logprob_data
            req.tokens = req.generated_prefix + self.engine.finish(sid)
            if req.finish_reason == "error":
                # The engine terminated this sequence on a raising stream
                # callback (client went away mid-stream). Only THIS request
                # fails; the rest of the batch keeps decoding.
                req.error = "stream callback failed"
            obs.ENGINE_REQUESTS.inc(
                outcome="error" if req.error else "completed"
            )
            obs.CLASS_REQUESTS.inc(**{
                "class": obs.trace.class_of(req.trace, "interactive"),
                "outcome": "error" if req.error else "completed",
            })
            if req.error:
                obs.flight.anomaly(
                    "request_error", seq_id=sid, error=req.error,
                    request_id=obs.flight.request_id_of(req.trace),
                )
            req.done.set()

    def _recover(self) -> None:
        """Slice-restart tolerance: rebuild the engine and re-admit every
        in-flight request from retained host state (SURVEY §5's "queue
        drain + re-prefill from retained prompts").

        For each running sequence, whatever tokens the dying engine's host
        state still exposes are salvaged into ``generated_prefix`` and the
        request re-enters the admission queue (``_requeue_salvaged``: the
        re-prefill rebuilds its full context, prefix cache making it cheap
        when pages survive). Streaming clients notice nothing:
        already-delivered tokens are not re-sent."""
        self._restarts += 1
        log.error(
            "engine restart %d/%d: rebuilding device state, re-admitting "
            "%d running + %d prefilling requests",
            self._restarts, self._max_restarts,
            len(self._running), len(self._prefilling),
        )
        obs.flight.anomaly(
            "engine_restart", restart=self._restarts,
            max_restarts=self._max_restarts,
            running=len(self._running), prefilling=len(self._prefilling),
        )
        salvaged: list[Request] = []
        for sid, req in list(self._running.items()):
            partial: list[int] = []
            seq_obj = self.engine.sequences.get(sid)
            try:
                partial = self.engine.finish(sid)
            except Exception:  # noqa: BLE001 - device state may be gone
                pass
            # Slice logprobs to the tokens actually salvaged: if finish()
            # raised, partial is empty and keeping the entries would
            # misalign every post-restart token's logprobs.
            if self._requeue_salvaged(
                req, partial,
                seq_obj.logprob_data if seq_obj is not None else [],
            ):
                salvaged.append(req)
        self._running.clear()
        for sid, req in list(self._prefilling.items()):
            # Not decoding yet: nothing generated, just re-admit whole.
            req.seq_id = None
            req.enqueued_s = time.perf_counter()
            salvaged.append(req)
        self._prefilling.clear()
        # Oldest first so re-admitted work keeps its queue position.
        self._waiting = salvaged + self._waiting
        # Release the dead engine's device buffers BEFORE building the
        # replacement: params + KV cache can be most of HBM, and a wedged
        # (not torn-down) runtime would otherwise hold both engines at
        # once and OOM the rebuild. The old engine stays referenced for
        # its host-side state (allocator, sequences) in case the rebuild
        # fails — admission is host-only, so queued work survives.
        for buf in (
            "params", "cache", "_carry", "_hist",
            "_async_carry", "_async_fsm_carry",
        ):
            try:
                setattr(self.engine, buf, None)
            except Exception:  # noqa: BLE001
                pass
        try:
            self.engine = self._engine_factory()
        except Exception:  # noqa: BLE001 - slice may still be restarting
            # Keep the old engine reference: admission is host-side, so
            # queued work is not insta-failed; the next persistent device
            # failure triggers another recovery attempt (until the
            # restart budget runs out).
            log.exception(
                "engine rebuild failed; keeping queued work for the next "
                "recovery attempt"
            )

    def _loop(self) -> None:
        log.info("scheduler loop started (batch=%d)", self.engine.cfg.max_batch_size)
        consecutive_failures = 0
        while not self._stop.is_set():
            try:
                self._drain_queue()
                self._try_admit()
                if self._running or self._prefilling:
                    # Only counted with work in flight: idle ticks spin
                    # at an arbitrary rate, which would make hit-count
                    # fault selectors wall-clock-dependent.
                    faults.maybe_raise(
                        "sched.step_fault", RuntimeError,
                        "injected scheduler step fault",
                    )
                # Mixed tick first: one dispatch covers decode AND a
                # prefill chunk (one weight stream). Falls back to the
                # split prefill-then-decode tick when it cannot run.
                mixed = self._mixed_tick()
                if not mixed:
                    self._advance_prefill()
                self._reap()
                if not self._running:
                    if self._prefilling:
                        continue  # keep advancing admission chunks
                    # Idle: the mixed-tick cadence breaks here — the wait
                    # must not be observed as host gap.
                    gap_break = getattr(self.engine, "mixed_gap_break", None)
                    if gap_break is not None:
                        gap_break()
                    # Land pending device->host page copies (the
                    # offload double buffer's drain side), then wait.
                    flush = getattr(self.engine, "offload_flush", None)
                    if flush is not None:
                        try:
                            flush()
                        except Exception:  # noqa: BLE001 - best-effort
                            pass
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                if not mixed:
                    self.engine.step_block(sorted(self._running))
                    self._reap()
                consecutive_failures = 0
            except Exception as e:  # noqa: BLE001 - the loop must survive
                # A raising stream callback surfaces here after the engine
                # already marked its sequence done/"error" — _reap fails
                # just that request. Only a persistently failing engine
                # (no per-seq attribution, no progress) fails the batch.
                log.exception("scheduler step failed")
                before = len(self._running)
                try:
                    self._reap()
                except Exception:  # noqa: BLE001
                    pass
                if len(self._running) < before:
                    # Attributed: the offending request(s) were reaped —
                    # that IS progress, not an engine failure.
                    consecutive_failures = 0
                    continue
                consecutive_failures += 1
                if consecutive_failures < 3:
                    continue
                if (
                    self._engine_factory is not None
                    and self._restarts < self._max_restarts
                ):
                    self._recover()
                    consecutive_failures = 0
                    continue
                log.error("engine failing persistently; failing in-flight requests")
                obs.flight.anomaly(
                    "request_error",
                    error=f"engine failing persistently: {e}",
                    failed_requests=len(self._running),
                )
                for sid, req in list(self._running.items()):
                    req.error = f"engine step failed: {e}"
                    # Earlier restarts' salvage was already streamed to the
                    # client; keep it in the result even if the dead
                    # engine's finish() raises.
                    req.tokens = list(req.generated_prefix)
                    try:
                        req.tokens = (
                            req.generated_prefix + self.engine.finish(sid)
                        )
                    except Exception:  # noqa: BLE001
                        pass
                    req.done.set()
                self._running.clear()
                consecutive_failures = 0
        if self._draining:
            # Graceful drain: leave every in-flight request intact for
            # drain_for_migration() to park and hand to the fleet router.
            log.info(
                "scheduler loop stopped for drain (%d running, %d "
                "prefilling, %d waiting retained)",
                len(self._running), len(self._prefilling),
                len(self._waiting),
            )
            return
        # drain on shutdown
        for req in self._waiting:
            req.error = "scheduler stopped"
            req.done.set()
        for sid, req in list(self._prefilling.items()):
            try:
                self.engine.abort_request(sid)
            except Exception:  # noqa: BLE001
                pass
            req.error = "scheduler stopped"
            req.done.set()
        self._prefilling.clear()
        for sid, req in list(self._running.items()):
            req.tokens = req.generated_prefix + self.engine.finish(sid)
            req.error = "scheduler stopped"
            req.done.set()
        self._running.clear()
        log.info("scheduler loop stopped")
