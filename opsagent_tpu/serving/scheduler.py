"""Continuous-batching scheduler.

Maps concurrent agent sessions onto the engine's fixed decode batch
(BASELINE.json config 5: 32 concurrent execute sessions): an admission queue
feeds prefill as pages free up; all running sequences advance together in
decode steps; finished sequences release pages immediately, letting queued
requests enter mid-flight. Runs in a dedicated thread — JAX dispatch is
blocking — with asyncio-friendly completion events.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats
from .engine import Engine
from .kvcache import InvalidRequest, OutOfPages, PromptTooLong
from .sampler import SamplingParams

log = get_logger("scheduler")


class RequestError(RuntimeError):
    """A failed request with an HTTP-ish status classification (400 = the
    request can never succeed, 500 = engine-side failure)."""

    def __init__(self, message: str, status: int = 500):
        super().__init__(message)
        self.status = status


@dataclass
class Request:
    prompt_ids: list[int]
    sampling: SamplingParams
    mask_fn: Callable[[list[int]], np.ndarray] | None = None
    on_token: Callable[[int], None] | None = None
    # filled by the scheduler:
    seq_id: int | None = None
    tokens: list[int] = field(default_factory=list)
    finish_reason: str = ""
    error: str = ""
    error_status: int = 500  # meaningful only when error is set
    done = None  # threading.Event, set in __post_init__
    enqueued_s: float = field(default_factory=time.perf_counter)

    def __post_init__(self) -> None:
        self.done = threading.Event()


class Scheduler:
    def __init__(self, engine: Engine, admission_timeout_s: float = 120.0):
        self.engine = engine
        self.admission_timeout_s = admission_timeout_s
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._waiting: list[Request] = []
        self._prefilling: dict[int, Request] = {}  # begun, chunks pending
        self._running: dict[int, Request] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()

    # -- public ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def submit(self, req: Request) -> Request:
        self._queue.put(req)
        self._wake.set()
        return req

    def complete(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        mask_fn=None,
        on_token=None,
        timeout_s: float = 600.0,
    ) -> list[int]:
        """Blocking convenience: submit and wait for the generated tokens."""
        req = Request(prompt_ids, sampling, mask_fn=mask_fn, on_token=on_token)
        self.submit(req)
        if not req.done.wait(timeout_s):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RequestError(req.error, req.error_status)
        return req.tokens

    # -- loop --------------------------------------------------------------
    def _drain_queue(self) -> None:
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                return

    def _try_admit(self) -> None:
        """Move waiting requests into the prefilling state while page
        budget and batch slots allow. Only the cheap page allocation
        happens here (engine.begin_request); the device work is advanced
        one chunk per loop tick by ``_advance_prefill`` so long prompts
        cannot stall running decodes."""
        still: list[Request] = []
        now = time.perf_counter()
        for req in self._waiting:
            occupied = len(self._running) + len(self._prefilling)
            if occupied >= self.engine.cfg.max_batch_size:
                still.append(req)
                continue
            if now - req.enqueued_s > self.admission_timeout_s:
                req.error = "admission timed out (engine saturated)"
                req.done.set()
                continue
            try:
                seq_id = self.engine.begin_request(
                    req.prompt_ids,
                    req.sampling,
                    mask_fn=req.mask_fn,
                    stream=req.on_token,
                )
            except OutOfPages:
                # Transient: pages will free as running sequences finish.
                still.append(req)
                continue
            except PromptTooLong as e:
                # Permanent: reject immediately with a clear error.
                req.error = str(e)
                req.error_status = 400
                req.done.set()
                continue
            except InvalidRequest as e:
                # Malformed request (e.g. empty prompt): the client's fault.
                # Plain ValueErrors from engine internals stay 500 below.
                req.error = f"admission failed: {e}"
                req.error_status = 400
                req.done.set()
                continue
            except Exception as e:  # noqa: BLE001 - surfaced on the request
                req.error = f"admission failed: {e}"
                req.done.set()
                continue
            req.seq_id = seq_id
            self._prefilling[seq_id] = req
            get_perf_stats().record_metric(
                "scheduler.queue_wait", (now - req.enqueued_s) * 1e3, "ms"
            )
        self._waiting = still

    def _advance_prefill(self) -> None:
        """Run ONE prefill chunk for the oldest admitting request. One
        chunk per tick means a 4096-token prompt interleaves ~bucket-sized
        slices of prefill with decode blocks instead of monopolizing the
        device for the whole admission."""
        if not self._prefilling:
            return
        sid = next(iter(self._prefilling))
        req = self._prefilling[sid]
        try:
            if self.engine.prefill_step(sid):
                self._running[sid] = self._prefilling.pop(sid)
        except Exception as e:  # noqa: BLE001 - engine cleaned up already
            self._prefilling.pop(sid, None)
            req.error = f"admission failed: {e}"
            if isinstance(e, (InvalidRequest, PromptTooLong)):
                req.error_status = 400
            req.done.set()

    def _reap(self) -> None:
        finished = [
            sid for sid, req in self._running.items()
            if self.engine.sequences[sid].done
        ]
        for sid in finished:
            req = self._running.pop(sid)
            req.finish_reason = self.engine.sequences[sid].finish_reason
            req.tokens = self.engine.finish(sid)
            if req.finish_reason == "error":
                # The engine terminated this sequence on a raising stream
                # callback (client went away mid-stream). Only THIS request
                # fails; the rest of the batch keeps decoding.
                req.error = "stream callback failed"
            req.done.set()

    def _loop(self) -> None:
        log.info("scheduler loop started (batch=%d)", self.engine.cfg.max_batch_size)
        consecutive_failures = 0
        while not self._stop.is_set():
            try:
                self._drain_queue()
                self._try_admit()
                self._advance_prefill()
                self._reap()
                if not self._running:
                    if self._prefilling:
                        continue  # keep advancing admission chunks
                    # idle: wait for work
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                self.engine.step_block(sorted(self._running))
                self._reap()
                consecutive_failures = 0
            except Exception as e:  # noqa: BLE001 - the loop must survive
                # A raising stream callback surfaces here after the engine
                # already marked its sequence done/"error" — _reap fails
                # just that request. Only a persistently failing engine
                # (no per-seq attribution, no progress) fails the batch.
                log.exception("scheduler step failed")
                before = len(self._running)
                try:
                    self._reap()
                except Exception:  # noqa: BLE001
                    pass
                if len(self._running) < before:
                    # Attributed: the offending request(s) were reaped —
                    # that IS progress, not an engine failure.
                    consecutive_failures = 0
                    continue
                consecutive_failures += 1
                if consecutive_failures < 3:
                    continue
                log.error("engine failing persistently; failing in-flight requests")
                for sid, req in list(self._running.items()):
                    req.error = f"engine step failed: {e}"
                    try:
                        req.tokens = self.engine.finish(sid)
                    except Exception:  # noqa: BLE001
                        pass
                    req.done.set()
                self._running.clear()
                consecutive_failures = 0
        # drain on shutdown
        for req in self._waiting:
            req.error = "scheduler stopped"
            req.done.set()
        for sid, req in list(self._prefilling.items()):
            try:
                self.engine.abort_request(sid)
            except Exception:  # noqa: BLE001
                pass
            req.error = "scheduler stopped"
            req.done.set()
        self._prefilling.clear()
        for sid, req in list(self._running.items()):
            req.tokens = self.engine.finish(sid)
            req.error = "scheduler stopped"
            req.done.set()
        self._running.clear()
        log.info("scheduler loop stopped")
