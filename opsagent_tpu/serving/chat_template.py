"""Chat-template application per model family.

Renders OpenAI-style message lists (+ optional tool schemas) into the token
stream each model family was trained on. With an ``HFTokenizer`` whose
tokenizer ships a chat template, that template wins; otherwise family-specific
string templates are used. The ``ByteTokenizer`` gets a simple marker-based
template that is trivially learnable by test models and unambiguous to parse.
"""

from __future__ import annotations

import json
from typing import Any

from .tokenizer import ByteTokenizer, Tokenizer


def _content_str(msg: dict[str, Any]) -> str:
    c = msg.get("content")
    if c is None:
        if msg.get("tool_calls"):
            return json.dumps({"tool_calls": msg["tool_calls"]}, ensure_ascii=False)
        return ""
    if isinstance(c, str):
        return c
    return json.dumps(c, ensure_ascii=False)


def render_tools_preamble(tools: list[dict[str, Any]] | None) -> str:
    """Inject tool schemas as a system-prompt suffix (model-family agnostic)."""
    if not tools:
        return ""
    lines = [
        "\n\nYou have access to the following functions. To call one, reply "
        "with a JSON object {\"tool_calls\": [{\"id\": \"call_0\", \"type\": "
        "\"function\", \"function\": {\"name\": ..., \"arguments\": "
        "\"<json-encoded args>\"}}]} and nothing else.",
    ]
    for t in tools:
        fn = t.get("function", t)
        lines.append(
            f"- {fn.get('name')}: {fn.get('description', '')} "
            f"parameters schema: {json.dumps(fn.get('parameters', {}), ensure_ascii=False)}"
        )
    return "\n".join(lines)


def render_llama3(messages: list[dict[str, Any]], tools=None) -> str:
    out = ["<|begin_of_text|>"]
    msgs = _merge_tools_into_system(messages, tools)
    for m in msgs:
        role = m.get("role", "user")
        out.append(
            f"<|start_header_id|>{role}<|end_header_id|>\n\n{_content_str(m)}<|eot_id|>"
        )
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


def render_qwen(messages: list[dict[str, Any]], tools=None) -> str:
    out = []
    msgs = _merge_tools_into_system(messages, tools)
    for m in msgs:
        role = m.get("role", "user")
        out.append(f"<|im_start|>{role}\n{_content_str(m)}<|im_end|>\n")
    out.append("<|im_start|>assistant\n")
    return "".join(out)


def _merge_tools_into_system(
    messages: list[dict[str, Any]], tools
) -> list[dict[str, Any]]:
    if not tools:
        return messages
    pre = render_tools_preamble(tools)
    msgs = [dict(m) for m in messages]
    for m in msgs:
        if m.get("role") == "system":
            m["content"] = _content_str(m) + pre
            return msgs
    return [{"role": "system", "content": pre.strip()}] + msgs


def byte_template_ids(
    tok: ByteTokenizer, messages: list[dict[str, Any]], tools=None
) -> list[int]:
    """Marker-token template for the byte tokenizer."""
    role_ids = {
        "system": tok.SYS,
        "user": tok.USER,
        "assistant": tok.ASSISTANT,
        "tool": tok.USER,
    }
    ids: list[int] = [tok.bos_id]
    for m in _merge_tools_into_system(messages, tools):
        ids.append(role_ids.get(m.get("role", "user"), tok.USER))
        ids.extend(tok.encode(_content_str(m)))
        ids.append(tok.END)
    ids.append(tok.ASSISTANT)
    return ids


def apply_chat_template(
    tokenizer: Tokenizer,
    messages: list[dict[str, Any]],
    model_family: str = "",
    tools: list[dict[str, Any]] | None = None,
) -> list[int]:
    """messages -> prompt token ids ready for prefill."""
    if isinstance(tokenizer, ByteTokenizer):
        return byte_template_ids(tokenizer, messages, tools)
    hf = getattr(tokenizer, "hf", None)
    if hf is not None and getattr(hf, "chat_template", None):
        return hf.apply_chat_template(
            _merge_tools_into_system(messages, tools),
            add_generation_prompt=True,
            tokenize=True,
        )
    family = model_family.lower()
    if "qwen" in family or "deepseek" in family:
        text = render_qwen(messages, tools)
    else:
        text = render_llama3(messages, tools)
    return tokenizer.encode(text)
