"""Elastic fleet autoscaling: shed pressure -> standby replicas.

The scaling loop closes over signals the fleet already emits rather
than inventing new ones: the router's shed decision (_check_overload —
every decode queue at the watermark) is the scale-UP trigger, sustained
idleness of a scaler-launched replica is the scale-DOWN trigger, and
scale-down itself is just the existing graceful drain
(FleetRouter.drain), so no session sees an error when capacity leaves.

What makes scale-up worth doing at all is the snapshot subsystem
(serving/snapshot/): a standby launched with ``serve-engine
--restore-snapshot`` mmaps weights and replays the compile cache, so it
reaches request-ready in a fraction of fresh-init time — inside the
window a traffic spike is still going on. Launched replicas join with
``role="standby"`` (the router's route() only considers ``decode``),
and the scaler promotes them once /healthz says request-ready, so a
half-initialized engine can never receive traffic.

``ReplicaLauncher`` is the placement boundary: ``SubprocessLauncher``
spawns serve-engine processes on this host (the shipped implementation
— one TPU host, multiple small-mesh replicas), ``LocalStackLauncher``
builds in-process stacks (tests). A k8s/GKE launcher is the same three
methods against an API server.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
import urllib.request
import json as _json
from typing import Any, Callable

from ... import obs
from ...utils.logger import get_logger

log = get_logger("autoscale")


class ReplicaLauncher:
    """Minimal placement interface the Autoscaler drives."""

    def launch(self, replica_id: str) -> None:
        """Start a replica that will (eventually) appear in the router's
        registry with ``role="standby"``. Must not block on init."""
        raise NotImplementedError

    def request_ready(self, replica_id: str) -> bool:
        """True once the replica can serve requests (weights on device,
        warmup done)."""
        raise NotImplementedError

    def stop(self, replica_id: str) -> None:
        """Tear the replica down (after the router has drained it)."""
        raise NotImplementedError


class LocalStackLauncher(ReplicaLauncher):
    """In-process launcher for tests: ``stack_factory()`` returns a
    ready ServingStack (e.g. built around ``Engine.from_snapshot``)."""

    def __init__(self, router: Any, stack_factory: Callable[[], Any]):
        self.router = router
        self.stack_factory = stack_factory
        self._stacks: dict[str, Any] = {}

    def launch(self, replica_id: str) -> None:
        stack = self.stack_factory()
        self._stacks[replica_id] = stack
        self.router.add_local(stack, replica_id, role="standby")

    def request_ready(self, replica_id: str) -> bool:
        return replica_id in self._stacks

    def stop(self, replica_id: str) -> None:
        stack = self._stacks.pop(replica_id, None)
        if stack is not None:
            stack.close()


class SubprocessLauncher(ReplicaLauncher):
    """Launch standby replicas as serve-engine subprocesses restoring
    from a snapshot, joining the fleet over HTTP on sequential ports."""

    def __init__(
        self,
        snapshot_path: str,
        router_url: str,
        host: str = "127.0.0.1",
        port_base: int = 8400,
    ):
        self.snapshot_path = snapshot_path
        self.router_url = router_url
        self.host = host
        self.port_base = port_base
        self._procs: dict[str, subprocess.Popen] = {}
        self._urls: dict[str, str] = {}
        self._next_port = port_base

    def launch(self, replica_id: str) -> None:
        port = self._next_port
        self._next_port += 1
        url = f"http://{self.host}:{port}"
        cmd = [
            sys.executable, "-m", "opsagent_tpu.cli.main", "serve-engine",
            "--restore-snapshot", self.snapshot_path,
            "--host", self.host, "--port", str(port),
            "--join-fleet", self.router_url,
            "--advertise", url,
            "--replica-id", replica_id,
            "--replica-role", "standby",
        ]
        self._procs[replica_id] = subprocess.Popen(cmd)
        self._urls[replica_id] = url
        log.info(
            "launched standby %s on %s (pid %d, snapshot %s)",
            replica_id, url, self._procs[replica_id].pid,
            self.snapshot_path,
        )

    def request_ready(self, replica_id: str) -> bool:
        url = self._urls.get(replica_id)
        proc = self._procs.get(replica_id)
        if url is None or proc is None or proc.poll() is not None:
            return False
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=2) as r:
                return _json.loads(r.read()).get("status") == "ok"
        except Exception:  # noqa: BLE001 - still booting
            return False

    def stop(self, replica_id: str) -> None:
        proc = self._procs.pop(replica_id, None)
        self._urls.pop(replica_id, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


class Autoscaler:
    """Shed-driven scale-up, idleness-driven scale-down.

    One launch is in flight at a time (a standby that hasn't been
    promoted yet absorbs the pressure signal — launching more while the
    first is still warming just thunders the herd), bounded by
    ``max_replicas`` scaler-launched replicas and a post-launch
    ``cooldown_s``. Tests drive ``tick()`` directly; production uses
    ``start()``'s daemon thread."""

    def __init__(
        self,
        router: Any,
        launcher: ReplicaLauncher,
        max_replicas: int = 4,
        cooldown_s: float = 30.0,
        queue_high: int | None = None,
        scale_down_after: int = 10,
        interval_s: float = 2.0,
    ):
        self.router = router
        self.launcher = launcher
        self.max_replicas = max_replicas
        self.cooldown_s = cooldown_s
        self.queue_high = queue_high
        self.scale_down_after = scale_down_after
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._shed = 0
        self._shed_by_class: dict[str, int] = {}
        self._seq = 0
        # -inf, not 0.0: monotonic() counts from boot, so on a freshly
        # booted host 0.0 would put the FIRST launch inside the cooldown
        # window and silently block scale-up for cooldown_s seconds.
        self._last_launch = float("-inf")
        self._pending: set[str] = set()    # launched, not yet promoted
        self._active: set[str] = set()     # promoted, scaler-owned
        self._idle_ticks: dict[str, int] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.launched_total = 0
        self.promoted_total = 0
        self.retired_total = 0

    # -- signals -----------------------------------------------------------
    def note_shed(self, slo_class: str = "") -> None:
        """Called by FleetRouter._check_overload on every shed 429, with
        the SLO class of the request that was turned away — so the
        scale-up decision can say WHOSE backlog triggered it."""
        with self._lock:
            self._shed += 1
            cls = slo_class or "interactive"
            self._shed_by_class[cls] = self._shed_by_class.get(cls, 0) + 1

    def _take_shed(self) -> tuple[int, dict[str, int]]:
        with self._lock:
            n, self._shed = self._shed, 0
            by_class, self._shed_by_class = self._shed_by_class, {}
            return n, by_class

    # -- the loop ----------------------------------------------------------
    def tick(self) -> dict[str, Any]:
        """One scaling decision. Returns what it did (for tests/logs)."""
        reg = self.router.registry
        reg.refresh_local()
        out: dict[str, Any] = {
            "promoted": [], "launched": None, "retired": [],
        }

        # 1) Promote request-ready standbys into the routable decode set.
        for info in list(reg.alive(role="standby")):
            rid = info.replica_id
            if rid in self._pending and not self.launcher.request_ready(rid):
                continue
            if not reg.set_role(rid, "decode"):
                continue
            if info.handle is not None:
                # Keep the handle's self-reported role consistent so a
                # registry re-register from info() doesn't demote it.
                info.handle.role = "decode"
            self._pending.discard(rid)
            self._active.add(rid)
            self._idle_ticks[rid] = 0
            self.promoted_total += 1
            obs.FLEET_SCALE_EVENTS.inc(direction="promote")
            obs.flight.record("replica_promote", replica=rid)
            log.info("standby %s promoted to decode", rid)
            out["promoted"].append(rid)

        # 2) Scale up on pressure.
        shed, shed_by_class = self._take_shed()
        decode = reg.alive(role="decode")
        depths = [c.queue_depth() for c in decode]
        pressure = shed > 0 or (
            self.queue_high is not None and depths
            and min(depths) >= self.queue_high
        )
        fleet_size = len(self._pending) + len(self._active)
        now = time.monotonic()
        if (
            pressure
            and not self._pending
            and fleet_size < self.max_replicas
            and now - self._last_launch >= self.cooldown_s
        ):
            self._seq += 1
            rid = f"scale-{self._seq}"
            try:
                self.launcher.launch(rid)
            except Exception:  # noqa: BLE001 - launch is best-effort
                log.exception("standby launch %s failed", rid)
            else:
                self._pending.add(rid)
                self._last_launch = now
                self.launched_total += 1
                obs.FLEET_SCALE_EVENTS.inc(direction="up")
                # Which class's turned-away demand pulled the trigger:
                # the dominant class in this window's shed tally (ties
                # break deterministically by name).
                trigger_class = (
                    max(
                        sorted(shed_by_class),
                        key=lambda c: shed_by_class[c],
                    )
                    if shed_by_class else ""
                )
                obs.flight.record(
                    "replica_launch", replica=rid, shed_events=shed,
                    min_queue_depth=min(depths) if depths else -1,
                    trigger_class=trigger_class,
                    shed_by_class=shed_by_class,
                )
                log.info(
                    "scale-up: launching %s (shed=%d, class=%s, "
                    "min queue=%s)",
                    rid, shed, trigger_class or "n/a",
                    min(depths) if depths else "n/a",
                )
                out["launched"] = rid

        # 3) Scale down scaler-owned replicas that sat idle long enough.
        #    Drain is graceful (sessions migrate), so this is safe even
        #    if a request slips in between the check and the drain.
        if not pressure:
            by_id = {c.replica_id: c for c in decode}
            for rid in list(self._active):
                info = by_id.get(rid)
                if info is None:
                    self._active.discard(rid)
                    self._idle_ticks.pop(rid, None)
                    continue
                if info.queue_depth() == 0 and not info.draining:
                    self._idle_ticks[rid] = self._idle_ticks.get(rid, 0) + 1
                else:
                    self._idle_ticks[rid] = 0
                if self._idle_ticks[rid] >= self.scale_down_after:
                    try:
                        self.router.drain(rid)
                    except Exception:  # noqa: BLE001
                        log.exception("scale-down drain of %s failed", rid)
                        continue
                    self.launcher.stop(rid)
                    self._active.discard(rid)
                    self._idle_ticks.pop(rid, None)
                    self.retired_total += 1
                    obs.FLEET_SCALE_EVENTS.inc(direction="down")
                    obs.flight.record("replica_retire", replica=rid)
                    log.info("scale-down: retired idle replica %s", rid)
                    out["retired"].append(rid)
        else:
            for rid in self._idle_ticks:
                self._idle_ticks[rid] = 0
        return out

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            shed_pending = self._shed
            shed_by_class = dict(self._shed_by_class)
        return {
            "max_replicas": self.max_replicas,
            "pending": sorted(self._pending),
            "active": sorted(self._active),
            "shed_pending": shed_pending,
            "shed_pending_by_class": shed_by_class,
            "launched_total": self.launched_total,
            "promoted_total": self.promoted_total,
            "retired_total": self.retired_total,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _run() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - keep the loop alive
                    log.exception("autoscaler tick failed")

        self._thread = threading.Thread(
            target=_run, name="autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for rid in list(self._pending) + list(self._active):
            try:
                self.launcher.stop(rid)
            except Exception:  # noqa: BLE001
                log.exception("launcher stop of %s failed", rid)
