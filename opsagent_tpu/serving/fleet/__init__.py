"""Fleet serving: replica registry, prefix-affinity router, and session
migration over KV-page transfer (ROADMAP item 3 — the architecture step
from "a fast engine" to "heavy traffic from millions of users").

Modules:

- ``registry``  — replica self-registration (capacity, mesh shape, role,
  health, prefix-trie digest), heartbeat + liveness reaping, drain state.
- ``router``    — the front-end: speaks the engine-server API, places
  sessions by prefix-affinity first / least-loaded-goodput second with
  sticky pinning, bounded queue spill-over, and graceful replica drain.
- ``transfer``  — replica-to-replica KV-page shipping using the host
  pool's token-chain keys as the wire format, so the receiving engine
  restores-instead-of-reprefills exactly like a local offload hit.
- ``client``    — the replica-side membership client (``serve-engine
  --join-fleet``): register, heartbeat, drain state for /healthz.
- ``pagestore`` — fleet-global KV: the heartbeat digests indexed into a
  chain->owners directory, plus the peer-to-peer page fault-in client
  that turns an admission miss into a wire restore instead of a
  re-prefill (tier order: HBM trie -> host pool -> peer fetch ->
  re-prefill).
"""

from .pagestore import (  # noqa: F401
    PageDirectory,
    PageStoreClient,
    http_client,
    local_client,
)
from .registry import ReplicaInfo, ReplicaRegistry  # noqa: F401
from .router import (  # noqa: F401
    FleetRouter,
    LocalReplica,
    build_router_app,
    run_router_server,
)
from .transfer import migrate_chain, pack_entries, unpack_entries  # noqa: F401
