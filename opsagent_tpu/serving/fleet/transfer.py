"""Replica-to-replica KV-page transfer: the data plane of session
migration.

The wire format IS the host pool's keying scheme (offload/pool.py): each
shipped page carries the full page-aligned token chain whose KV it holds
plus the page's numpy leaves. The receiving side inserts the entries into
its own HostPagePool under the identical chain keys, so the next admission
of that history restores-instead-of-reprefills through the EXACT local
offload-hit path (engine._restore_from_host -> offload/copy.py scatter) —
migration adds no new restore code, only a second way pages arrive in the
pool.

Pages serialize as JSON-safe dicts (dtype/shape/base64 payload per pytree
leaf, flattened in ``jax.tree_util`` order). The receiver rebuilds the
pytree against its OWN cache treedef — both replicas serve the same model,
so the structures match; a structure mismatch is detected and the entry
dropped (a dropped entry only costs a re-prefill, never correctness: the
pool verifies full token chains on match, so a half-shipped chain can
never alias another history's KV).
"""

from __future__ import annotations

import base64
import hashlib
import time
from typing import Any

import numpy as np

from ... import obs
from ...utils.logger import get_logger
from .. import faults

log = get_logger("fleet.transfer")


def _record_digest(tokens: np.ndarray, blobs: list[bytes]) -> str:
    """Integrity digest over a record's chain tokens + payload bytes.
    Computed sender-side in pack_entries, verified receiver-side in
    unpack_entries — a bit flip anywhere in transit rejects the page
    (restore fallback: re-prefill) instead of restoring silent garbage."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.asarray(tokens, np.int32).tobytes())
    for blob in blobs:
        h.update(blob)
    return h.hexdigest()


def pack_entries(entries: list[Any]) -> list[dict[str, Any]]:
    """HostPage entries -> JSON-safe transfer records (chain tokens +
    per-leaf dtype/shape/base64 data + integrity digest)."""
    import jax

    out: list[dict[str, Any]] = []
    for e in entries:
        leaves = jax.tree_util.tree_leaves(e.data)
        blobs = [
            np.ascontiguousarray(leaf).tobytes() for leaf in leaves
        ]
        tokens = np.asarray(e.tokens, np.int32)
        out.append({
            "tokens": tokens.tolist(),
            "digest": _record_digest(tokens, blobs),
            "leaves": [
                {
                    "dtype": str(np.asarray(leaf).dtype),
                    "shape": list(np.asarray(leaf).shape),
                    "data": base64.b64encode(blob).decode("ascii"),
                }
                for leaf, blob in zip(leaves, blobs)
            ],
        })
    return out


def _reject(rec: dict[str, Any], reason: str, **extra: Any) -> None:
    """Drop a corrupt/malformed transfer record: counted, anomaly-dumped,
    and only costs the receiver a re-prefill (restore fallback)."""
    obs.FLEET_KV_IMPORT_REJECTS.inc()
    obs.flight.anomaly(
        "kv_import_reject", cause=reason,
        tokens=len(rec.get("tokens") or ()), **extra,
    )
    log.warning("rejecting KV import record (%s); receiver re-prefills",
                reason)


def unpack_entries(
    records: list[dict[str, Any]], template: Any
) -> list[tuple[list[int], Any]]:
    """Transfer records -> [(chain_tokens, page_tree)] rebuilt against
    ``template``'s pytree structure (any tree with the cache's structure —
    the engine cache itself works; leaf SHAPES in the template are
    ignored). Records are verified before insertion: a leaf count that
    mismatches the local cache structure, or a payload whose digest does
    not match the sender's, is rejected (counter + anomaly dump) — the
    receiver falls back to re-prefill, never restores corrupt KV.
    Records without a digest (older senders) are structure-checked only."""
    import jax

    treedef = jax.tree_util.tree_structure(template)
    out: list[tuple[list[int], Any]] = []
    for rec in records:
        specs = list(rec.get("leaves") or [])
        if faults.fire("transfer.truncate", tokens=len(rec.get("tokens") or ())):
            specs = specs[:-1]   # injected: last leaf lost in transit
        if treedef.num_leaves != len(specs):
            _reject(
                rec, "structure_mismatch",
                record_leaves=len(specs),
                local_leaves=treedef.num_leaves,
            )
            continue
        try:
            blobs = [base64.b64decode(s["data"]) for s in specs]
        except (KeyError, ValueError, TypeError) as e:
            _reject(rec, "undecodable_payload", error=str(e)[:120])
            continue
        if blobs and faults.fire(
            "transfer.corrupt", tokens=len(rec.get("tokens") or ())
        ):
            b = bytearray(blobs[0])      # injected: flip one payload bit
            b[len(b) // 2] ^= 0x01
            blobs[0] = bytes(b)
        digest = rec.get("digest")
        if digest is not None:
            got = _record_digest(
                np.asarray(rec.get("tokens") or [], np.int32), blobs
            )
            if got != digest:
                _reject(rec, "digest_mismatch", expected=digest, got=got)
                continue
        try:
            leaves = [
                np.frombuffer(blob, dtype=np.dtype(s["dtype"]))
                .reshape(s["shape"]).copy()
                for s, blob in zip(specs, blobs)
            ]
        except (KeyError, ValueError, TypeError) as e:
            _reject(rec, "malformed_leaf", error=str(e)[:120])
            continue
        out.append(
            ([int(t) for t in rec["tokens"]],
             jax.tree_util.tree_unflatten(treedef, leaves))
        )
    return out


def records_nbytes(records: list[dict[str, Any]]) -> int:
    """Decoded payload bytes of a transfer (3/4 of the base64 length)."""
    return sum(
        (len(s.get("data", "")) * 3) // 4
        for rec in records
        for s in rec.get("leaves", ())
    )


def migrate_chain(
    src: Any, dst: Any, token_ids: list[int], reason: str,
    session: str = "", park: bool = True, request_id: str = "",
) -> int:
    """Ship one token chain's KV pages from replica ``src`` to replica
    ``dst`` (both ReplicaHandle, serving/fleet/router.py). With ``park``
    the source first evicts the chain from its HBM trie into its host
    pool (Engine.park_chain) — required when the pages are still
    trie-resident; already-parked chains export directly. Returns pages
    shipped (0 on any failure — migration is an optimization layered on
    a correct re-prefill fallback, so it never raises into routing).
    ``request_id`` tags the flight events with the journey this transfer
    serves, so the fleet timeline stitcher can attribute the window."""
    t0 = time.perf_counter()
    rid_field = {"request_id": request_id} if request_id else {}
    obs.flight.record(
        "session_migrate", phase="enter", reason=reason, session=session,
        src=getattr(src, "replica_id", "?"),
        dst=getattr(dst, "replica_id", "?"),
        tokens=len(token_ids), **rid_field,
    )
    pages = 0
    nbytes = 0
    err = ""
    try:
        records = src.export_pages(token_ids, park=park)
        if records:
            pages = dst.import_pages(records)
            nbytes = records_nbytes(records)
    except Exception as e:  # noqa: BLE001 - re-prefill covers correctness
        err = str(e)
        log.exception("chain migration failed (receiver will re-prefill)")
    dt = time.perf_counter() - t0
    if pages:
        obs.FLEET_MIGRATIONS.inc(reason=reason)
        obs.FLEET_TRANSFER_PAGES.inc(pages)
        obs.FLEET_TRANSFER_BYTES.inc(nbytes)
        obs.FLEET_TRANSFER_SECONDS.observe(dt)
    if request_id:
        obs.FLEET_HOP_SECONDS.observe(dt, hop="migrate")
    obs.flight.record(
        "session_migrate", phase="exit", reason=reason, session=session,
        src=getattr(src, "replica_id", "?"),
        dst=getattr(dst, "replica_id", "?"),
        pages=pages, bytes=nbytes, ms=round(dt * 1e3, 3),
        **({"error": err} if err else {}), **rid_field,
    )
    return pages
