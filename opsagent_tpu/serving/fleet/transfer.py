"""Replica-to-replica KV-page transfer: the data plane of session
migration.

The wire format IS the host pool's keying scheme (offload/pool.py): each
shipped page carries the full page-aligned token chain whose KV it holds
plus the page's numpy leaves. The receiving side inserts the entries into
its own HostPagePool under the identical chain keys, so the next admission
of that history restores-instead-of-reprefills through the EXACT local
offload-hit path (engine._restore_from_host -> offload/copy.py scatter) —
migration adds no new restore code, only a second way pages arrive in the
pool.

Pages serialize as JSON-safe dicts (dtype/shape/base64 payload per pytree
leaf, flattened in ``jax.tree_util`` order). The receiver rebuilds the
pytree against its OWN cache treedef — both replicas serve the same model,
so the structures match; a structure mismatch is detected and the entry
dropped (a dropped entry only costs a re-prefill, never correctness: the
pool verifies full token chains on match, so a half-shipped chain can
never alias another history's KV).
"""

from __future__ import annotations

import base64
import time
from typing import Any

import numpy as np

from ... import obs
from ...utils.logger import get_logger

log = get_logger("fleet.transfer")


def pack_entries(entries: list[Any]) -> list[dict[str, Any]]:
    """HostPage entries -> JSON-safe transfer records (chain tokens +
    per-leaf dtype/shape/base64 data)."""
    import jax

    out: list[dict[str, Any]] = []
    for e in entries:
        leaves = jax.tree_util.tree_leaves(e.data)
        out.append({
            "tokens": np.asarray(e.tokens, np.int32).tolist(),
            "leaves": [
                {
                    "dtype": str(np.asarray(leaf).dtype),
                    "shape": list(np.asarray(leaf).shape),
                    "data": base64.b64encode(
                        np.ascontiguousarray(leaf).tobytes()
                    ).decode("ascii"),
                }
                for leaf in leaves
            ],
        })
    return out


def unpack_entries(
    records: list[dict[str, Any]], template: Any
) -> list[tuple[list[int], Any]]:
    """Transfer records -> [(chain_tokens, page_tree)] rebuilt against
    ``template``'s pytree structure (any tree with the cache's structure —
    the engine cache itself works; leaf SHAPES in the template are
    ignored). Records whose leaf count mismatches the template are
    dropped with a log line."""
    import jax

    treedef = jax.tree_util.tree_structure(template)
    out: list[tuple[list[int], Any]] = []
    for rec in records:
        specs = rec.get("leaves") or []
        if treedef.num_leaves != len(specs):
            log.warning(
                "transfer record leaf count %d != local cache structure "
                "%d; dropping page", len(specs), treedef.num_leaves,
            )
            continue
        leaves = [
            np.frombuffer(
                base64.b64decode(s["data"]), dtype=np.dtype(s["dtype"])
            ).reshape(s["shape"]).copy()
            for s in specs
        ]
        out.append(
            ([int(t) for t in rec["tokens"]],
             jax.tree_util.tree_unflatten(treedef, leaves))
        )
    return out


def records_nbytes(records: list[dict[str, Any]]) -> int:
    """Decoded payload bytes of a transfer (3/4 of the base64 length)."""
    return sum(
        (len(s.get("data", "")) * 3) // 4
        for rec in records
        for s in rec.get("leaves", ())
    )


def migrate_chain(
    src: Any, dst: Any, token_ids: list[int], reason: str,
    session: str = "", park: bool = True,
) -> int:
    """Ship one token chain's KV pages from replica ``src`` to replica
    ``dst`` (both ReplicaHandle, serving/fleet/router.py). With ``park``
    the source first evicts the chain from its HBM trie into its host
    pool (Engine.park_chain) — required when the pages are still
    trie-resident; already-parked chains export directly. Returns pages
    shipped (0 on any failure — migration is an optimization layered on
    a correct re-prefill fallback, so it never raises into routing)."""
    t0 = time.perf_counter()
    obs.flight.record(
        "session_migrate", phase="enter", reason=reason, session=session,
        src=getattr(src, "replica_id", "?"),
        dst=getattr(dst, "replica_id", "?"),
        tokens=len(token_ids),
    )
    pages = 0
    nbytes = 0
    err = ""
    try:
        records = src.export_pages(token_ids, park=park)
        if records:
            pages = dst.import_pages(records)
            nbytes = records_nbytes(records)
    except Exception as e:  # noqa: BLE001 - re-prefill covers correctness
        err = str(e)
        log.exception("chain migration failed (receiver will re-prefill)")
    dt = time.perf_counter() - t0
    if pages:
        obs.FLEET_MIGRATIONS.inc(reason=reason)
        obs.FLEET_TRANSFER_PAGES.inc(pages)
        obs.FLEET_TRANSFER_BYTES.inc(nbytes)
        obs.FLEET_TRANSFER_SECONDS.observe(dt)
    obs.flight.record(
        "session_migrate", phase="exit", reason=reason, session=session,
        src=getattr(src, "replica_id", "?"),
        dst=getattr(dst, "replica_id", "?"),
        pages=pages, bytes=nbytes, ms=round(dt * 1e3, 3),
        **({"error": err} if err else {}),
    )
    return pages
