"""The fleet router: a thin front-end that speaks the engine-server API
and spreads sessions over N engine replicas.

Placement policy, in order:

1. **Sticky pinning** — a session (keyed by its opening messages, or an
   explicit ``session_id``/``user`` field) stays on the replica that
   served its first turn, as long as that replica is alive, admitting,
   and under its queue spill bound.
2. **Prefix affinity** — otherwise the replica whose advertised prefix
   digest covers the longest page-aligned prefix of the prompt wins
   (its trie/host pool already holds the KV; the follow-up turn skips
   the re-prefill entirely).
3. **Least-loaded goodput** — no cached state anywhere: the replica with
   the lowest occupancy/goodput load score takes the session.

Bounded queue spill-over bounces a route off an over-deep preferred
replica; when the session's pages live on the replica it is bounced FROM,
the router ships them over the KV-page transfer path first
(serving/fleet/transfer.py) so the receiving engine restores instead of
re-prefilling. Graceful drain parks a replica's running sessions,
migrates them (pages + salvaged tokens) to the rest of the fleet with
zero request errors, and deregisters the replica.

Disaggregated prefill lanes: replicas registered ``role=prefill`` take
long cold admissions (prompt >= the prefill threshold with no useful
affinity anywhere), run exactly one token, and their prefill KV flows to
the chosen decode replica through the same transfer path.

Two replica flavors behind one handle interface: ``LocalReplica`` wraps
an in-process ServingStack (tests, the fleet bench stage, co-hosted
fleets), ``HttpReplica`` a remote engine server (``serve-engine
--join-fleet``).

Failure containment: every replica call feeds a per-replica circuit
breaker (healthy → suspect → ejected with half-open probes, see
registry.ReplicaHealth); connect-phase failures retry on another replica
with exponential backoff + jitter; a replica dying mid-SSE triggers
failover that re-submits the request elsewhere and resumes the client
stream from the last emitted character (greedy decode makes the resumed
text byte-identical); queued cold admissions can be TTFT-hedged on a
second replica; and when every replica's queue is past the shed
watermark, new work gets 429 + Retry-After instead of deepening the
collapse. Fault points for all of these live in serving/faults.py.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import queue as queue_mod
import random
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass, replace as dc_replace
from typing import Any

from ... import obs
from ...utils.logger import get_logger
from .. import faults
from ..scheduler import RequestError
from .registry import ReplicaInfo, ReplicaRegistry, prompt_chain_keys
from .transfer import migrate_chain, pack_entries, unpack_entries

log = get_logger("fleet.router")

DEFAULT_PREFILL_THRESHOLD = 256   # prompt tokens; env/CLI overridable
DEFAULT_MAX_RETRIES = 2           # connect-phase retries per request
DEFAULT_MAX_FAILOVERS = 2         # mid-stream re-submissions per request
RETRY_BACKOFF_BASE_S = 0.05
RETRY_BACKOFF_CAP_S = 2.0


class ReplicaStreamBroken(ConnectionError):
    """A replica's SSE stream died (disconnect, or an in-band error
    chunk from a dying engine) before the finish chunk arrived."""


class OverloadError(RequestError):
    """Router admission control shed this request (HTTP 429); carries
    the Retry-After hint the HTTP front-end surfaces as a header."""

    def __init__(self, message: str, retry_after_s: int = 1):
        super().__init__(message, 429)
        self.retry_after_s = retry_after_s


def _retryable(e: BaseException) -> bool:
    """Failures worth re-routing: transport errors and engine-side (5xx)
    verdicts. Client errors (4xx: bad request, overload shed) would fail
    identically on every replica."""
    if isinstance(e, RequestError):
        return e.status >= 500
    return isinstance(
        e, (urllib.error.URLError, ConnectionError, TimeoutError, OSError)
    )


def _fanout_of(body: Any) -> str | None:
    """The fan-out ID a request body carries, if any (agent/fanout
    orchestrator children) — threaded through journeys and flight events
    so one fan-out is traceable end to end across the fleet."""
    try:
        fid = body.get("fanout_id")
    except AttributeError:
        return None
    return str(fid) if fid else None


def _chunk_content(chunk: Any) -> str:
    """Content delta carried by an SSE chunk dict ('' for head/finish)."""
    if not isinstance(chunk, dict):
        return ""
    choices = chunk.get("choices") or []
    if not choices:
        return ""
    return (choices[0].get("delta") or {}).get("content") or ""


def _is_head_chunk(chunk: Any) -> bool:
    """The role-announcement chunk that opens every stream (emitted once
    per client stream even across failovers)."""
    if not isinstance(chunk, dict):
        return False
    choices = chunk.get("choices") or []
    if not choices:
        return False
    return "role" in (choices[0].get("delta") or {})


def _trim_chunk_content(chunk: dict[str, Any], skip: int) -> dict[str, Any]:
    """Drop the first ``skip`` chars of a chunk's content delta — the
    failover seam lands mid-chunk and the prefix was already delivered."""
    out = copy.deepcopy(chunk)
    delta = out["choices"][0]["delta"]
    delta["content"] = delta["content"][skip:]
    return out


# -- replica handles ----------------------------------------------------------
class LocalReplica:
    """In-process replica handle over a ServingStack (serving/api.py)."""

    def __init__(self, stack: Any, replica_id: str, role: str = "decode"):
        self.stack = stack
        self.replica_id = replica_id
        self.role = role

    # routing data plane
    def chat_completion(self, body: dict[str, Any]) -> dict[str, Any]:
        return self.stack.chat_completion(body)

    def chat_completion_stream(self, body: dict[str, Any]):
        return self.stack.chat_completion_stream(body)

    def tokenize(self, body: dict[str, Any]) -> list[int]:
        from ..chat_template import apply_chat_template

        tools = (
            None if body.get("tool_choice") == "none" else body.get("tools")
        )
        return apply_chat_template(
            self.stack.engine.tokenizer, body.get("messages", []),
            model_family=self.stack.model_name, tools=tools,
        )

    # registry feeds
    def info(self) -> ReplicaInfo:
        eng = self.stack.engine
        return ReplicaInfo(
            replica_id=self.replica_id,
            model=self.stack.model_name,
            role=self.role,
            capacity=int(eng.cfg.max_batch_size),
            page_size=int(eng.cfg.page_size),
            mesh={"tp": eng.cfg.tp, "sp": eng.cfg.sp, "ep": eng.cfg.ep},
            digests=set(self.prefix_digests()),
            load=self.load_snapshot(),
            local=True,
            handle=self,
        )

    def load_snapshot(self) -> dict[str, Any]:
        eng = self.stack.engine
        sched = self.stack.scheduler
        out = {
            "running": len(sched._running),
            "queued": len(sched._waiting) + sched._queue.qsize(),
            "prefilling": len(sched._prefilling),
            "free_pages": eng.alloc.free_pages,
            "goodput": {},
        }
        if eng.offload is not None:
            out["host_pool_pages"] = eng.offload.pool.num_pages
        return out

    def prefix_digests(self) -> list[str]:
        return self.stack.engine.prefix_digests()

    def digests_truncated(self) -> bool:
        return self.stack.engine.digests_truncated()

    # KV transfer plane
    def park_tokens(self, token_ids: list[int]) -> int:
        eng = self.stack.engine
        parked = eng.park_chain(token_ids)
        eng.offload_flush()
        return parked

    def export_pages(
        self, token_ids: list[int], park: bool = True,
        start_page: int = 0,
    ) -> list[dict[str, Any]]:
        eng = self.stack.engine
        if eng.offload is None:
            return []
        if park:
            self.park_tokens(token_ids)
        else:
            # Peer fault-in must not cost this replica its own cache:
            # replicate trie-resident pages into the pool (copy, not
            # evict) so the pack below can serve them.
            eng.replicate_chain(token_ids)
        return pack_entries(
            eng.offload.pool.match(token_ids, start_page=start_page)
        )

    def import_pages(self, records: list[dict[str, Any]]) -> int:
        eng = self.stack.engine
        if eng.offload is None:
            return 0
        n = 0
        for tokens, tree in unpack_entries(records, eng.cache):
            if eng.offload.pool.put(tokens, tree):
                n += 1
        return n

    # drain plane
    def drain_sessions(self) -> list[Any]:
        return self.stack.scheduler.drain_for_migration()

    def submit_request(self, req: Any) -> None:
        self.stack.scheduler.submit(req)

    # observability plane
    def slo(self) -> dict[str, Any]:
        return obs.slo.evaluate()

    def history(self, **kwargs: Any) -> dict[str, Any]:
        return obs.history.query(**kwargs)

    def timeline(self, request_id: str) -> dict[str, Any] | None:
        return obs.timeline.assemble(request_id)

    def flight(
        self, n: int = 256, kind: str = ""
    ) -> list[dict[str, Any]]:
        return obs.flight.get_recorder().snapshot(n=n, kind=kind or None)

    def close(self) -> None:
        self.stack.close()


class HttpReplica:
    """Remote replica handle over the engine server's HTTP surface."""

    def __init__(self, url: str, replica_id: str, timeout_s: float = 300.0):
        self.url = url.rstrip("/")
        self.replica_id = replica_id
        self.timeout_s = timeout_s

    @staticmethod
    def _hop_headers(body: Any) -> dict[str, str]:
        """X-Fleet-* hop annotation headers mirroring ``fleet_hop`` in
        the body (the wire spec: body field primary; the headers let a
        front proxy that strips unknown body fields still propagate the
        journey ID to the replica)."""
        hop = body.get("fleet_hop") if isinstance(body, dict) else None
        if not isinstance(hop, dict) or not hop.get("request_id"):
            return {}
        return {
            "X-Fleet-Request-Id": str(hop["request_id"]),
            "X-Fleet-Hop": str(hop.get("hop", "")),
            "X-Fleet-Replica": str(hop.get("replica", "")),
        }

    def _call(
        self, path: str, body: dict | None = None,
        timeout_s: float | None = None,
        headers: dict[str, str] | None = None,
    ) -> dict[str, Any]:
        faults.maybe_raise(
            "fleet.connect",
            urllib.error.URLError(ConnectionRefusedError(
                "injected connection refused"
            )),
            replica=self.replica_id, path=path,
        )
        faults.maybe_raise(
            "fleet.timeout", TimeoutError, "injected request timeout",
            replica=self.replica_id, path=path,
        )
        data = None if body is None else json.dumps(body).encode("utf-8")
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        req = urllib.request.Request(
            self.url + path, data=data, headers=hdrs,
        )
        with urllib.request.urlopen(  # noqa: S310 - operator-registered URL
            req, timeout=timeout_s or self.timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def chat_completion(self, body: dict[str, Any]) -> dict[str, Any]:
        try:
            return self._call(
                "/v1/chat/completions", body,
                headers=self._hop_headers(body),
            )
        except urllib.error.HTTPError as e:  # surface the engine's verdict
            try:
                payload = json.loads(e.read().decode("utf-8"))
                msg = payload.get("error", {}).get("message", str(e))
            except Exception:  # noqa: BLE001
                msg = str(e)
            raise RequestError(msg, e.code) from e

    def chat_completion_stream(self, body: dict[str, Any]):
        """SSE pass-through: yields parsed chunk dicts like the local
        generator, so the router's stream handler treats both alike."""
        faults.maybe_raise(
            "fleet.connect",
            urllib.error.URLError(ConnectionRefusedError(
                "injected connection refused"
            )),
            replica=self.replica_id, path="/v1/chat/completions",
        )
        data = json.dumps(dict(body, stream=True)).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/v1/chat/completions", data=data,
            headers={
                "Content-Type": "application/json",
                **self._hop_headers(body),
            },
        )
        with urllib.request.urlopen(  # noqa: S310
            req, timeout=self.timeout_s
        ) as resp:
            for raw in resp:
                line = raw.decode("utf-8").strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    return
                yield json.loads(payload)

    def load_snapshot(self) -> dict[str, Any]:
        h = self._call("/healthz", timeout_s=5.0)
        return {
            "running": h.get("running", 0),
            "queued": h.get("queued", 0),
            "prefilling": h.get("prefilling", 0),
            "free_pages": h.get("free_pages", 0),
            "goodput": h.get("goodput", {}),
        }

    def prefix_digests(self) -> list[str]:
        return self._call("/fleet/digests", timeout_s=10.0).get(
            "digests", []
        )

    def park_tokens(self, token_ids: list[int]) -> int:
        return int(self._call(
            "/fleet/park", {"tokens": token_ids}, timeout_s=30.0
        ).get("parked_tokens", 0))

    def export_pages(
        self, token_ids: list[int], park: bool = True,
        start_page: int = 0,
    ) -> list[dict[str, Any]]:
        out = self._call(
            "/fleet/kv/export",
            {
                "chains": [
                    {"tokens": token_ids, "start_page": start_page}
                ],
                "park": park,
            },
            timeout_s=60.0,
        )
        results = out.get("results")
        if results:
            return results[0].get("pages", [])
        return out.get("pages", [])

    def import_pages(self, records: list[dict[str, Any]]) -> int:
        return int(self._call(
            "/fleet/kv/import", {"pages": records}, timeout_s=60.0
        ).get("imported", 0))

    def drain_sessions(self) -> list[Any]:
        # Cross-process live-request hand-off would need the router to own
        # the client connection end-to-end; today an HTTP drain stops
        # admissions (the replica finishes what it runs) and the parked
        # idle sessions migrate lazily on their next turn.
        try:
            self._call("/fleet/drain", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 - best-effort notification
            log.exception("drain notification to %s failed", self.url)
        return []

    def slo(self) -> dict[str, Any]:
        return self._call("/api/slo", timeout_s=10.0)

    def history(
        self, series: list[str] | None = None, since: float = 300.0,
        step: float | None = None,
    ) -> dict[str, Any]:
        q = f"?since={since}"
        if series:
            q += "&series=" + ",".join(series)
        if step:
            q += f"&step={step}"
        return self._call(f"/api/metrics/history{q}", timeout_s=10.0)

    def timeline(self, request_id: str) -> dict[str, Any] | None:
        try:
            return self._call(
                f"/api/timeline/{request_id}", timeout_s=10.0
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def flight(
        self, n: int = 256, kind: str = ""
    ) -> list[dict[str, Any]]:
        q = f"?n={int(n)}"
        if kind:
            q += f"&kind={kind}"
        out = self._call(f"/api/debug/flight{q}", timeout_s=10.0)
        return out.get("events", [])


# -- routing decisions --------------------------------------------------------
def _merge_class_reports(
    reports: list[list[dict[str, Any]]], budget: float
) -> list[dict[str, Any]]:
    """Fold per-replica SLO-class reports into one fleet view: requests,
    bad, and outcome counts sum; attainment is recomputed from the sums;
    p95 latencies take the worst replica (a fleet p95 cannot be computed
    from per-replica quantiles — max is the honest bound); history
    windows merge per label with request-weighted attainment."""
    by_cls: dict[str, dict[str, Any]] = {}
    for rows in reports:
        for c in rows or []:
            cls = c.get("class")
            if not cls:
                continue
            m = by_cls.setdefault(cls, {
                "class": cls, "requests": 0, "bad": 0,
                "ttft_p95_ms": None, "itl_p95_ms": None,
                "outcomes": {}, "_windows": {},
            })
            m["requests"] += int(c.get("requests") or 0)
            m["bad"] += int(c.get("bad") or 0)
            for k in ("ttft_p95_ms", "itl_p95_ms"):
                v = c.get(k)
                if v is not None:
                    m[k] = v if m[k] is None else max(m[k], v)
            for o, n in (c.get("outcomes") or {}).items():
                m["outcomes"][o] = m["outcomes"].get(o, 0) + int(n)
            for label, w in (c.get("windows") or {}).items():
                mw = m["_windows"].setdefault(
                    label, {"requests": 0, "good": 0.0}
                )
                wr = int(w.get("requests") or 0)
                mw["requests"] += wr
                mw["good"] += wr * float(w.get("attainment") or 0.0)
    out: list[dict[str, Any]] = []
    for cls in obs.SLO_CLASSES:
        m = by_cls.get(cls)
        if m is None or m["requests"] <= 0:
            continue
        m["attainment"] = round(
            (m["requests"] - m["bad"]) / m["requests"], 6
        )
        windows: dict[str, Any] = {}
        for label, mw in m.pop("_windows").items():
            if mw["requests"] <= 0:
                continue
            att = mw["good"] / mw["requests"]
            windows[label] = {
                "requests": mw["requests"],
                "attainment": round(att, 6),
                "burn_rate": (
                    round((1.0 - att) / budget, 4) if budget > 0 else None
                ),
            }
        m["windows"] = windows
        out.append(m)
    return out


@dataclass
class RouteDecision:
    replica: ReplicaInfo
    policy: str                 # pinned|affinity|least_loaded|spill|forced
    affinity_pages: int = 0
    queue_depth: int = 0
    migrate_from: str | None = None   # replica id still holding the pages
    session: str = ""


class FleetRouter:
    """Placement + migration control plane. Thread-safe; the HTTP app
    drives it from executor threads, tests call it directly."""

    def __init__(
        self,
        registry: ReplicaRegistry | None = None,
        affinity: bool = True,
        queue_spill: int | None = None,
        prefill_threshold: int = DEFAULT_PREFILL_THRESHOLD,
        tokenizer: Any = None,
        model_family: str = "",
        sticky: bool = True,
        placement: str = "affinity",
        max_retries: int = DEFAULT_MAX_RETRIES,
        max_failovers: int = DEFAULT_MAX_FAILOVERS,
        hedge_queue_depth: int | None = None,
        shed_queue_depth: int | None = None,
        batch_shed_queue_depth: int | None = None,
        pagestore: bool = True,
        journeys: bool | None = None,
    ):
        """``sticky=False`` disables session->replica pinning (every turn
        re-places from scratch). ``placement="round_robin"`` replaces the
        whole policy with a stateless rotation — the bench fleet-affinity
        stage's OFF phase, and what a cache-oblivious load balancer in
        front of the same replicas would do. (Least-loaded alone is NOT a
        fair no-affinity baseline for turn-based sessions: a session's
        own replica frees a slot the instant its turn ends, so occupancy
        routes the follow-up straight back home by accident.)

        Failure containment knobs: ``max_retries`` bounds connect-phase
        re-routes per request, ``max_failovers`` bounds mid-stream
        re-submissions, ``hedge_queue_depth`` (None = off) races a
        duplicate of a queued cold non-streaming admission on a second
        replica, ``shed_queue_depth`` (None = off) sheds new admissions
        with 429 + Retry-After once EVERY live decode replica's queue
        is at or past the watermark. ``batch_shed_queue_depth`` is the
        earlier watermark for batch/background requests (default: half
        of ``shed_queue_depth``, floor 1) — fan-out children soak
        LEFTOVER capacity, so batch work backs off before it can be
        what pushes interactive traffic to its own shed line.

        ``pagestore`` wires every ``add_local`` replica's engine with a
        fleet-global KV fault-in client against this router's directory
        (fleet/pagestore.py): an admission that misses its local trie +
        host pool fetches the chain peer-to-peer instead of
        re-prefilling. With the directory on, affinity placement is a
        locality optimization, not a correctness crutch — any live
        replica can serve a known session. ``pagestore=False`` is the
        A/B OFF phase (pre-directory behavior).

        ``journeys`` (default: ``$OPSAGENT_FLEET_JOURNEYS`` != "0")
        turns on fleet request journeys: the router mints one journey ID
        per request, stamps every replica hop with it (the engine adopts
        the ID), tracks participants per request, and serves stitched
        cross-replica timelines. ``journeys=False`` is the obs-overhead
        A/B OFF phase — requests route identically but carry no stamps
        and the participants map stays empty."""
        self.registry = registry or ReplicaRegistry()
        self.affinity = affinity
        self.sticky = sticky
        self.placement = placement
        self._rr = 0
        self.queue_spill = queue_spill
        self.prefill_threshold = prefill_threshold
        self.max_retries = max_retries
        self.max_failovers = max_failovers
        self.hedge_queue_depth = hedge_queue_depth
        self.shed_queue_depth = shed_queue_depth
        self.batch_shed_queue_depth = batch_shed_queue_depth
        self._tokenizer = tokenizer
        self._model_family = model_family
        self.pagestore = pagestore
        if journeys is None:
            journeys = os.environ.get(
                "OPSAGENT_FLEET_JOURNEYS", "1"
            ) != "0"
        self.journeys = journeys
        self._lock = threading.Lock()
        self._pins: OrderedDict[str, str] = OrderedDict()     # session->rid
        # Append-only participants map (bounded LRU), one record per
        # journey: every replica hop (route / stream / failover / hedge /
        # prefill / migrate) appends here, so a hedged or failed-over
        # request keeps its WHOLE replica set — not just the final
        # winner (the old ``_owners`` map's partial-story bug).
        self._participants: OrderedDict[str, dict[str, Any]] = \
            OrderedDict()
        self._max_map = 8192
        # Elastic scale-out (serving/fleet/autoscale.py). None = static
        # fleet; set by run_router_server or a test harness. The router
        # only feeds it shed pressure — all policy lives in the scaler.
        self.autoscaler: Any = None
        # Anomaly dumps in this process gain the triggering request's
        # cross-replica journey (obs/flight.py _dump_context).
        obs.flight.set_journey_provider(self.journey_of)

    # -- membership convenience -------------------------------------------
    def add_local(
        self, stack: Any, replica_id: str, role: str = "decode"
    ) -> LocalReplica:
        """Register an in-process ServingStack as a replica.
        ``role="standby"`` keeps it out of the routable decode set until
        the autoscaler promotes it."""
        handle = LocalReplica(stack, replica_id, role=role)
        self.registry.register(handle.info())
        if self.pagestore and stack.engine.offload is not None:
            from .pagestore import local_client

            stack.engine.pagestore = local_client(
                self.registry, replica_id, stack.engine
            )
        return handle

    # -- session identity ---------------------------------------------------
    @staticmethod
    def session_key(body: dict[str, Any]) -> str:
        """Stable identity of a conversation: an explicit session_id/user
        field wins; otherwise the digest of the opening (system + first
        user) messages — which every follow-up turn of an agent session
        re-sends verbatim at the head of its history."""
        explicit = body.get("session_id") or body.get("user")
        if explicit:
            return str(explicit)
        head = []
        for m in body.get("messages", []):
            head.append((m.get("role", ""), str(m.get("content", ""))))
            if m.get("role") == "user":
                break
        return hashlib.blake2b(
            json.dumps(head, ensure_ascii=False).encode("utf-8"),
            digest_size=12,
        ).hexdigest()

    def tokenize(self, body: dict[str, Any]) -> list[int] | None:
        """Prompt token ids for affinity scoring: the router-owned
        tokenizer when configured (HTTP fleets), else the first live
        local replica's engine tokenizer (in-process fleets). None
        disables affinity for this request (least-loaded still routes)."""
        if self._tokenizer is not None:
            from ..chat_template import apply_chat_template

            tools = (
                None if body.get("tool_choice") == "none"
                else body.get("tools")
            )
            return apply_chat_template(
                self._tokenizer, body.get("messages", []),
                model_family=self._model_family, tools=tools,
            )
        for info in self.registry.alive(admitting=False):
            if info.local and info.handle is not None:
                try:
                    return info.handle.tokenize(body)
                except Exception:  # noqa: BLE001 - affinity is best-effort
                    log.exception("local tokenize failed")
                    return None
        return None

    # -- placement ----------------------------------------------------------
    def _spill_bound(self, info: ReplicaInfo) -> int:
        return self.queue_spill if self.queue_spill is not None \
            else max(2, info.capacity)

    def route(
        self,
        body: dict[str, Any],
        token_ids: list[int] | None = None,
        force_replica: str | None = None,
        exclude: set[str] | None = None,
    ) -> RouteDecision:
        """``exclude`` drops replicas the caller just watched fail (the
        retry/failover loops); when exclusion would empty the fleet the
        full candidate set is kept — retrying the only replica beats
        failing outright. Routing onto an ejected-past-cooldown replica
        marks its half-open probe (the call outcome closes or re-opens
        the breaker)."""
        d = self._route_inner(body, token_ids, force_replica, exclude)
        health = self.registry.health_of(d.replica.replica_id)
        if health is not None and health.state == "ejected":
            self.registry.begin_probe(d.replica.replica_id)
            obs.flight.record(
                "replica_probe", replica=d.replica.replica_id,
                session=d.session,
            )
        return d

    def _route_inner(
        self,
        body: dict[str, Any],
        token_ids: list[int] | None = None,
        force_replica: str | None = None,
        exclude: set[str] | None = None,
    ) -> RouteDecision:
        self.registry.refresh_local()
        skey = self.session_key(body)
        candidates = self.registry.alive(role="decode")
        if exclude:
            kept = [
                c for c in candidates if c.replica_id not in exclude
            ]
            if kept:
                candidates = kept
        if not candidates:
            raise RequestError("no live decode replicas in the fleet", 503)
        if self.placement == "round_robin" and force_replica is None:
            with self._lock:
                pick = candidates[self._rr % len(candidates)]
                self._rr += 1
            return RouteDecision(
                pick, "round_robin",
                queue_depth=pick.queue_depth(), session=skey,
            )
        with self._lock:
            pinned_id = self._pins.get(skey) if self.sticky else None
        pinned = next(
            (c for c in candidates if c.replica_id == pinned_id), None
        )
        # Affinity scores (pages of cached prefix per replica).
        scores: dict[str, int] = {}
        if self.affinity and token_ids:
            by_psize: dict[int, list[str]] = {}
            for c in candidates:
                keys = by_psize.get(c.page_size)
                if keys is None:
                    keys = prompt_chain_keys(token_ids, c.page_size)
                    by_psize[c.page_size] = keys
                scores[c.replica_id] = c.affinity_pages(keys)

        def best_of(pool: list[ReplicaInfo]) -> tuple[ReplicaInfo, str, int]:
            """(replica, policy, affinity_pages) over ``pool``: longest
            cached prefix wins; ties and score-0 fall to least-loaded."""
            if scores:
                top = max(
                    pool, key=lambda c: (
                        scores.get(c.replica_id, 0) * c.page_size,
                        -c.load_score(),
                    ),
                )
                if scores.get(top.replica_id, 0) > 0:
                    return top, "affinity", scores[top.replica_id]
            top = min(pool, key=lambda c: c.load_score())
            return top, "least_loaded", scores.get(top.replica_id, 0)

        def holder_of_pages(exclude: str) -> str | None:
            """The replica (any state, any role) best holding this
            prompt's pages, for migrate-from bookkeeping."""
            best_id, best_score = None, 0
            for info in self.registry.all():
                if info.replica_id == exclude:
                    continue
                s = scores.get(info.replica_id, 0)
                if s > best_score:
                    best_id, best_score = info.replica_id, s
            if best_id is None and pinned_id and pinned_id != exclude:
                best_id = pinned_id
            return best_id

        if force_replica is not None:
            forced = self.registry.get(force_replica)
            if forced is None:
                raise RequestError(
                    f"unknown replica {force_replica!r}", 404
                )
            return RouteDecision(
                forced, "forced",
                affinity_pages=scores.get(force_replica, 0),
                queue_depth=forced.queue_depth(),
                migrate_from=holder_of_pages(force_replica),
                session=skey,
            )
        if pinned is not None:
            depth = pinned.queue_depth()
            if depth < self._spill_bound(pinned):
                return RouteDecision(
                    pinned, "pinned",
                    affinity_pages=scores.get(pinned.replica_id, 0),
                    queue_depth=depth, session=skey,
                )
            # Bounded spill-over: the preferred replica's queue is too
            # deep; route elsewhere and bring the pages along.
            obs.FLEET_SPILLOVERS.inc()
            others = [c for c in candidates if c is not pinned]
            if others:
                top, _, pages = best_of(others)
                mig = (
                    pinned.replica_id
                    if scores.get(pinned.replica_id, 0) > pages else None
                )
                return RouteDecision(
                    top, "spill", affinity_pages=pages,
                    queue_depth=top.queue_depth(),
                    migrate_from=mig, session=skey,
                )
            return RouteDecision(
                pinned, "pinned",
                affinity_pages=scores.get(pinned.replica_id, 0),
                queue_depth=depth, session=skey,
            )
        top, policy, pages = best_of(candidates)
        mig = None
        if policy == "least_loaded":
            mig = holder_of_pages(top.replica_id)
        return RouteDecision(
            top, policy, affinity_pages=pages,
            queue_depth=top.queue_depth(), migrate_from=mig, session=skey,
        )

    def _record_decision(
        self, d: RouteDecision, request_id: str | None = None,
        fanout_id: str | None = None,
    ) -> None:
        obs.FLEET_ROUTE_DECISIONS.inc(policy=d.policy)
        obs.FLEET_AFFINITY_PAGES.observe(float(d.affinity_pages))
        obs.flight.record(
            "route_decision", replica=d.replica.replica_id,
            policy=d.policy, affinity_pages=d.affinity_pages,
            affinity_tokens=d.affinity_pages * d.replica.page_size,
            queue_depth=d.queue_depth, session=d.session,
            **({"request_id": request_id} if request_id else {}),
            **({"fanout_id": fanout_id} if fanout_id else {}),
        )

    # -- journey bookkeeping -------------------------------------------------
    # Journey shapes in escalation order: a journey counts once, under
    # its most eventful shape (a hedged request that then fails over is
    # a failover journey).
    _SHAPE_RANK = {"direct": 0, "retried": 1, "hedged": 2, "failover": 3}

    def _new_journey(self, body: Any = None) -> str | None:
        """Mint the journey ID the engine will ADOPT as its completion
        id (same chatcmpl- namespace) and open its participants record.
        None when journeys are off (the obs-overhead kill switch)."""
        if not self.journeys:
            return None
        jid = obs.new_request_id("chatcmpl")
        fanout_id = _fanout_of(body)
        with self._lock:
            self._participants[jid] = {
                "t0_wall": time.time(), "shape": "direct",
                "class": obs.slo.classify(body),
                "replicas": [], "hops": [],
                **({"fanout_id": fanout_id} if fanout_id else {}),
            }
            while len(self._participants) > self._max_map:
                self._participants.popitem(last=False)
        return jid

    def _journey_class(self, jid: str | None) -> str:
        with self._lock:
            rec = self._participants.get(jid) if jid else None
        return (rec or {}).get("class") or "interactive"

    def _stamp_hop(
        self, body: dict[str, Any], jid: str | None, hop: str,
        replica_id: str,
    ) -> dict[str, Any]:
        """Copy of ``body`` stamped with the fleet hop annotation the
        engine pops and adopts; the caller's body stays clean so a
        retry/failover leg restamps with its own hop kind."""
        if not jid:
            return body
        out = dict(body)
        out["fleet_hop"] = {
            "request_id": jid, "hop": hop, "replica": replica_id,
        }
        return out

    def _note_hop(
        self, jid: str | None, replica_id: str, hop: str, **extra: Any
    ) -> None:
        """Append one hop record to the journey BEFORE dispatching, so
        the participants map holds every replica the request touched
        even when the touch fails (the failed leg is exactly the one
        the postmortem needs)."""
        if not jid:
            return
        with self._lock:
            rec = self._participants.get(jid)
            if rec is None:
                return
            if replica_id and replica_id not in rec["replicas"]:
                rec["replicas"].append(replica_id)
            rec["hops"].append({
                "hop": hop, "replica": replica_id,
                "wall": time.time(), **extra,
            })
            self._participants.move_to_end(jid)

    def _note_shape(self, jid: str | None, shape: str) -> None:
        if not jid:
            return
        with self._lock:
            rec = self._participants.get(jid)
            if rec is not None and (
                self._SHAPE_RANK.get(shape, 0)
                > self._SHAPE_RANK.get(rec.get("shape", "direct"), 0)
            ):
                rec["shape"] = shape

    def _finish_journey(self, jid: str | None) -> None:
        """Count the completed journey once, under its final shape and
        SLO class."""
        if not jid:
            return
        with self._lock:
            rec = self._participants.get(jid)
            if rec is None or rec.get("counted"):
                return
            rec["counted"] = True
            shape = rec.get("shape", "direct")
            cls = rec.get("class") or "interactive"
        obs.FLEET_JOURNEYS.inc(**{"shape": shape, "class": cls})

    def journey_of(self, request_id: str) -> dict[str, Any] | None:
        """The cross-replica journey of a tracked request (shape +
        replicas + hops) — the flight recorder's journey provider, so
        anomaly dumps naming a request carry its whole fleet story."""
        with self._lock:
            rec = self._participants.get(request_id)
            if rec is None:
                return None
            return {
                "shape": rec.get("shape", "direct"),
                "replicas": list(rec["replicas"]),
                "hops": [dict(h) for h in rec["hops"]],
            }

    def participants_of(self, request_id: str) -> dict[str, Any] | None:
        with self._lock:
            rec = self._participants.get(request_id)
            if rec is None:
                return None
            return {
                "t0_wall": rec.get("t0_wall"),
                "shape": rec.get("shape", "direct"),
                "replicas": list(rec["replicas"]),
                "hops": [dict(h) for h in rec["hops"]],
            }

    def _note_ownership(
        self, d: RouteDecision, resp_id: str | None,
        jid: str | None = None,
    ) -> None:
        with self._lock:
            if self.sticky:
                self._pins[d.session] = d.replica.replica_id
                self._pins.move_to_end(d.session)
                while len(self._pins) > self._max_map:
                    self._pins.popitem(last=False)
            if resp_id and jid and resp_id != jid:
                # The replica did NOT adopt the journey id (journeys
                # disabled replica-side, or an older build): alias the
                # record under the id the client actually holds.
                rec = self._participants.get(jid)
                if rec is not None:
                    self._participants[resp_id] = rec
                    while len(self._participants) > self._max_map:
                        self._participants.popitem(last=False)
            elif resp_id and not jid:
                # Journeys off router-side: keep a minimal final-replica
                # record so timeline lookups still resolve.
                self._participants[resp_id] = {
                    "t0_wall": time.time(), "shape": "direct",
                    "replicas": [d.replica.replica_id], "hops": [],
                }
                while len(self._participants) > self._max_map:
                    self._participants.popitem(last=False)

    def _maybe_migrate(
        self, d: RouteDecision, token_ids: list[int] | None, reason: str,
        jid: str | None = None,
    ) -> None:
        if d.migrate_from is None or not token_ids:
            return
        if reason == "misroute" and self.pagestore:
            # Fleet-global KV demotes the eager misroute push: the
            # receiving replica faults the chain in (pull) through the
            # page directory at admission, so a push here would only
            # duplicate the transfer. Failover and drain pushes stay —
            # their source is failing/leaving and may be gone from the
            # directory by the time the receiver asks.
            return
        src = self.registry.get(d.migrate_from)
        if src is None or src.handle is None or d.replica.handle is None:
            return
        self._note_hop(jid, d.migrate_from, "migrate", reason=reason)
        migrate_chain(
            src.handle, d.replica.handle, token_ids,
            reason=reason, session=d.session, request_id=jid or "",
        )

    def _maybe_prefill_lane(
        self, d: RouteDecision, body: dict[str, Any],
        token_ids: list[int] | None, jid: str | None = None,
    ) -> None:
        """Disaggregated prefill: a long cold admission runs its prefill
        on a role=prefill replica, whose KV then flows to the chosen
        decode replica over the transfer path; the decode replica's
        admission restores the prompt pages instead of prefilling them."""
        if not token_ids or len(token_ids) < self.prefill_threshold:
            return
        covered = d.affinity_pages * d.replica.page_size
        if covered * 2 >= len(token_ids):
            return  # warm enough locally; the lane would only add copies
        lanes = self.registry.alive(role="prefill")
        if not lanes or d.replica.handle is None:
            return
        lane = min(lanes, key=lambda c: c.load_score())
        if lane.handle is None:
            return
        obs.FLEET_ROUTE_DECISIONS.inc(policy="prefill")
        obs.flight.record(
            "route_decision", replica=lane.replica_id, policy="prefill",
            affinity_pages=0, queue_depth=lane.queue_depth(),
            session=d.session,
            **({"request_id": jid} if jid else {}),
        )
        self._note_hop(jid, lane.replica_id, "prefill")
        t0 = time.perf_counter()
        try:
            pre_body = dict(body)
            pre_body.pop("stream", None)
            pre_body.pop("n", None)
            pre_body["max_tokens"] = 1
            lane.handle.chat_completion(
                self._stamp_hop(pre_body, jid, "prefill", lane.replica_id)
            )
        except Exception:  # noqa: BLE001 - the lane is an optimization
            log.exception("prefill lane failed; decode replica prefills")
            return
        finally:
            if jid:
                obs.FLEET_HOP_SECONDS.observe(
                    time.perf_counter() - t0, hop="prefill"
                )
        migrate_chain(
            lane.handle, d.replica.handle, token_ids,
            reason="prefill_handoff", session=d.session,
            request_id=jid or "",
        )

    # -- overload shedding ---------------------------------------------------
    def _check_overload(
        self, force_replica: str | None, body: Any = None
    ) -> None:
        """Router admission control: once EVERY live decode replica's
        queue depth is at or past the watermark, new work is shed with
        429 + Retry-After BEFORE it deepens the queues (backpressure to
        the client instead of melted replicas). Forced routes (operator
        overrides, drain tooling) bypass the shed. The shed is classed:
        which class's demand the fleet turned away is the signal the
        autoscaler's replica_launch decision records as trigger_class.

        The watermark is per class: batch/background admissions shed at
        ``batch_shed_queue_depth`` (default half the interactive
        watermark), so a fan-out wave only soaks capacity interactive
        traffic is not using — batch demand backs off while interactive
        requests still admit freely."""
        if self.shed_queue_depth is None or force_replica is not None:
            return
        cls = obs.slo.classify(body)
        watermark = self.shed_queue_depth
        if cls != "interactive":
            watermark = (
                self.batch_shed_queue_depth
                if self.batch_shed_queue_depth is not None
                else max(1, self.shed_queue_depth // 2)
            )
        self.registry.refresh_local()
        cands = self.registry.alive(role="decode")
        if not cands:
            return  # route() raises its own 503
        depths = [c.queue_depth() for c in cands]
        if min(depths) < watermark:
            return
        retry_after = int(min(30, max(1, min(depths))))
        obs.FLEET_SHED.inc(**{"class": cls})
        if self.autoscaler is not None:
            # Shed = demand the fleet turned away: the strongest scale-up
            # signal there is. Note it before the 429 leaves the building.
            self.autoscaler.note_shed(cls)
        obs.FLEET_REQUESTS.inc(outcome="shed")
        obs.CLASS_REQUESTS.inc(**{"class": cls, "outcome": "shed"})
        fanout_id = _fanout_of(body)
        obs.flight.record(
            "request_shed", min_queue_depth=min(depths),
            watermark=watermark, retry_after_s=retry_after,
            slo_class=cls,
            **({"fanout_id": fanout_id} if fanout_id else {}),
        )
        raise OverloadError(
            f"fleet overloaded: every replica queue depth >= {watermark} "
            f"(class {cls}); retry later", retry_after,
        )

    @staticmethod
    def _backoff(attempt: int) -> None:
        """Exponential backoff + jitter between re-routes. The jitter is
        real randomness on purpose — it decorrelates retrying clients;
        fault DECISIONS (faults.py) stay count-based so injected chaos
        is deterministic even though retry timing is not."""
        delay = min(
            RETRY_BACKOFF_CAP_S,
            RETRY_BACKOFF_BASE_S * (2 ** max(0, attempt - 1)),
        )
        time.sleep(delay * random.uniform(0.5, 1.0))

    # -- TTFT hedging --------------------------------------------------------
    def _pick_hedge_backup(self, d: RouteDecision) -> ReplicaInfo | None:
        """A queued COLD admission (no cached prefix anywhere to lose)
        whose chosen replica has ``hedge_queue_depth`` or more requests
        ahead of it is worth racing on a second replica: greedy decode
        is deterministic, so the duplicate is pure latency insurance."""
        if self.hedge_queue_depth is None or d.affinity_pages > 0:
            return None
        if d.queue_depth < self.hedge_queue_depth:
            return None
        others = [
            c for c in self.registry.alive(role="decode")
            if c.replica_id != d.replica.replica_id
            and c.handle is not None
        ]
        if not others:
            return None
        return min(others, key=lambda c: c.load_score())

    def _hedged_complete(
        self, body: dict[str, Any], d: RouteDecision, backup: ReplicaInfo,
        jid: str | None = None,
    ) -> tuple[RouteDecision, dict[str, Any]]:
        """Race the admission on primary + backup; first completion wins
        (the loser's work is discarded — greedy outputs are identical).
        Each arrival feeds the circuit breaker; the winner's decision is
        what gets recorded/pinned."""
        obs.FLEET_HEDGES.inc(**{"class": self._journey_class(jid)})
        self._note_shape(jid, "hedged")
        obs.flight.record(
            "fleet_hedge", primary=d.replica.replica_id,
            backup=backup.replica_id, queue_depth=d.queue_depth,
            session=d.session,
            **({"request_id": jid} if jid else {}),
        )
        results: queue_mod.Queue = queue_mod.Queue()

        def _run(info: ReplicaInfo, hop: str) -> None:
            self._note_hop(jid, info.replica_id, hop)
            t0 = time.perf_counter()
            try:
                resp = info.handle.chat_completion(
                    self._stamp_hop(body, jid, hop, info.replica_id)
                )
                results.put((info, resp, None))
            except Exception as e:  # noqa: BLE001 - raced; judged below
                results.put((info, None, e))
            finally:
                if jid:
                    obs.FLEET_HOP_SECONDS.observe(
                        time.perf_counter() - t0, hop=hop
                    )

        for info, hop in ((d.replica, "route"), (backup, "hedge")):
            threading.Thread(
                target=_run, args=(info, hop), daemon=True
            ).start()
        last_err: Exception | None = None
        for _ in range(2):
            info, resp, err = results.get()
            self.registry.note_result(info.replica_id, ok=err is None)
            if err is None:
                if info.replica_id == backup.replica_id:
                    return dc_replace(
                        d, replica=backup, policy="hedge",
                        queue_depth=backup.queue_depth(),
                    ), resp
                return d, resp
            last_err = err
        raise last_err  # both lost the race

    # -- request plane -------------------------------------------------------
    def complete(
        self, body: dict[str, Any], force_replica: str | None = None
    ) -> dict[str, Any]:
        token_ids = self.tokenize(body)
        self._check_overload(force_replica, body)
        jid = self._new_journey(body)
        excluded: set[str] = set()
        attempt = 0
        while True:
            d = self.route(
                body, token_ids, force_replica=force_replica,
                exclude=excluded,
            )
            if d.replica.handle is None:
                raise RequestError(
                    f"replica {d.replica.replica_id} has no handle", 503
                )
            self._maybe_migrate(
                d, token_ids,
                reason="failover" if excluded else "misroute", jid=jid,
            )
            if not excluded:
                self._maybe_prefill_lane(d, body, token_ids, jid=jid)
            backup = self._pick_hedge_backup(d) if not excluded else None
            rid_name = d.replica.replica_id
            try:
                if backup is not None:
                    d, resp = self._hedged_complete(body, d, backup, jid=jid)
                else:
                    self._note_hop(jid, rid_name, "route", attempt=attempt)
                    t_leg = time.perf_counter()
                    try:
                        resp = d.replica.handle.chat_completion(
                            self._stamp_hop(body, jid, "route", rid_name)
                        )
                    finally:
                        if jid:
                            obs.FLEET_HOP_SECONDS.observe(
                                time.perf_counter() - t_leg, hop="route"
                            )
                    self.registry.note_result(rid_name, ok=True)
            except Exception as e:  # noqa: BLE001 - classified below
                if backup is None:
                    self.registry.note_result(rid_name, ok=False)
                if (
                    attempt < self.max_retries and _retryable(e)
                    and force_replica is None
                ):
                    attempt += 1
                    excluded.add(rid_name)
                    self._note_shape(jid, "retried")
                    obs.FLEET_RETRIES.inc()
                    obs.flight.record(
                        "fleet_retry", replica=rid_name, attempt=attempt,
                        error=str(e)[:200],
                        **({"request_id": jid} if jid else {}),
                    )
                    self._backoff(attempt)
                    continue
                obs.FLEET_REQUESTS.inc(outcome="error")
                obs.trace.mark_anomalous(jid, reason="fleet_error")
                raise
            rid = resp.get("id") if isinstance(resp, dict) else None
            self._record_decision(
                d, request_id=rid or jid, fanout_id=_fanout_of(body),
            )
            self._note_ownership(d, rid, jid)
            self._finish_journey(jid)
            obs.FLEET_REQUESTS.inc(outcome="completed")
            if isinstance(resp, dict):
                resp.setdefault("fleet", {})["replica"] = \
                    d.replica.replica_id
                resp["fleet"]["policy"] = d.policy
            return resp

    def complete_stream(
        self, body: dict[str, Any], force_replica: str | None = None
    ):
        """Generator of SSE chunk dicts routed to the chosen replica.

        Mid-stream failover: when the serving replica dies (transport
        error, injected disconnect, or an in-band error chunk from a
        dying engine), the request is re-submitted to a surviving
        replica and the client stream RESUMES from the last emitted
        character offset — greedy re-prefill regenerates the identical
        text, the already-delivered prefix is skipped, so the client
        sees no gap, no duplicate, and no error. Non-greedy streams only
        fail over before the first content chunk (a resampled
        continuation would splice two different generations)."""
        token_ids = self.tokenize(body)
        self._check_overload(force_replica, body)
        jid = self._new_journey(body)
        try:
            greedy = float(body.get("temperature") or 0.0) == 0.0
        except (TypeError, ValueError):
            greedy = False
        excluded: set[str] = set()
        failovers = 0
        emitted_chars = 0     # content chars delivered to the client
        sent_head = False     # role chunk already delivered
        while True:
            d = self.route(
                body, token_ids, force_replica=force_replica,
                exclude=excluded,
            )
            if d.replica.handle is None:
                raise RequestError(
                    f"replica {d.replica.replica_id} has no handle", 503
                )
            self._maybe_migrate(
                d, token_ids,
                reason="failover" if failovers else "misroute", jid=jid,
            )
            if failovers == 0:
                self._maybe_prefill_lane(d, body, token_ids, jid=jid)
            rid_name = d.replica.replica_id
            skip_chars = emitted_chars   # dedup on re-submit
            first = True
            hop_kind = "failover" if failovers else "stream"
            self._note_hop(jid, rid_name, hop_kind, failovers=failovers)
            t_leg = time.perf_counter()
            try:
                gen = d.replica.handle.chat_completion_stream(
                    self._stamp_hop(body, jid, hop_kind, rid_name)
                )
                for chunk in gen:
                    faults.maybe_raise(
                        "fleet.stream_disconnect",
                        ReplicaStreamBroken(
                            "injected mid-SSE disconnect"
                        ),
                        replica=rid_name,
                    )
                    if isinstance(chunk, dict) and "error" in chunk:
                        # In-band error chunk (the engine's scheduler
                        # failed the request mid-decode): to the client
                        # this replica is dead — fail over.
                        raise ReplicaStreamBroken(str(
                            chunk["error"].get("message", "stream error")
                        ))
                    if first:
                        req_id = chunk.get("id") \
                            if isinstance(chunk, dict) else None
                        self._record_decision(
                            d, request_id=req_id or jid,
                            fanout_id=_fanout_of(body),
                        )
                        self._note_ownership(d, req_id, jid)
                        first = False
                    content = _chunk_content(chunk)
                    if content:
                        if skip_chars >= len(content):
                            skip_chars -= len(content)
                            continue
                        if skip_chars > 0:
                            chunk = _trim_chunk_content(chunk, skip_chars)
                            content = content[skip_chars:]
                            skip_chars = 0
                        emitted_chars += len(content)
                        yield chunk
                        continue
                    if _is_head_chunk(chunk):
                        if sent_head:
                            continue
                        sent_head = True
                    yield chunk
                if jid:
                    obs.FLEET_HOP_SECONDS.observe(
                        time.perf_counter() - t_leg, hop=hop_kind
                    )
                self.registry.note_result(rid_name, ok=True)
                self._finish_journey(jid)
                obs.FLEET_REQUESTS.inc(outcome="completed")
                return
            except Exception as e:  # noqa: BLE001 - classified below
                if jid:
                    obs.FLEET_HOP_SECONDS.observe(
                        time.perf_counter() - t_leg, hop=hop_kind
                    )
                self.registry.note_result(rid_name, ok=False)
                resumable = greedy or emitted_chars == 0
                if (
                    failovers < self.max_failovers and _retryable(e)
                    and resumable and force_replica is None
                ):
                    failovers += 1
                    excluded.add(rid_name)
                    self._note_shape(jid, "failover")
                    obs.FLEET_FAILOVERS.inc()
                    # Tail-based retention: a failed-over journey is
                    # always investigation-worthy — pin its trace before
                    # the resumed leg even starts (the mark outlives the
                    # first leg's trace object).
                    obs.trace.mark_anomalous(jid, reason="failover")
                    obs.flight.record(
                        "failover", replica=rid_name,
                        failovers=failovers,
                        emitted_chars=emitted_chars,
                        error=str(e)[:200], session=d.session,
                        **({"request_id": jid} if jid else {}),
                    )
                    self._backoff(failovers)
                    continue
                obs.FLEET_REQUESTS.inc(outcome="error")
                obs.trace.mark_anomalous(jid, reason="fleet_error")
                raise

    # -- drain ----------------------------------------------------------------
    def drain(self, replica_id: str) -> dict[str, Any]:
        """Graceful drain: stop admitting to ``replica_id``, park its
        running sessions, migrate them (KV pages + salvaged requests) to
        the rest of the fleet, then deregister it. In-process replicas
        hand their live Request objects across schedulers, so clients
        (including streams) never see an error; HTTP replicas stop
        admitting and migrate lazily (see HttpReplica.drain_sessions)."""
        info = self.registry.get(replica_id)
        if info is None:
            raise RequestError(f"unknown replica {replica_id!r}", 404)
        self.registry.set_draining(replica_id, True)
        obs.flight.record("replica_drain", replica=replica_id, phase="enter")
        moved = 0
        errors = 0
        reqs: list[Any] = []
        if info.handle is not None:
            try:
                reqs = info.handle.drain_sessions()
            except Exception:  # noqa: BLE001
                log.exception("drain_sessions failed on %s", replica_id)
                errors += 1
        targets = [
            c for c in self.registry.alive(role="decode")
            if c.replica_id != replica_id and c.handle is not None
        ]
        for req in reqs:
            if not targets:
                # Nowhere to go: the request stays queued on the drained
                # replica's scheduler? No — the scheduler is stopped.
                # Fail it loudly rather than hanging the client forever.
                req.error = "fleet drain found no target replica"
                req.done.set()
                errors += 1
                continue
            dst = min(targets, key=lambda c: c.load_score())
            migrate_chain(
                info.handle, dst.handle, list(req.prompt_ids),
                reason="drain", park=False,
            )
            dst.handle.submit_request(req)
            moved += 1
        with self._lock:
            stale = [k for k, v in self._pins.items() if v == replica_id]
            for k in stale:
                del self._pins[k]
        self.registry.deregister(replica_id)
        obs.flight.record(
            "replica_drain", replica=replica_id, phase="exit",
            migrated=moved, errors=errors,
        )
        return {
            "replica": replica_id, "migrated_sessions": moved,
            "errors": errors,
        }

    # -- observability plane ---------------------------------------------------
    def owner_of(self, request_id: str) -> str | None:
        """Final serving replica of a tracked request — the last
        request-plane hop's replica. The full replica set lives in
        participants_of; this keeps the old owner-map contract for
        bench/operator callers."""
        with self._lock:
            rec = self._participants.get(request_id)
            if rec is None:
                return None
            for h in reversed(rec["hops"]):
                if h.get("hop") in ("route", "stream", "failover", "hedge"):
                    return h.get("replica") or None
            return rec["replicas"][-1] if rec["replicas"] else None

    def _timeline_single(self, request_id: str) -> dict[str, Any] | None:
        """Single-replica pass-through: forward to the owning replica so
        ``opsagent timeline`` / GET /api/timeline work through the
        router instead of 404ing. Unknown owners fall back to asking
        every live replica (the id may predate a router restart)."""
        rid = self.owner_of(request_id)
        candidates = []
        if rid is not None:
            info = self.registry.get(rid)
            if info is not None:
                candidates.append(info)
        if not candidates:
            candidates = self.registry.alive(admitting=False)
        for info in candidates:
            if info.handle is None:
                continue
            try:
                tl = info.handle.timeline(request_id)
            except Exception:  # noqa: BLE001 - try the next replica
                continue
            if tl is not None:
                tl["replica"] = info.replica_id
                return tl
        return None

    def timeline(self, request_id: str) -> dict[str, Any] | None:
        """Fleet-scope timeline: ask EVERY replica the participants map
        recorded for the journey, stitch their per-replica timelines
        (skew-corrected by the heartbeat clock-offset estimates) with
        the router-side routing/failover/hedge/fault-in windows into one
        multi-lane view. Requests without a journey record (journeys
        off, or pre-restart ids) degrade to the single-replica
        pass-through; reaped participants degrade to the survivors."""
        rec = self.participants_of(request_id)
        if rec is None:
            return self._timeline_single(request_id)
        sources: dict[str, dict[str, Any]] = {}
        reaped: list[str] = []
        shared_done = False
        for rid in rec["replicas"] or [None]:
            if rid is None:
                break
            info = self.registry.get(rid)
            if info is None or info.handle is None:
                reaped.append(rid)
                continue
            if isinstance(info.handle, LocalReplica):
                # In-process replicas share one trace store: assemble
                # the journey trace once under the shared lane and let
                # the stitcher attribute segments to replica lanes via
                # the trace's fleet legs.
                if not shared_done:
                    tl = obs.timeline.assemble(request_id)
                    if tl is not None:
                        sources["_shared"] = tl
                    shared_done = True
                continue
            try:
                tl = info.handle.timeline(request_id)
            except Exception:  # noqa: BLE001 - participant unreachable
                reaped.append(rid)
                continue
            if tl is not None:
                sources[rid] = tl
        if not sources:
            return self._timeline_single(request_id)
        try:
            events = self.fleet_flight(
                n=0, request_id=request_id
            ).get("events", [])
        except Exception:  # noqa: BLE001 - events only enrich windows
            events = []
        out = obs.timeline.stitch_fleet(
            request_id, sources, journey=rec,
            offsets=self.registry.clock_offsets(),
            reaped=reaped, events=events,
        )
        # Single-replica callers keep reading tl["replica"]: the final
        # serving replica (the full set is in tl["replicas"]).
        out["replica"] = self.owner_of(request_id) or ""
        return out

    def fleet_flight(
        self, n: int = 256, kind: str = "", request_id: str = "",
    ) -> dict[str, Any]:
        """GET /api/fleet/flight: every replica's flight ring merged
        into one replica-tagged, skew-corrected, time-ordered ledger.
        The router's own process ring is included once (it also covers
        all in-process replicas — they share it); remote replicas are
        polled over HTTP. ``request_id`` filters to one journey's
        events; ``n`` caps the merged tail (0 = no cap)."""
        offsets = self.registry.clock_offsets()
        merged: list[dict[str, Any]] = []
        replicas: list[str] = []

        def _ingest(evs: list[dict[str, Any]], src: str, off: float):
            for e in evs:
                if not isinstance(e, dict):
                    continue
                if request_id and e.get("request_id") != request_id:
                    continue
                e = dict(e)
                e.setdefault("replica", src)
                e["source"] = src
                if "wall" in e:
                    e["wall_corrected"] = e["wall"] - off
                merged.append(e)

        _ingest(
            obs.flight.get_recorder().snapshot(kind=kind or None),
            "router", 0.0,
        )
        for info in self.registry.alive(admitting=False):
            replicas.append(info.replica_id)
            if info.handle is None or isinstance(info.handle, LocalReplica):
                continue   # local replicas share the router's ring
            try:
                evs = info.handle.flight(
                    n=2048 if request_id else n, kind=kind
                )
            except Exception:  # noqa: BLE001 - degrade to survivors
                continue
            _ingest(evs, info.replica_id, offsets.get(info.replica_id, 0.0))
        merged.sort(
            key=lambda e: e.get("wall_corrected", e.get("wall", 0.0))
        )
        if n and not request_id:
            merged = merged[-n:]
        return {
            "replicas": replicas,
            "clock_offset_s": offsets,
            "events": merged,
        }

    def slo_aggregate(self) -> dict[str, Any]:
        """Fleet-wide /api/slo: every replica's verdicts concatenated
        (names prefixed with the replica id) in the standard shape, so
        ``opsagent slo-check --url <router>`` gates the whole fleet —
        one breached replica breaches the fleet."""
        slos: list[dict[str, Any]] = []
        replicas = 0
        # Per-class accounting starts from THIS process's report (sheds
        # are classified here; in-process replicas share this registry,
        # so their completions are already in it) and folds in each
        # REMOTE replica's classes — remote completions are classified
        # in the replica process, never here. LocalReplica handles are
        # skipped in the fold: their slo() reads the same process-wide
        # registry and would double-count.
        remote_classes: list[list[dict[str, Any]]] = []
        for info in self.registry.alive(admitting=False):
            if info.handle is None:
                continue
            try:
                verdicts = info.handle.slo()
            except Exception:  # noqa: BLE001 - unreachable replica
                slos.append({
                    "name": f"{info.replica_id}:reachable",
                    "pass": False, "unit": "",
                })
                continue
            replicas += 1
            for v in verdicts.get("slos", []):
                v = dict(v)
                v["name"] = f"{info.replica_id}:{v.get('name', '?')}"
                slos.append(v)
            if not isinstance(info.handle, LocalReplica):
                remote_classes.append(verdicts.get("classes") or [])
        budget = obs.slo._env_float(obs.slo._ENV_ERR, 0.01)
        classes = _merge_class_reports(
            [obs.slo.get_watchdog().class_report()] + remote_classes,
            budget,
        )
        return {
            "slos": slos,
            "classes": classes,
            "error_budget": budget,
            "fleet": {"replicas": replicas},
        }

    def metrics_history(
        self, series: list[str] | None = None, since: float = 300.0,
        step: float | None = None,
    ) -> dict[str, Any]:
        """Fleet-wide /api/metrics/history: the router process's own
        history store (shared by every in-process replica — same
        registry, same sampler) plus each remote replica's store with
        series prefixed ``{replica_id}:`` and timestamps skew-corrected
        into the router's clock via the registry's ClockSync offsets —
        the same ``wall - offset`` convention as the fleet flight
        ledger, so stitched timelines and history lines agree."""
        local = obs.history.query(series=series, since=since, step=step)
        out_series: dict[str, Any] = dict(local["series"])
        offsets = self.registry.clock_offsets()
        replicas: list[str] = []
        for info in self.registry.alive(admitting=False):
            replicas.append(info.replica_id)
            if info.handle is None or isinstance(info.handle, LocalReplica):
                continue   # local replicas share the router's store
            try:
                remote = info.handle.history(
                    series=series, since=since, step=step
                )
            except Exception:  # noqa: BLE001 - degrade to survivors
                continue
            off = offsets.get(info.replica_id, 0.0)
            for name, s in remote.get("series", {}).items():
                out_series[f"{info.replica_id}:{name}"] = {
                    "kind": s.get("kind", "gauge"),
                    "points": [
                        [p[0] - off, p[1]]
                        for p in s.get("points", [])
                        if isinstance(p, (list, tuple)) and len(p) == 2
                    ],
                }
        return {
            "now": local["now"],
            "since": since,
            "step": step,
            "tiers": local["tiers"],
            "replicas": replicas,
            "clock_offset_s": offsets,
            "series": out_series,
        }

    def fleet_snapshot(self) -> dict[str, Any]:
        """GET /api/fleet: the registry view plus a per-replica SLO
        rollup and the router's session/ownership footprint."""
        self.registry.refresh_local()
        snap = self.registry.snapshot()
        for row in snap["replicas"]:
            info = self.registry.get(row["id"])
            if info is None or info.handle is None:
                continue
            try:
                verdicts = info.handle.slo().get("slos", [])
                row["slo"] = {
                    "pass": all(
                        v.get("pass") is not False for v in verdicts
                    ),
                    "slos": verdicts,
                }
            except Exception:  # noqa: BLE001
                row["slo"] = {"pass": None, "error": "unreachable"}
        with self._lock:
            snap["pinned_sessions"] = len(self._pins)
            snap["tracked_requests"] = len(self._participants)
        return snap

    def bench_rows(self) -> list[dict[str, Any]]:
        """GET /api/fleet/bench: bench-result-shaped rows assembled from
        each replica's live SLO values, so ``opsagent perf-check --url
        <router>`` (and scripts/perf_gate.py) can gate a running fleet
        with the same machinery that gates bench jsonl files."""
        rows: list[dict[str, Any]] = []
        for info in self.registry.alive(admitting=False):
            if info.handle is None:
                continue
            try:
                verdicts = info.handle.slo().get("slos", [])
            except Exception:  # noqa: BLE001
                continue
            for v in verdicts:
                val = v.get("value")
                if val is None:
                    continue
                unit = (v.get("unit") or "").lower()
                if unit not in ("ms", "s", "seconds") and "/s" not in unit:
                    # perf-check derives better/worse direction from the
                    # unit; a ratio row (error_rate) would gate backwards.
                    continue
                rows.append({
                    "metric": (
                        f"fleet_{v.get('name', '?')}[{info.replica_id}]"
                    ),
                    "value": val,
                    "unit": v.get("unit", ""),
                })
        return rows


# -- HTTP front-end -----------------------------------------------------------
def build_router_app(router: FleetRouter):
    """The router's aiohttp application: the engine-server API plus the
    fleet control endpoints (register/heartbeat/deregister/drain) and the
    fleet observability rollup."""
    import asyncio

    from aiohttp import web

    def _exec(fn, *args):
        return asyncio.get_running_loop().run_in_executor(
            None, fn, *args
        )

    async def completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        if not body.get("messages"):
            return web.json_response(
                {"error": {"message": "messages is required"}}, status=400
            )
        force = request.query.get("replica") or None
        loop = asyncio.get_running_loop()

        def _err_response(e: Exception) -> web.Response:
            status = e.status if isinstance(e, RequestError) else 500
            headers = {}
            retry_after = getattr(e, "retry_after_s", None)
            if status == 429 and retry_after is not None:
                headers["Retry-After"] = str(int(retry_after))
            return web.json_response(
                {"error": {"message": str(e), "type": type(e).__name__}},
                status=status, headers=headers,
            )

        if body.get("stream"):
            gen = router.complete_stream(body, force_replica=force)
            try:
                first = await loop.run_in_executor(
                    None, lambda: next(gen, None)
                )
            except Exception as e:  # noqa: BLE001
                return _err_response(e)
            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            })
            await resp.prepare(request)
            chunk = first
            try:
                while chunk is not None:
                    await resp.write(
                        b"data: " + json.dumps(chunk).encode("utf-8")
                        + b"\n\n"
                    )
                    chunk = await loop.run_in_executor(
                        None, lambda: next(gen, None)
                    )
            except Exception as e:  # noqa: BLE001 - headers already sent
                err = {"error": {"message": str(e)}}
                await resp.write(
                    b"data: " + json.dumps(err).encode("utf-8") + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        try:
            out = await loop.run_in_executor(
                None, lambda: router.complete(body, force_replica=force)
            )
        except Exception as e:  # noqa: BLE001
            return _err_response(e)
        return web.json_response(out)

    async def models(request: web.Request) -> web.Response:
        seen = []
        for info in router.registry.alive(admitting=False):
            if info.model and info.model not in seen:
                seen.append(info.model)
        return web.json_response({
            "object": "list",
            "data": [
                {"id": m, "object": "model", "owned_by": "opsagent-fleet"}
                for m in seen
            ],
        })

    async def healthz(request: web.Request) -> web.Response:
        router.registry.refresh_local()
        replicas = router.registry.all()
        out = {
            "status": "ok" if any(
                not r.draining for r in replicas
            ) else "no_replicas",
            "role": "router",
            "replicas": len(replicas),
            "draining": sum(1 for r in replicas if r.draining),
            "prefill_lanes": sum(
                1 for r in replicas if r.role == "prefill"
            ),
            "health": router.registry.health_snapshot(clock=True),
            "queued": sum(r.queue_depth() for r in replicas),
            "shed_queue_depth": router.shed_queue_depth,
            "directory": router.registry.directory.stats(),
        }
        if router.autoscaler is not None:
            out["autoscale"] = router.autoscaler.snapshot()
        if faults.active():
            out["faults"] = faults.summary()
        return web.json_response(out)

    async def metrics(request: web.Request) -> web.Response:
        return web.Response(
            text=obs.metrics_text(), content_type="text/plain",
            charset="utf-8",
        )

    async def fleet_get(request: web.Request) -> web.Response:
        return web.json_response(
            await _exec(router.fleet_snapshot)
        )

    async def fleet_bench(request: web.Request) -> web.Response:
        return web.json_response(await _exec(router.bench_rows))

    async def slo_get(request: web.Request) -> web.Response:
        return web.json_response(await _exec(router.slo_aggregate))

    async def history_get(request: web.Request) -> web.Response:
        # GET /api/metrics/history — fleet-aggregated telemetry history:
        # router-process series plus replica-prefixed remote series,
        # timestamps skew-corrected via the registry's clock offsets.
        try:
            kwargs = obs.history.parse_query(request.query)
        except ValueError as e:
            return web.json_response(
                {"error": {"message": f"bad query: {e}"}}, status=400
            )
        return web.json_response(await _exec(
            lambda: router.metrics_history(**kwargs)
        ))

    async def timeline_get(request: web.Request) -> web.Response:
        tl = await _exec(
            router.timeline, request.match_info["request_id"]
        )
        if tl is None:
            return web.json_response(
                {"error": {"message": "unknown request_id"}}, status=404
            )
        return web.json_response(tl)

    async def register(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        rid = body.get("replica_id") or ""
        url = body.get("url") or ""
        if not rid or not url:
            return web.json_response(
                {"error": {"message": "replica_id and url are required"}},
                status=400,
            )
        info = ReplicaInfo(
            replica_id=rid,
            model=body.get("model", ""),
            url=url,
            role=body.get("role", "decode"),
            capacity=int(body.get("capacity", 8)),
            page_size=int(body.get("page_size", 64)),
            mesh=dict(body.get("mesh") or {}),
            digests=set(body.get("digests") or ()),
            digest_truncated=bool(body.get("digest_truncated", False)),
            load=dict(body.get("load") or {}),
            handle=HttpReplica(url, rid),
        )
        router.registry.register(info)
        return web.json_response({
            "status": "registered", "replica_id": rid,
            "heartbeat_ttl_s": router.registry.ttl_s,
            # Echoed back on the next heartbeat so the registry can
            # estimate this replica's clock offset and RTT.
            "router_ts": time.time(),
        })

    async def heartbeat(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        ok = router.registry.heartbeat(
            body.get("replica_id", ""),
            load=body.get("load"),
            digests=body.get("digests"),
            digest_truncated=body.get("digest_truncated"),
            replica_ts=body.get("replica_ts"),
            echo_router_ts=body.get("echo_router_ts"),
            echo_held_s=body.get("echo_held_s"),
        )
        if not ok:
            # 410: the replica was reaped (or the router restarted) — it
            # should POST /fleet/register again.
            return web.json_response(
                {"error": {"message": "unknown replica; re-register"}},
                status=410,
            )
        return web.json_response({"status": "ok", "router_ts": time.time()})

    async def directory_lookup(request: web.Request) -> web.Response:
        # Fleet-global KV: a replica that missed locally asks which
        # peers own these chain keys. Owners come back WITH their
        # advertised URLs so the replica fetches peer-to-peer — page
        # payloads never transit the router.
        try:
            body = await request.json()
            keys = [str(k) for k in body.get("keys") or []]
        except (json.JSONDecodeError, TypeError):
            return web.json_response(
                {"error": {"message": "keys must be a string list"}},
                status=400,
            )

        def _lookup() -> dict[str, Any]:
            asking = request.query.get("replica") or ""
            out: dict[str, list[dict[str, str]]] = {}
            for key, rids in router.registry.directory.owners(
                keys
            ).items():
                owners = []
                for rid in rids:
                    if rid == asking:
                        continue
                    info = router.registry.get(rid)
                    if info is None or info.draining:
                        continue
                    owners.append({"id": rid, "url": info.url})
                if owners:
                    out[key] = owners
            return {"owners": out}

        return web.json_response(await _exec(_lookup))

    async def directory_get(request: web.Request) -> web.Response:
        # Operator view (``opsagent fleet-kv``): the directory rows plus
        # each replica's tier footprint (trie+pool digest count, host
        # pool size, truncation).
        def _snap() -> dict[str, Any]:
            router.registry.refresh_local()
            try:
                limit = int(request.query.get("limit", 256))
            except ValueError:
                limit = 256
            snap = router.registry.directory.snapshot(limit=limit)
            snap["replicas"] = [
                {
                    "id": info.replica_id,
                    "role": info.role,
                    "state": (
                        "draining" if info.draining else "active"
                    ),
                    "digest_count": len(info.digests),
                    "digest_truncated": info.digest_truncated,
                    "host_pool_pages": info.load.get(
                        "host_pool_pages", 0
                    ),
                    "heartbeat_age_s": round(
                        time.monotonic() - info.last_heartbeat, 3
                    ),
                }
                for info in router.registry.all()
            ]
            return snap

        return web.json_response(await _exec(_snap))

    async def fleet_flight_get(request: web.Request) -> web.Response:
        try:
            n = int(request.query.get("n", 256))
        except ValueError:
            n = 256
        kind = request.query.get("kind", "")
        rid = request.query.get("request_id", "")
        return web.json_response(await _exec(
            lambda: router.fleet_flight(n=n, kind=kind, request_id=rid)
        ))

    async def deregister(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        router.registry.deregister(body.get("replica_id", ""))
        return web.json_response({"status": "ok"})

    async def drain(request: web.Request) -> web.Response:
        rid = request.match_info["replica_id"]
        try:
            out = await _exec(router.drain, rid)
        except RequestError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=e.status
            )
        return web.json_response(out)

    from aiohttp import web as _web

    app = _web.Application(client_max_size=256 * 1024 * 1024)
    app.router.add_post("/v1/chat/completions", completions)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/api/slo", slo_get)
    app.router.add_get("/api/metrics/history", history_get)
    app.router.add_get("/api/fleet", fleet_get)
    app.router.add_get("/api/fleet/bench", fleet_bench)
    app.router.add_get("/api/fleet/directory", directory_get)
    app.router.add_get("/api/fleet/flight", fleet_flight_get)
    app.router.add_get("/api/timeline/{request_id}", timeline_get)
    app.router.add_post("/fleet/register", register)
    app.router.add_post("/fleet/heartbeat", heartbeat)
    app.router.add_post("/fleet/directory/lookup", directory_lookup)
    app.router.add_post("/fleet/deregister", deregister)
    app.router.add_post("/fleet/drain/{replica_id}", drain)
    return app


def run_router_server(
    host: str = "0.0.0.0",
    port: int = 8090,
    tokenizer: str = "",
    model_name: str = "",
    affinity: bool = True,
    queue_spill: int | None = None,
    prefill_threshold: int = DEFAULT_PREFILL_THRESHOLD,
    heartbeat_ttl_s: float | None = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
    hedge_queue_depth: int | None = None,
    shed_queue_depth: int | None = None,
    autoscale_snapshot: str = "",
    autoscale_max_replicas: int = 4,
    autoscale_port_base: int = 8400,
    autoscale_cooldown_s: float = 30.0,
) -> None:
    """``opsagent serve-router``: the fleet front-end as a process. The
    tokenizer (HF path, or the hermetic byte tokenizer by default) must
    match the replicas' — affinity scores hash token chains, so a
    mismatched tokenizer silently zeroes every score (placement then
    degrades to least-loaded, which is correct but cold).

    ``autoscale_snapshot`` (an ``opsagent snapshot create`` directory)
    turns on elastic scale-out: shed pressure launches standby replicas
    from the snapshot as local subprocesses and promotes them into the
    decode set once request-ready."""
    from aiohttp import web

    from ..tokenizer import load_tokenizer

    router = FleetRouter(
        registry=ReplicaRegistry(ttl_s=heartbeat_ttl_s),
        affinity=affinity,
        queue_spill=queue_spill,
        prefill_threshold=prefill_threshold,
        tokenizer=load_tokenizer(tokenizer),
        model_family=model_name,
        max_retries=max_retries,
        hedge_queue_depth=hedge_queue_depth,
        shed_queue_depth=shed_queue_depth,
    )
    scaler = None
    if autoscale_snapshot:
        from .autoscale import Autoscaler, SubprocessLauncher

        launcher = SubprocessLauncher(
            snapshot_path=autoscale_snapshot,
            router_url=f"http://127.0.0.1:{port}",
            port_base=autoscale_port_base,
        )
        scaler = Autoscaler(
            router, launcher,
            max_replicas=autoscale_max_replicas,
            cooldown_s=autoscale_cooldown_s,
        )
        router.autoscaler = scaler
        scaler.start()
    app = build_router_app(router)
    # Telemetry time machine: the router samples its own process series
    # (fleet shed/hedge/failover rates ride here) at 1 Hz behind
    # /api/metrics/history.
    obs.history.get_history().start()

    async def _announce(_) -> None:
        log.info("fleet router listening on %s:%d", host, port)

    async def _shutdown(_) -> None:
        if scaler is not None:
            scaler.stop()

    app.on_startup.append(_announce)
    app.on_shutdown.append(_shutdown)
    web.run_app(app, host=host, port=port, print=None)
