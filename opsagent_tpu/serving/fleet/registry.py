"""Replica registry: the control-plane state the fleet router places
against.

Engine replicas self-register with capacity, mesh shape, role
(decode/prefill), page geometry, and a compact prefix-trie digest (hex
chain keys of their HBM-trie + host-pool resident page chains —
Engine.prefix_digests). Registrations stay alive through heartbeats that
refresh load + digests; a replica that stops heartbeating past the
liveness TTL is reaped (its pinned sessions re-route by affinity on their
next turn — the transferred/cached pages are gone, so they re-prefill,
which is correct and merely slow). In-process replicas (LocalReplica)
mark themselves ``local`` and are polled live instead of push-heartbeated.

Affinity scoring: ``prompt_chain_keys`` digests a prompt's page-aligned
prefixes with the SAME chain-key function the host pool and HBM trie
advertisement use, and ``affinity_pages`` counts consecutive leading keys
a replica holds — longest-cached-prefix wins, in pages.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ... import obs
from ...utils.logger import get_logger
from ..offload.pool import chain_key_hex
from .pagestore import PageDirectory

log = get_logger("fleet.registry")

ENV_HEARTBEAT_TTL = "OPSAGENT_FLEET_HEARTBEAT_TTL_S"
DEFAULT_HEARTBEAT_TTL_S = 10.0

ENV_EJECT_COOLDOWN = "OPSAGENT_FLEET_EJECT_COOLDOWN_S"
DEFAULT_EJECT_COOLDOWN_S = 2.0
EJECT_COOLDOWN_MAX_S = 30.0
EJECT_AFTER_FAILURES = 3      # consecutive call failures -> ejected
PROBE_TIMEOUT_S = 30.0        # a half-open probe that never reports back


def heartbeat_ttl_s(override: float | None = None) -> float:
    if override is not None and override > 0:
        return float(override)
    try:
        v = float(os.environ.get(ENV_HEARTBEAT_TTL, ""))
        if v > 0:
            return v
    except ValueError:
        pass
    return DEFAULT_HEARTBEAT_TTL_S


def eject_cooldown_s(override: float | None = None) -> float:
    if override is not None and override > 0:
        return float(override)
    try:
        v = float(os.environ.get(ENV_EJECT_COOLDOWN, ""))
        if v > 0:
            return v
    except ValueError:
        pass
    return DEFAULT_EJECT_COOLDOWN_S


@dataclass
class ReplicaHealth:
    """Circuit-breaker state for one replica, fed by router call
    outcomes (``ReplicaRegistry.note_result``) and heartbeat staleness:

        healthy --failure--> suspect --(3 consecutive)--> ejected
        ejected --cooldown--> half-open probe --success--> healthy
                                              --failure--> ejected
                                                (cooldown doubles, capped)

    Ejected replicas are excluded from admitting ``alive()`` reads until
    the cooldown elapses; then ONE in-flight probe request is admitted
    (``begin_probe``) and its outcome decides."""

    state: str = "healthy"            # healthy | suspect | ejected
    consecutive_failures: int = 0
    ejections: int = 0                # lifetime; drives cooldown backoff
    ejected_until: float = 0.0        # monotonic deadline
    probe_started: float = 0.0        # 0 = no half-open probe in flight

    def admitting(self, now: float) -> bool:
        if self.state != "ejected":
            return True
        if now < self.ejected_until:
            return False
        # Half-open: admit only while no (live) probe is in flight.
        return (
            self.probe_started == 0.0
            or now - self.probe_started > PROBE_TIMEOUT_S
        )


CLOCK_EWMA_ALPHA = 0.3            # weight of the newest heartbeat sample


@dataclass
class ClockSync:
    """Per-replica clock model from heartbeat timestamp echoes.

    Each heartbeat response carries the router's wall clock at send
    (``router_ts``); the replica echoes it on its NEXT beat together
    with how long it held it (``echo_held_s``, measured on the
    replica's monotonic clock) and its own wall clock at send
    (``replica_ts``). On receipt at router wall time ``now``:

        rtt    = (now - echo_router_ts) - echo_held_s
        offset = replica_ts - (now - rtt / 2)

    ``offset_s`` is the replica's wall clock MINUS the router's — the
    correction the fleet timeline stitcher subtracts from a replica's
    segment timestamps. Both estimates are EWMAs so one delayed beat
    cannot yank the fleet's time axis."""

    offset_s: float = 0.0
    rtt_s: float = 0.0
    samples: int = 0

    def update(self, offset_s: float, rtt_s: float) -> None:
        if self.samples == 0:
            self.offset_s, self.rtt_s = offset_s, rtt_s
        else:
            a = CLOCK_EWMA_ALPHA
            self.offset_s += a * (offset_s - self.offset_s)
            self.rtt_s += a * (rtt_s - self.rtt_s)
        self.samples += 1


def prompt_chain_keys(token_ids: list[int], page_size: int) -> list[str]:
    """Hex chain keys of every page-aligned prefix of ``token_ids[:-1]``
    (minus the last token, mirroring admission's match_prefix: at least
    one tail token always prefills to produce next-token logits)."""
    if page_size <= 0 or len(token_ids) < 2:
        return []
    usable = token_ids[: len(token_ids) - 1]
    return [
        chain_key_hex(usable[: (i + 1) * page_size])
        for i in range(len(usable) // page_size)
    ]


@dataclass
class ReplicaInfo:
    replica_id: str
    model: str = ""
    url: str = ""                  # "" for in-process handles
    role: str = "decode"           # "decode" | "prefill"
    capacity: int = 8              # max concurrent sessions (batch size)
    page_size: int = 64
    mesh: dict[str, int] = field(default_factory=dict)   # tp/sp/ep shape
    digests: set[str] = field(default_factory=set)
    digest_truncated: bool = False   # advertisement hit the digest cap
    load: dict[str, Any] = field(default_factory=dict)
    draining: bool = False
    local: bool = False            # polled live; heartbeat TTL waived
    last_heartbeat: float = field(default_factory=time.monotonic)
    handle: Any = None             # ReplicaHandle (router.py)

    def affinity_pages(self, keys: list[str]) -> int:
        """Consecutive leading prompt chain keys this replica holds —
        the longest-cached-prefix score, in pages."""
        n = 0
        for k in keys:
            if k not in self.digests:
                break
            n += 1
        return n

    def queue_depth(self) -> int:
        return int(self.load.get("queued", 0)) + int(
            self.load.get("prefilling", 0)
        )

    def load_score(self) -> float:
        """Least-loaded ordering key: running+queued sessions normalized
        by capacity, with the goodput fraction (decode-active share of
        recent wall time, from the replica's attribution snapshot) as a
        tiebreak — a replica whose wall clock is mostly queued/tool-
        blocked time scores as busier than its occupancy suggests."""
        occupied = int(self.load.get("running", 0)) + self.queue_depth()
        score = occupied / max(1, self.capacity)
        gp = self.load.get("goodput", {})
        try:
            busy = float(gp.get("queued", 0.0))
            active = float(gp.get("decode_active", 0.0))
            if busy + active > 0:
                score += busy / (busy + active) * 0.5
        except (TypeError, ValueError, AttributeError):
            pass
        return score

    def snapshot(self) -> dict[str, Any]:
        return {
            "id": self.replica_id,
            "model": self.model,
            "url": self.url,
            "role": self.role,
            "capacity": self.capacity,
            "page_size": self.page_size,
            "mesh": dict(self.mesh),
            "state": "draining" if self.draining else "active",
            "local": self.local,
            "digest_count": len(self.digests),
            "digest_truncated": self.digest_truncated,
            "load": dict(self.load),
            "heartbeat_age_s": round(
                time.monotonic() - self.last_heartbeat, 3
            ),
        }


class ReplicaRegistry:
    def __init__(
        self,
        ttl_s: float | None = None,
        eject_cooldown: float | None = None,
    ):
        self.ttl_s = heartbeat_ttl_s(ttl_s)
        self.eject_cooldown_s = eject_cooldown_s(eject_cooldown)
        self._lock = threading.Lock()
        self._replicas: dict[str, ReplicaInfo] = {}
        self._health: dict[str, ReplicaHealth] = {}
        self._clocks: dict[str, ClockSync] = {}
        self.reaped = 0
        # Fleet-global KV directory: chain_key_hex -> owning replicas,
        # kept in lockstep with the digest advertisements above.
        self.directory = PageDirectory()

    # -- membership --------------------------------------------------------
    def register(self, info: ReplicaInfo) -> None:
        with self._lock:
            info.last_heartbeat = time.monotonic()
            self._replicas[info.replica_id] = info
            # A (re-)registration is a fresh process (or an operator's
            # explicit rejoin): start from a clean health slate.
            self._health[info.replica_id] = ReplicaHealth()
            # In-process replicas share the router's clock: their offset
            # is identically zero, no echo protocol needed.
            self._clocks[info.replica_id] = (
                ClockSync(0.0, 0.0, 1) if info.local else ClockSync()
            )
        obs.FLEET_CLOCK_SKEW.set(0.0, replica=info.replica_id)
        self.directory.update(info.replica_id, info.digests)
        log.info(
            "replica %s registered (role=%s model=%s url=%s capacity=%d "
            "digests=%d)", info.replica_id, info.role, info.model,
            info.url or "<in-process>", info.capacity, len(info.digests),
        )
        self._observe()

    def heartbeat(
        self,
        replica_id: str,
        load: dict[str, Any] | None = None,
        digests: list[str] | None = None,
        digest_truncated: bool | None = None,
        replica_ts: float | None = None,
        echo_router_ts: float | None = None,
        echo_held_s: float | None = None,
    ) -> bool:
        """Refresh liveness (+ optionally load/digests). Returns False
        for unknown ids — the replica should re-register (it was reaped
        or the router restarted).

        ``replica_ts`` / ``echo_router_ts`` / ``echo_held_s`` are the
        clock-sync echo: the replica's wall clock at send, the router
        timestamp from the PREVIOUS heartbeat response, and how long
        the replica held it (monotonic). Together they yield one RTT +
        clock-offset sample (see ClockSync)."""
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is None:
                return False
            info.last_heartbeat = time.monotonic()
            if (
                replica_ts is not None
                and echo_router_ts is not None
                and echo_held_s is not None
                and not info.local
            ):
                now = time.time()
                rtt = max(0.0, (now - echo_router_ts) - echo_held_s)
                offset = replica_ts - (now - rtt / 2.0)
                clock = self._clocks.setdefault(replica_id, ClockSync())
                clock.update(offset, rtt)
                obs.FLEET_CLOCK_SKEW.set(
                    clock.offset_s, replica=replica_id
                )
            if load is not None:
                info.load = dict(load)
            if digests is not None:
                info.digests = set(digests)
            if digest_truncated is not None:
                info.digest_truncated = bool(digest_truncated)
            draining = info.draining
        if digests is not None:
            # Draining replicas are already directory-invisible; keep
            # them out even if late heartbeats still advertise chains.
            self.directory.update(
                replica_id, () if draining else digests
            )
        return True

    def deregister(self, replica_id: str) -> bool:
        with self._lock:
            gone = self._replicas.pop(replica_id, None)
            self._health.pop(replica_id, None)
            self._clocks.pop(replica_id, None)
        obs.FLEET_CLOCK_SKEW.set(0.0, replica=replica_id)
        self.directory.remove_replica(replica_id)
        if gone is not None:
            log.info("replica %s deregistered", replica_id)
            self._observe()
        return gone is not None

    def set_draining(self, replica_id: str, draining: bool = True) -> bool:
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is None:
                return False
            info.draining = draining
            digests = set(info.digests)
        if draining:
            # A draining replica is about to migrate its chains away
            # and exit — stop advertising it as a fault-in source.
            self.directory.remove_replica(replica_id)
        else:
            self.directory.update(replica_id, digests)
        self._observe()
        return True

    def set_role(self, replica_id: str, role: str) -> bool:
        """Reassign a replica's role router-side. The autoscaler uses
        this to promote ``standby`` replicas (request-ready but not yet
        routable — ``route()`` only considers ``decode``) into the
        decode set. Heartbeats never carry role, so the change sticks
        until the replica fully re-registers."""
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is None:
                return False
            old = info.role
            info.role = role
        if old != role:
            log.info("replica %s role %s -> %s", replica_id, old, role)
        self._observe()
        return True

    # -- reads -------------------------------------------------------------
    def get(self, replica_id: str) -> ReplicaInfo | None:
        with self._lock:
            return self._replicas.get(replica_id)

    def reap(self) -> list[str]:
        """Drop replicas whose heartbeat is stale past the TTL (local
        handles are polled live and never reaped). Returns reaped ids."""
        now = time.monotonic()
        dead: list[str] = []
        with self._lock:
            for rid, info in list(self._replicas.items()):
                if info.local:
                    continue
                if now - info.last_heartbeat > self.ttl_s:
                    dead.append(rid)
                    del self._replicas[rid]
                    self._health.pop(rid, None)
                    self._clocks.pop(rid, None)
        for rid in dead:
            self.reaped += 1
            self.directory.remove_replica(rid)
            log.warning(
                "replica %s reaped (no heartbeat for > %.1fs)",
                rid, self.ttl_s,
            )
            obs.flight.record("replica_reaped", replica=rid)
        if dead:
            self._observe()
        return dead

    def refresh_local(self) -> None:
        """Poll in-process handles for live load + digests (their
        heartbeat equivalent; cheap — engine-lock reads only)."""
        with self._lock:
            locals_ = [i for i in self._replicas.values() if i.local]
        for info in locals_:
            if info.handle is None:
                continue
            try:
                info.load = info.handle.load_snapshot()
                info.digests = set(info.handle.prefix_digests())
                info.digest_truncated = bool(
                    getattr(info.handle, "digests_truncated", lambda: False)()
                )
                info.last_heartbeat = time.monotonic()
                self.directory.update(
                    info.replica_id,
                    () if info.draining else info.digests,
                )
            except Exception:  # noqa: BLE001 - a dying local replica
                log.exception(
                    "local replica %s poll failed", info.replica_id
                )

    def alive(
        self, role: str | None = None, admitting: bool = True
    ) -> list[ReplicaInfo]:
        """Live replicas, optionally filtered by role; ``admitting``
        excludes draining replicas (they finish/migrate, never admit)."""
        self.reap()
        now = time.monotonic()
        with self._lock:
            out = []
            for info in self._replicas.values():
                if role is not None and info.role != role:
                    continue
                if admitting and info.draining:
                    continue
                if not info.local and (
                    now - info.last_heartbeat > self.ttl_s
                ):
                    continue
                # Heartbeat staleness feeds the breaker: a remote replica
                # past half the TTL is suspect (still routable — the
                # liveness reap above handles full staleness).
                health = self._health.get(info.replica_id)
                if (
                    health is not None and not info.local
                    and health.state == "healthy"
                    and now - info.last_heartbeat > self.ttl_s / 2
                ):
                    health.state = "suspect"
                if admitting and health is not None \
                        and not health.admitting(now):
                    continue
                out.append(info)
            return out

    # -- circuit breaker ---------------------------------------------------
    def note_result(self, replica_id: str, ok: bool) -> None:
        """Feed one router call outcome into the replica's health state
        machine. Successes close the breaker; consecutive failures walk
        healthy -> suspect -> ejected with exponentially backed-off
        cooldowns (half-open probes readmit, see ReplicaHealth)."""
        ejected = False
        with self._lock:
            if replica_id not in self._replicas:
                return
            health = self._health.setdefault(replica_id, ReplicaHealth())
            health.probe_started = 0.0
            if ok:
                if health.state != "healthy":
                    log.info("replica %s healthy again", replica_id)
                health.state = "healthy"
                health.consecutive_failures = 0
            else:
                health.consecutive_failures += 1
                if health.state == "healthy":
                    health.state = "suspect"
                was_open = health.state == "ejected"
                if health.consecutive_failures >= EJECT_AFTER_FAILURES \
                        or was_open:
                    health.state = "ejected"
                    health.ejections += 1
                    cooldown = min(
                        self.eject_cooldown_s
                        * (2 ** max(0, health.ejections - 1)),
                        EJECT_COOLDOWN_MAX_S,
                    )
                    health.ejected_until = time.monotonic() + cooldown
                    ejected = True
        if ejected:
            log.warning(
                "replica %s ejected by circuit breaker (%d consecutive "
                "failures)", replica_id,
                self._health[replica_id].consecutive_failures,
            )
            obs.FLEET_EJECTIONS.inc()
            obs.flight.record(
                "replica_ejected", replica=replica_id,
                failures=self._health[replica_id].consecutive_failures,
                ejections=self._health[replica_id].ejections,
            )
        self._observe()

    def begin_probe(self, replica_id: str) -> None:
        """Mark the half-open probe in flight: the router calls this when
        it routes a request onto an ejected-past-cooldown replica, so
        only one probe is outstanding at a time."""
        with self._lock:
            health = self._health.get(replica_id)
            if health is not None and health.state == "ejected":
                health.probe_started = time.monotonic()

    def health_of(self, replica_id: str) -> ReplicaHealth | None:
        with self._lock:
            return self._health.get(replica_id)

    def clock_of(self, replica_id: str) -> ClockSync | None:
        with self._lock:
            return self._clocks.get(replica_id)

    def clock_offsets(self) -> dict[str, float]:
        """Per-replica clock-offset estimates in seconds (replica wall
        minus router wall); replicas without a converged estimate are
        reported at 0 — the stitcher then trusts their timestamps."""
        with self._lock:
            return {
                rid: (c.offset_s if c.samples else 0.0)
                for rid, c in self._clocks.items()
            }

    def health_snapshot(
        self, clock: bool = False
    ) -> dict[str, str] | dict[str, dict[str, Any]]:
        """Per-replica breaker state; ``clock=True`` widens each value
        to ``{"state", "clock_offset_s", "clock_rtt_s",
        "clock_samples"}`` for surfaces that also want the skew
        estimate (router /healthz, registry snapshot)."""
        with self._lock:
            if not clock:
                return {rid: h.state for rid, h in self._health.items()}
            out: dict[str, dict[str, Any]] = {}
            for rid, h in self._health.items():
                c = self._clocks.get(rid) or ClockSync()
                out[rid] = {
                    "state": h.state,
                    "clock_offset_s": round(c.offset_s, 6),
                    "clock_rtt_s": round(c.rtt_s, 6),
                    "clock_samples": c.samples,
                }
            return out

    def all(self) -> list[ReplicaInfo]:
        with self._lock:
            return list(self._replicas.values())

    def snapshot(self) -> dict[str, Any]:
        health = self.health_snapshot(clock=True)
        rows = [i.snapshot() for i in self.all()]
        for row in rows:
            h = health.get(row["id"])
            row["health"] = h["state"] if h else "healthy"
            row["clock_offset_s"] = h["clock_offset_s"] if h else 0.0
            row["clock_rtt_s"] = h["clock_rtt_s"] if h else 0.0
        return {
            "replicas": rows,
            "heartbeat_ttl_s": self.ttl_s,
            "reaped_total": self.reaped,
            "directory": self.directory.stats(),
        }

    def _observe(self) -> None:
        counts: dict[tuple[str, str], int] = {}
        for info in self.all():
            key = (info.role, "draining" if info.draining else "active")
            counts[key] = counts.get(key, 0) + 1
        for role in ("decode", "prefill", "standby"):
            for state in ("active", "draining"):
                obs.FLEET_REPLICAS.set(
                    float(counts.get((role, state), 0)),
                    role=role, state=state,
                )
        hcounts: dict[str, int] = {}
        for state in self.health_snapshot().values():
            hcounts[state] = hcounts.get(state, 0) + 1
        for state in ("healthy", "suspect", "ejected"):
            obs.FLEET_REPLICA_HEALTH.set(
                float(hcounts.get(state, 0)), state=state
            )
