"""Fleet-global KV page store: directory + peer-to-peer fault-in.

PRs 7-10 made KV pages a per-replica asset the router papers over with
affinity scoring and explicit push-migration. This module inverts that:
the prefix digests every replica already heartbeats (Engine.prefix_digests
-> ReplicaInfo.digests) are indexed into a fleet-wide **directory** mapping
``chain_key_hex`` -> owning replicas, and a replica that misses locally at
admission (HBM trie AND host pool) consults the directory and **faults the
chain in** peer-to-peer over the existing ``/fleet/kv/export`` wire format
(transfer.pack_entries/unpack_entries, digest-verified). Fetched pages
land in the local HostPagePool under the identical chain keys, so the
admission restores them through the EXACT offload-restore path
(engine._restore_from_host -> promote_prefix): bit-exact, immediately
trie-visible, zero new restore code.

Tier order after this module: HBM trie -> host pool -> peer fetch ->
re-prefill. Every tier is an optimization over the next; correctness never
depends on a hit. The fetch client NEVER raises into admission — a miss,
a slow peer, an over-budget payload, or a corrupt record degrades to local
re-prefill, counted (``opsagent_pagestore_fallbacks_total``).

Staleness: directory rows are advertisements, not leases. A peer that no
longer holds an advertised chain (LRU-evicted between heartbeats, or a
404 / digest-reject on the fetch) is a **stale-entry signal**: the row is
evicted from the directory (``opsagent_pagestore_stale_entries_total``)
instead of retried — the next heartbeat re-advertises whatever the peer
really holds.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable

from ... import obs
from ...utils.logger import get_logger
from .. import faults
from ..offload.pool import chain_key_hex
from .transfer import records_nbytes, unpack_entries

log = get_logger("fleet.pagestore")

ENV_FETCH_TIMEOUT = "OPSAGENT_PAGESTORE_TIMEOUT_S"
DEFAULT_FETCH_TIMEOUT_S = 5.0

ENV_FETCH_MAX_BYTES = "OPSAGENT_PAGESTORE_MAX_BYTES"
DEFAULT_FETCH_MAX_BYTES = 256 << 20  # 256 MiB per fault-in

MAX_PEERS_PER_FAULT_IN = 2  # bounded: never a retry loop over the fleet


def fetch_timeout_s(override: float | None = None) -> float:
    if override is not None and override > 0:
        return float(override)
    try:
        v = float(os.environ.get(ENV_FETCH_TIMEOUT, ""))
        if v > 0:
            return v
    except ValueError:
        pass
    return DEFAULT_FETCH_TIMEOUT_S


def fetch_max_bytes(override: int | None = None) -> int:
    if override is not None and override > 0:
        return int(override)
    try:
        v = int(os.environ.get(ENV_FETCH_MAX_BYTES, ""))
        if v > 0:
            return v
    except ValueError:
        pass
    return DEFAULT_FETCH_MAX_BYTES


class PageDirectory:
    """chain_key_hex -> {replica_id: last_seen} index over heartbeat
    digests. Thread-safe; updated wholesale per replica (the heartbeat
    carries the replica's full advertisement, so an update is a set
    diff), invalidated row-wise on stale-entry signals and replica-wise
    on reap/deregister/drain."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owners: dict[str, dict[str, float]] = {}
        self._by_replica: dict[str, set[str]] = {}
        # cumulative stats (registry snapshot + router /healthz)
        self.lookups = 0
        self.hits = 0
        self.misses = 0
        self.stale_evictions = 0

    # -- writes ------------------------------------------------------------
    def update(self, replica_id: str, keys: Any) -> None:
        """Replace ``replica_id``'s advertisement with ``keys``."""
        new = set(keys or ())
        now = time.monotonic()
        with self._lock:
            old = self._by_replica.get(replica_id, set())
            for k in old - new:
                owners = self._owners.get(k)
                if owners is not None:
                    owners.pop(replica_id, None)
                    if not owners:
                        del self._owners[k]
            for k in new:
                self._owners.setdefault(k, {})[replica_id] = now
            if new:
                self._by_replica[replica_id] = new
            else:
                self._by_replica.pop(replica_id, None)

    def remove_replica(self, replica_id: str) -> int:
        """Drop every row owned by ``replica_id`` (reap / deregister /
        drain). Returns rows removed."""
        with self._lock:
            keys = self._by_replica.pop(replica_id, set())
            n = 0
            for k in keys:
                owners = self._owners.get(k)
                if owners is not None and owners.pop(replica_id, None) \
                        is not None:
                    n += 1
                    if not owners:
                        del self._owners[k]
            return n

    def invalidate(self, key: str, replica_id: str) -> bool:
        """Stale-entry eviction: the peer advertised ``key`` but could
        not produce it (evicted, 404, digest reject). Evict the single
        row — the replica's other advertisements stay valid."""
        with self._lock:
            owners = self._owners.get(key)
            if owners is None or owners.pop(replica_id, None) is None:
                return False
            if not owners:
                del self._owners[key]
            rep = self._by_replica.get(replica_id)
            if rep is not None:
                rep.discard(key)
            self.stale_evictions += 1
            return True

    # -- reads -------------------------------------------------------------
    def owners(self, keys: list[str]) -> dict[str, list[str]]:
        """Owning replica ids per chain key, freshest advertisement
        first. Counts lookup hit/miss stats per key."""
        out: dict[str, list[str]] = {}
        with self._lock:
            for k in keys:
                self.lookups += 1
                owners = self._owners.get(k)
                if owners:
                    self.hits += 1
                    out[k] = [
                        rid for rid, _ in sorted(
                            owners.items(), key=lambda kv: -kv[1]
                        )
                    ]
                else:
                    self.misses += 1
        return out

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "chains": len(self._owners),
                "replicas": len(self._by_replica),
                "lookups": self.lookups,
                "hits": self.hits,
                "misses": self.misses,
                "stale_evictions": self.stale_evictions,
            }

    def snapshot(self, limit: int = 256) -> dict[str, Any]:
        """Operator view (GET /api/fleet/directory, ``opsagent
        fleet-kv``): per-chain owners with advertisement staleness."""
        now = time.monotonic()
        with self._lock:
            rows = [
                {
                    "chain": k,
                    "owners": [
                        {"id": rid, "age_s": round(now - seen, 3)}
                        for rid, seen in sorted(
                            owners.items(), key=lambda kv: -kv[1]
                        )
                    ],
                }
                for k, owners in list(self._owners.items())[:limit]
            ]
            truncated = len(self._owners) > limit
        return {
            "stats": self.stats(),
            "rows": rows,
            "truncated": truncated,
        }


class PageStoreClient:
    """The fault-in side: resolve missing chain keys against the
    directory, fetch the pages peer-to-peer, land them in the local host
    pool. Composed from three callables so in-process fleets (router-
    owned directory + LocalReplica handles) and HTTP fleets (router
    lookup endpoint + peer engine servers) share one implementation:

        lookup(keys)          -> {key: [owner, ...]}   owner: {"id", ...}
        fetch(owner, tokens, start_page, timeout_s) -> transfer records
        template()            -> any pytree with the local cache treedef

    ``fault_in`` NEVER raises: admission calls it inline, and every
    failure mode (no owner, timeout, oversized payload, digest reject)
    degrades to local re-prefill, counted."""

    def __init__(
        self,
        self_id: str,
        page_size: int,
        pool: Any,
        template: Callable[[], Any],
        lookup: Callable[[list[str]], dict[str, list[dict[str, Any]]]],
        fetch: Callable[..., list[dict[str, Any]]],
        on_stale: Callable[[str, str], None] | None = None,
        timeout_s: float | None = None,
        max_bytes: int | None = None,
    ):
        self.self_id = self_id
        self.page_size = page_size
        self.pool = pool
        self.template = template
        self.lookup = lookup
        self.fetch = fetch
        self.on_stale = on_stale
        self.timeout_s = fetch_timeout_s(timeout_s)
        self.max_bytes = fetch_max_bytes(max_bytes)
        # cumulative stats (tests / bench read the deltas)
        self.remote_hit_pages = 0
        self.fallbacks = 0
        self.stale_entries = 0

    # -- internals ---------------------------------------------------------
    def _note_stale(self, key: str, replica_id: str) -> None:
        self.stale_entries += 1
        obs.PAGESTORE_STALE_ENTRIES.inc()
        if self.on_stale is not None:
            try:
                self.on_stale(key, replica_id)
            except Exception:  # noqa: BLE001 - bookkeeping only
                log.exception("stale-entry eviction callback failed")

    def _fallback(self, reason: str, **ctx: Any) -> int:
        self.fallbacks += 1
        obs.PAGESTORE_FALLBACKS.inc(reason=reason)
        obs.flight.record(
            "page_fault_in", phase="exit", outcome="fallback",
            reason=reason, replica=self.self_id, **ctx,
        )
        return 0

    def fault_in(
        self, token_ids: list[int], start_page: int,
        request_id: str = "",
    ) -> int:
        """Fetch pages ``start_page..`` of ``token_ids`` (a page-aligned
        usable prefix) from peers into the local host pool. Returns
        pages landed (0 on any failure — the caller re-prefills).
        ``request_id`` tags the flight events with the journey this
        fetch serves so the fleet stitcher can draw the fault-in
        window."""
        try:
            return self._fault_in(token_ids, start_page, request_id)
        except Exception:  # noqa: BLE001 - NEVER raises into admission
            log.exception("page fault-in failed; re-prefilling")
            return self._fallback(
                "error",
                **({"request_id": request_id} if request_id else {}),
            )

    def _fault_in(
        self, token_ids: list[int], start_page: int,
        request_id: str = "",
    ) -> int:
        P = self.page_size
        total = len(token_ids) // P
        if start_page >= total:
            return 0
        missing = {
            chain_key_hex(token_ids[: (i + 1) * P]): i
            for i in range(start_page, total)
        }
        keys = list(missing)
        rid_field = {"request_id": request_id} if request_id else {}
        obs.PAGESTORE_LOOKUPS.inc(len(keys))
        try:
            owners_map = self.lookup(keys)
        except Exception:  # noqa: BLE001 - directory unreachable
            log.exception("pagestore directory lookup failed")
            return self._fallback(
                "lookup_error", chains=len(keys), **rid_field
            )
        # Rank candidate peers by how many missing chains they cover
        # (the deepest-coverage owner almost always holds the whole
        # suffix — chains are prefixes of each other).
        coverage: dict[str, int] = {}
        owner_info: dict[str, dict[str, Any]] = {}
        claims: dict[str, list[str]] = {}
        for key, owners in owners_map.items():
            for o in owners:
                rid = o.get("id") if isinstance(o, dict) else str(o)
                if rid == self.self_id or rid is None:
                    continue
                coverage[rid] = coverage.get(rid, 0) + 1
                owner_info.setdefault(
                    rid, o if isinstance(o, dict) else {"id": rid}
                )
                claims.setdefault(rid, []).append(key)
        if not coverage:
            return self._fallback(
                "no_owner", chains=len(keys), **rid_field
            )
        ranked = sorted(coverage, key=lambda r: -coverage[r])
        obs.flight.record(
            "page_fault_in", phase="enter", replica=self.self_id,
            chains=len(keys), start_page=start_page,
            candidates=len(ranked), **rid_field,
        )
        t0 = time.perf_counter()
        landed = 0
        nbytes = 0
        outcome = "miss"
        for rid in ranked[:MAX_PEERS_PER_FAULT_IN]:
            try:
                faults.maybe_raise(
                    "pagestore.fetch_timeout", TimeoutError,
                    "injected pagestore fetch timeout",
                    peer=rid, replica=self.self_id,
                )
                records = self.fetch(
                    owner_info[rid], token_ids,
                    start_page + landed, self.timeout_s,
                )
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    # Gone peer endpoint / unknown chain: stale signal,
                    # evict the rows — do NOT retry this peer.
                    for key in claims.get(rid, ()):
                        self._note_stale(key, rid)
                    continue
                log.warning("pagestore fetch from %s failed: %s", rid, e)
                self.fallbacks += 1
                obs.PAGESTORE_FALLBACKS.inc(reason="error")
                continue
            except (TimeoutError, OSError) as e:
                log.warning("pagestore fetch from %s timed out: %s", rid, e)
                self.fallbacks += 1
                obs.PAGESTORE_FALLBACKS.inc(reason="timeout")
                continue
            except Exception:  # noqa: BLE001
                log.exception("pagestore fetch from %s failed", rid)
                self.fallbacks += 1
                obs.PAGESTORE_FALLBACKS.inc(reason="error")
                continue
            if faults.fire(
                "pagestore.stale_entry", peer=rid, replica=self.self_id
            ):
                records = []   # injected: the peer evicted the chain
            if not records:
                # The directory said this peer owns the chain; the peer
                # says it does not (LRU eviction between the heartbeat
                # and the fetch). Stale rows evict — no retry loop.
                for key in claims.get(rid, ()):
                    self._note_stale(key, rid)
                continue
            got = records_nbytes(records)
            while records and got > self.max_bytes:
                # Size bound: drop tail records (deepest pages) until
                # the payload fits — a partial chain still restores its
                # leading pages; the rest re-prefills.
                got -= records_nbytes(records[-1:])
                records = records[:-1]
            unpacked = unpack_entries(records, self.template())
            if records and not unpacked:
                # Every record digest-rejected: corrupt peer — same
                # stale-entry treatment as a 404.
                for key in claims.get(rid, ()):
                    self._note_stale(key, rid)
                continue
            for toks, tree in unpacked:
                if self.pool.put(toks, tree):
                    landed += 1
            nbytes += got
            if landed:
                outcome = "hit"
            if start_page + landed >= total:
                break
        dt = time.perf_counter() - t0
        if landed:
            self.remote_hit_pages += landed
            obs.PAGESTORE_REMOTE_HITS.inc(landed)
            obs.PAGESTORE_FETCH_BYTES.inc(nbytes)
        elif outcome == "miss":
            self.fallbacks += 1
            obs.PAGESTORE_FALLBACKS.inc(reason="miss")
        obs.PAGESTORE_FETCH_SECONDS.observe(dt)
        if request_id:
            obs.FLEET_HOP_SECONDS.observe(dt, hop="fault_in")
        obs.flight.record(
            "page_fault_in", phase="exit", outcome=outcome,
            replica=self.self_id, pages=landed, bytes=nbytes,
            ms=round(dt * 1e3, 3), **rid_field,
        )
        return landed

    def stats(self) -> dict[str, int]:
        return {
            "remote_hit_pages": self.remote_hit_pages,
            "fallbacks": self.fallbacks,
            "stale_entries": self.stale_entries,
        }


# -- client factories ---------------------------------------------------------
def local_client(registry: Any, self_id: str, engine: Any) -> PageStoreClient:
    """Fault-in client for an in-process replica: the router's registry
    owns the directory, peer handles are LocalReplica objects reachable
    through it. Wired by FleetRouter.add_local."""

    def lookup(keys: list[str]) -> dict[str, list[dict[str, Any]]]:
        return {
            k: [{"id": rid} for rid in rids]
            for k, rids in registry.directory.owners(keys).items()
        }

    def fetch(
        owner: dict[str, Any], token_ids: list[int],
        start_page: int, timeout_s: float,
    ) -> list[dict[str, Any]]:
        info = registry.get(owner["id"])
        if info is None or info.handle is None:
            return []
        return info.handle.export_pages(
            token_ids, park=False, start_page=start_page,
        )

    return PageStoreClient(
        self_id=self_id,
        page_size=int(engine.cfg.page_size),
        pool=engine.offload.pool,
        template=lambda: engine.cache,
        lookup=lookup,
        fetch=fetch,
        on_stale=registry.directory.invalidate,
    )


def http_client(
    router_url: str, self_id: str, engine: Any,
) -> PageStoreClient:
    """Fault-in client for a ``serve-engine --join-fleet`` replica: the
    directory lives router-side (POST /fleet/directory/lookup returns
    owners WITH their advertised URLs); pages fetch peer-to-peer from
    the owning replica's /fleet/kv/export — the router never carries
    page payloads."""
    base = router_url.rstrip("/")

    def _post(
        url: str, body: dict[str, Any], timeout_s: float,
    ) -> dict[str, Any]:
        req = urllib.request.Request(
            url, data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(  # noqa: S310 - operator URLs
            req, timeout=timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def lookup(keys: list[str]) -> dict[str, list[dict[str, Any]]]:
        out = _post(
            base + "/fleet/directory/lookup", {"keys": keys},
            timeout_s=fetch_timeout_s(),
        )
        return out.get("owners", {})

    def fetch(
        owner: dict[str, Any], token_ids: list[int],
        start_page: int, timeout_s: float,
    ) -> list[dict[str, Any]]:
        url = (owner.get("url") or "").rstrip("/")
        if not url:
            return []
        out = _post(
            url + "/fleet/kv/export",
            {
                "chains": [
                    {"tokens": token_ids, "start_page": start_page}
                ],
                "park": False,
            },
            timeout_s=timeout_s,
        )
        results = out.get("results")
        if results:
            return results[0].get("pages", [])
        return out.get("pages", [])   # pre-batching engine servers

    return PageStoreClient(
        self_id=self_id,
        page_size=int(engine.cfg.page_size),
        pool=engine.offload.pool,
        template=lambda: engine.cache,
        lookup=lookup,
        fetch=fetch,
    )
