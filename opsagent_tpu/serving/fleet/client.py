"""Replica-side fleet membership: ``serve-engine --join-fleet <router>``.

Registers the engine replica with the router, then heartbeats on a
background thread — each beat refreshing the replica's load snapshot and
prefix digest so the router's affinity scores track what the trie/host
pool actually hold. A 410 from the heartbeat endpoint (reaped, or the
router restarted) triggers transparent re-registration, paced by a
jittered, capped backoff so a router restart does not get a thundering
herd of simultaneous re-registers; transport failures (``URLError`` /
``OSError`` — router blip, DNS, refused socket) never kill the
membership thread. The membership state also feeds the engine server's
``/healthz`` ``fleet`` block (replica id, role, registered-router URL,
drain state).
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any

from ...utils.logger import get_logger
from .. import faults

log = get_logger("fleet.client")

DEFAULT_HEARTBEAT_INTERVAL_S = 3.0
REGISTER_BACKOFF_BASE_S = 1.0
REGISTER_BACKOFF_CAP_S = 30.0


class FleetMembership:
    def __init__(
        self,
        stack: Any,
        router_url: str,
        advertise_url: str,
        replica_id: str = "",
        role: str = "decode",
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    ):
        self.stack = stack
        self.router_url = router_url.rstrip("/")
        self.advertise_url = advertise_url.rstrip("/")
        self.replica_id = replica_id or f"replica-{uuid.uuid4().hex[:8]}"
        self.role = role
        self.heartbeat_interval_s = heartbeat_interval_s
        self.registered = False
        self.draining = False
        self.last_heartbeat_ok: bool | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Re-registration pacing: per-replica seeded jitter decorrelates
        # a fleet's herd after a router restart without making any one
        # replica's schedule nondeterministic across its own retries.
        self._jitter = random.Random(self.replica_id)
        self._register_backoff_s = 0.0
        self._next_register_s = 0.0
        # Clock-sync echo: the router's wall clock from the last
        # register/heartbeat response, plus the local monotonic instant
        # it arrived. The NEXT beat echoes the timestamp together with
        # the held duration, giving the registry one RTT + clock-offset
        # sample per beat (registry.ClockSync).
        self._echo_router_ts: float | None = None
        self._echo_rx_mono: float = 0.0

    # -- wire ----------------------------------------------------------------
    def _post(self, path: str, body: dict[str, Any]) -> dict[str, Any]:
        req = urllib.request.Request(
            self.router_url + path,
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(  # noqa: S310 - operator URL
            req, timeout=10.0
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _note_router_ts(self, resp: dict[str, Any]) -> None:
        ts = resp.get("router_ts") if isinstance(resp, dict) else None
        if isinstance(ts, (int, float)):
            self._echo_router_ts = float(ts)
            self._echo_rx_mono = time.monotonic()

    def _payload(self, full: bool) -> dict[str, Any]:
        eng = self.stack.engine
        sched = self.stack.scheduler
        load: dict[str, Any] = {
            "running": len(sched._running),
            "queued": len(sched._waiting) + sched._queue.qsize(),
            "prefilling": len(sched._prefilling),
            "free_pages": eng.alloc.free_pages,
        }
        if getattr(eng, "offload", None) is not None:
            load["host_pool_pages"] = eng.offload.pool.num_pages
        body: dict[str, Any] = {
            "replica_id": self.replica_id,
            "load": load,
            "digests": eng.prefix_digests(),
            "digest_truncated": bool(
                getattr(eng, "digests_truncated", lambda: False)()
            ),
            "replica_ts": time.time(),
        }
        if self._echo_router_ts is not None:
            body["echo_router_ts"] = self._echo_router_ts
            body["echo_held_s"] = time.monotonic() - self._echo_rx_mono
        if full:
            body.update({
                "url": self.advertise_url,
                "model": self.stack.model_name,
                "role": self.role,
                "capacity": int(eng.cfg.max_batch_size),
                "page_size": int(eng.cfg.page_size),
                "mesh": {
                    "tp": eng.cfg.tp, "sp": eng.cfg.sp, "ep": eng.cfg.ep,
                },
            })
        return body

    # -- lifecycle -----------------------------------------------------------
    def register(self) -> bool:
        try:
            self._note_router_ts(
                self._post("/fleet/register", self._payload(full=True))
            )
        except Exception as e:  # noqa: BLE001 - router may not be up yet
            log.warning("fleet registration failed (will retry): %s", e)
            self.registered = False
            # Jittered, capped backoff before the next attempt.
            self._register_backoff_s = min(
                REGISTER_BACKOFF_CAP_S,
                (self._register_backoff_s or REGISTER_BACKOFF_BASE_S) * 2,
            )
            self._next_register_s = time.monotonic() + \
                self._register_backoff_s * self._jitter.uniform(0.5, 1.5)
            return False
        self.registered = True
        self._register_backoff_s = 0.0
        self._next_register_s = 0.0
        log.info(
            "joined fleet at %s as %s (role=%s)",
            self.router_url, self.replica_id, self.role,
        )
        return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self.register()
        self._thread = threading.Thread(
            target=self._beat_loop, daemon=True, name="fleet-heartbeat"
        )
        self._thread.start()

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            if self.draining:
                # Drained (POST /fleet/drain): the router deregistered
                # this replica on purpose — re-registering would undo the
                # drain. In-flight work finishes; the process is expected
                # to exit (or an operator clears .draining to rejoin).
                continue
            if not self.registered:
                if time.monotonic() >= self._next_register_s:
                    self.register()
                continue
            if faults.fire("client.heartbeat_drop", replica=self.replica_id):
                # Injected: this beat is silently lost in transit. The
                # router sees a stale heartbeat (suspect past ttl/2,
                # reaped past ttl); the replica just beats again.
                self.last_heartbeat_ok = False
                continue
            try:
                self._note_router_ts(
                    self._post(
                        "/fleet/heartbeat", self._payload(full=False)
                    )
                )
                self.last_heartbeat_ok = True
            except urllib.error.HTTPError as e:
                self.last_heartbeat_ok = False
                if e.code == 410:
                    # Reaped / router restarted: re-register next beat.
                    self.registered = False
            except (urllib.error.URLError, OSError):
                # Router blip (refused socket, DNS, reset): the thread
                # must survive — connectivity usually comes back.
                self.last_heartbeat_ok = False
            except Exception:  # noqa: BLE001 - router briefly unreachable
                self.last_heartbeat_ok = False

    def promote(self, role: str = "decode") -> None:
        """Accept a router-side role change (POST /fleet/promote on the
        engine server). Heartbeats never carry role, so the only way the
        promotion could revert is a full re-register — which now carries
        the new role too."""
        old, self.role = self.role, role
        if old != role:
            log.info(
                "replica %s promoted: role %s -> %s",
                self.replica_id, old, role,
            )

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if deregister and self.registered:
            try:
                self._post(
                    "/fleet/deregister", {"replica_id": self.replica_id}
                )
            except Exception:  # noqa: BLE001
                pass
            self.registered = False

    # -- /healthz fleet block -------------------------------------------------
    def healthz_block(self) -> dict[str, Any]:
        return {
            "replica_id": self.replica_id,
            "role": self.role,
            "router_url": self.router_url,
            "registered": self.registered,
            "draining": self.draining,
            "last_heartbeat_ok": self.last_heartbeat_ok,
        }
