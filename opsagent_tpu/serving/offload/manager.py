"""Offload policy + telemetry: ties the host pool and the copy engine to
one engine's cache/allocator.

All entry points run under the engine lock (the engine owns the cache the
copier reads/writes). The manager never raises into the serving path:
offload is best-effort — a failed spill loses nothing but a future restore
(the tokens re-prefill), and a failed restore falls back to re-prefill.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from ... import obs
from ...utils.logger import get_logger
from .copy import PageCopyEngine
from .pool import HostPagePool, tree_nbytes

log = get_logger("offload")


class OffloadManager:
    def __init__(
        self,
        pool: HostPagePool,
        copier: PageCopyEngine,
        page_size: int,
    ):
        self.pool = pool
        self.copier = copier
        self.page_size = page_size
        # cumulative restore stats (bench A/B reads the deltas)
        self.restored_tokens = 0
        self.restored_pages = 0
        self.spilled_pages = 0
        # remote tier: restored tokens whose pages arrived by peer
        # fault-in (fleet/pagestore.py) rather than a local spill — the
        # tier between host-pool-hit and re-prefill
        self.remote_hit_tokens = 0

    def note_remote_hit(self, tokens: int) -> None:
        """Credit ``tokens`` of the last restore to the peer-fetch tier
        (the engine calls this when an admission's restore was preceded
        by a page fault-in that landed the pages)."""
        if tokens > 0:
            self.remote_hit_tokens += int(tokens)

    # -- device -> host (spill) --------------------------------------------
    def spill(
        self, cache: Any, chains: list[tuple[int, list[int]]],
        trigger: str = "evict",
    ) -> int:
        """Enqueue device->host copies for ``(page, token_chain)`` pairs;
        the chain is the full page-aligned token prefix the page's KV
        covers (the host-pool key). The actual pull happens at the next
        ``flush()``. Returns the number of pages dispatched."""
        good_pages: list[int] = []
        metas: list[tuple[int, ...]] = []
        for page, chain in chains:
            if not chain or len(chain) % self.page_size != 0:
                continue
            good_pages.append(page)
            metas.append(tuple(chain))
        if not good_pages:
            return 0
        try:
            self.copier.dispatch_gather(cache, good_pages, metas)
        except Exception:  # noqa: BLE001 - offload is best-effort
            log.exception("page spill dispatch failed (content lost to host tier)")
            return 0
        self.spilled_pages += len(good_pages)
        obs.OFFLOAD_PAGES.inc(len(good_pages), dir="out")
        obs.flight.record(
            "offload", pages=len(good_pages), trigger=trigger,
        )
        return len(good_pages)

    def flush(self) -> int:
        """Pull every pending spill into the host pool. Returns pages
        landed."""
        if self.copier.pending_pages == 0:
            self._observe()
            return 0
        n = 0
        nbytes = 0
        try:
            for chain, page_tree in self.copier.flush():
                if self.pool.put(np.asarray(chain, np.int32), page_tree):
                    n += 1
                    nbytes += tree_nbytes(page_tree)
        except Exception:  # noqa: BLE001
            log.exception("offload flush failed")
        if nbytes:
            obs.OFFLOAD_BYTES.inc(nbytes, dir="out")
        self._observe()
        return n

    # -- host -> device (restore) ------------------------------------------
    def restore(
        self, cache: Any, dst_pages: list[int], entries: list[Any],
        seq_id: int | None = None,
        on_update=None,
    ) -> tuple[Any, int]:
        """Scatter host pool entries into freshly-allocated device pages.
        Returns ``(new_cache, restored_tokens)``. ``on_update`` follows
        :meth:`PageCopyEngine.scatter` (donation safety on mid-copy
        failure)."""
        if not entries:
            return cache, 0
        t0 = time.perf_counter()
        page_trees = [e.data for e in entries]
        cache = self.copier.scatter(
            cache, dst_pages, page_trees, on_update=on_update
        )
        dt = time.perf_counter() - t0
        tokens = len(entries) * self.page_size
        self.restored_pages += len(entries)
        self.restored_tokens += tokens
        nbytes = sum(e.nbytes for e in entries)
        obs.OFFLOAD_PAGES.inc(len(entries), dir="in")
        obs.OFFLOAD_BYTES.inc(nbytes, dir="in")
        obs.OFFLOAD_RESTORE_SECONDS.observe(dt)
        obs.OFFLOAD_REPREFILL_AVOIDED.inc(tokens)
        obs.flight.record(
            "restore", seq_id=seq_id, pages=len(entries), tokens=tokens,
            ms=round(dt * 1e3, 3),
        )
        self._observe()
        return cache, tokens

    # -- telemetry ---------------------------------------------------------
    def _observe(self) -> None:
        st = self.pool.stats()
        obs.HOST_POOL_BYTES.set(float(st["bytes"]))
        obs.HOST_POOL_PAGES.set(float(st["pages"]))
        obs.HOST_POOL_CAPACITY.set(float(st["capacity_bytes"]))
        drops = st["drops"]
        seen = getattr(self, "_drops_seen", 0)
        if drops > seen:
            obs.HOST_POOL_DROPS.inc(drops - seen)
            self._drops_seen = drops

    def stats(self) -> dict[str, Any]:
        return {
            **self.pool.stats(),
            "restored_pages": self.restored_pages,
            "restored_tokens": self.restored_tokens,
            "spilled_pages": self.spilled_pages,
            "remote_hit_tokens": self.remote_hit_tokens,
            "pending_pages": self.copier.pending_pages,
        }
