"""Device<->host KV page copies: the data plane of the offload tier.

Two jitted programs per page-count bucket:

- **gather** (device->host direction): slice ``cache[:, page_ids]`` out of
  every cache leaf — one dispatch regardless of layer count — returning
  fresh device buffers that a later ``flush()`` pulls to host numpy. The
  pull is DOUBLE-BUFFERED: dispatching a gather costs one enqueue (the
  device copies concurrently with whatever decode work follows), and the
  blocking device->host transfer happens at the next flush point, so page
  offload overlaps decode dispatches instead of stalling them.
- **scatter** (host->device): ``cache.at[:, page_ids].set(data)`` with the
  cache donated — the restore path writes straight into the live pages.

Shapes are static per bucket (page-id vectors pad by DUPLICATING a real
id, so padded scatter lanes rewrite identical content — a no-op), which
keeps the restore path inside the zero-post-warmup-compiles invariant:
``Engine.warmup`` runs both programs per bucket once.

Every cache leaf keeps its page axis at index 1 (``[L, N, P, ...]``, both
the fp and the QuantizedPages int8+scale layouts), so one ``tree.map``
covers all layouts.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_PAGE_AXIS = 1  # cache leaves are [L, num_pages, page_size, ...]


class PageCopyEngine:
    def __init__(self, mesh_ctx=None, copy_pages: int = 8):
        """``mesh_ctx`` is the engine's mesh context factory
        (``Engine.mesh_ctx``): the copy programs must compile under the
        same ambient mesh as every other engine program or the jit cache
        forks (see Engine._mesh_tls)."""
        import contextlib

        self._mesh_ctx = mesh_ctx or contextlib.nullcontext
        copy_pages = max(1, int(copy_pages))
        self.buckets = (1,) if copy_pages == 1 else (1, copy_pages)

        def _gather(cache, ids):
            return jax.tree_util.tree_map(
                lambda c: jnp.take(c, ids, axis=_PAGE_AXIS), cache
            )

        def _scatter(cache, ids, data):
            return jax.tree_util.tree_map(
                lambda c, d: c.at[:, ids].set(d.astype(c.dtype)), cache, data
            )

        self._gather_jit = jax.jit(_gather)
        self._scatter_jit = jax.jit(_scatter, donate_argnums=(0,))
        # Double buffer: gathers dispatched but not yet pulled to host.
        # Each entry is (metas, device_tree) where metas[j] describes the
        # j-th REAL page of the batch (padding lanes carry no meta).
        self._pending: list[tuple[list[Any], Any]] = []

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # -- device -> host ----------------------------------------------------
    def dispatch_gather(self, cache: Any, pages: list[int], metas: list[Any]) -> None:
        """Enqueue device->host copies of ``pages`` (chunked into buckets).
        ``metas[j]`` rides along to ``flush()`` with page ``pages[j]``'s
        content; the source pages must not be REWRITTEN by a dispatch
        enqueued before this call returns (device execution is in dispatch
        order, so anything dispatched after is safe)."""
        assert len(pages) == len(metas)
        for off in range(0, len(pages), self.buckets[-1]):
            chunk = pages[off : off + self.buckets[-1]]
            ms = metas[off : off + len(chunk)]
            b = self._bucket(len(chunk))
            ids = np.full((b,), chunk[0], np.int32)
            ids[: len(chunk)] = chunk
            with self._mesh_ctx():
                dev = self._gather_jit(cache, jnp.asarray(ids))
            self._pending.append((ms, dev))

    def flush(self) -> list[tuple[Any, Any]]:
        """Pull every pending gather to host numpy. Returns a flat list of
        (meta, host_page_tree): each host tree mirrors the cache structure
        with the page axis removed (one page: ``[L, P, ...]`` leaves).
        The blocking pull's wall time and byte volume feed the goodput
        ledger (attribution kind="other"): offload copies ride the same
        HBM the decode stream uses."""
        import time

        out: list[tuple[Any, Any]] = []
        pending, self._pending = self._pending, []
        if not pending:
            return out
        t0 = time.perf_counter()
        nbytes = 0
        for metas, dev in pending:
            host = jax.tree_util.tree_map(np.asarray, dev)
            nbytes += sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(host)
            )
            for j, meta in enumerate(metas):
                page_tree = jax.tree_util.tree_map(
                    lambda leaf, _j=j: np.ascontiguousarray(leaf[:, _j]), host
                )
                out.append((meta, page_tree))
        from ...obs import attribution

        attribution.record_copy(
            nbytes, "gather", seconds=time.perf_counter() - t0
        )
        return out

    @property
    def pending_pages(self) -> int:
        return sum(len(m) for m, _ in self._pending)

    # -- host -> device ----------------------------------------------------
    def scatter(
        self, cache: Any, pages: list[int], page_trees: list[Any],
        on_update=None,
    ) -> Any:
        """Write host page contents into device ``pages``; returns the new
        (donated) cache. Chunked into buckets; padding lanes rewrite the
        first real page with its own data. ``on_update(cache)`` fires after
        every chunk so the caller's cache reference never dangles on a
        donated buffer if a later chunk raises."""
        assert len(pages) == len(page_trees) and pages
        import time

        t0 = time.perf_counter()
        nbytes = sum(
            leaf.nbytes
            for tree in page_trees
            for leaf in jax.tree_util.tree_leaves(tree)
        )
        for off in range(0, len(pages), self.buckets[-1]):
            chunk = pages[off : off + self.buckets[-1]]
            trees = page_trees[off : off + len(chunk)]
            b = self._bucket(len(chunk))
            ids = np.full((b,), chunk[0], np.int32)
            ids[: len(chunk)] = chunk
            pad = [trees[0]] * (b - len(chunk))
            stacked = jax.tree_util.tree_map(
                lambda *leaves: np.stack(leaves, axis=_PAGE_AXIS),
                *(trees + pad),
            )
            with self._mesh_ctx():
                cache = self._scatter_jit(
                    cache,
                    jnp.asarray(ids),
                    jax.tree_util.tree_map(jnp.asarray, stacked),
                )
            if on_update is not None:
                on_update(cache)
        from ...obs import attribution

        attribution.record_copy(
            nbytes, "scatter", seconds=time.perf_counter() - t0
        )
        return cache

    def warm(self, cache: Any) -> Any:
        """Compile every bucket's gather and scatter once, content-
        preservingly: the scatter rewrites page 0 with its own gathered
        content. Returns the (donated-through) cache."""
        for b in self.buckets:
            ids = np.zeros((b,), np.int32)
            with self._mesh_ctx():
                dev = self._gather_jit(cache, jnp.asarray(ids))
                host = jax.tree_util.tree_map(np.asarray, dev)
                cache = self._scatter_jit(
                    cache,
                    jnp.asarray(ids),
                    jax.tree_util.tree_map(jnp.asarray, host),
                )
        return cache
