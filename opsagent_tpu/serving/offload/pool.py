"""Host-RAM KV page pool: tier 2 of the hierarchical KV cache.

Entries are keyed exactly like the HBM prefix trie — by the page-aligned
token chain whose KV the page holds — so the pool is a host-RAM extension
of the trie: ``match`` walks the same way ``PageAllocator.match_prefix``
does, and a chain restored from here re-registers into the trie
(``PageAllocator.promote_prefix``) so partial restores still hit for
concurrent admissions.

The pool is byte-bounded (``OPSAGENT_KV_HOST_POOL_BYTES``, default 1 GiB):
inserting past the bound drops least-recently-used entries. Dropping a
mid-chain entry orphans the pages behind it (``match`` stops at the first
miss); orphans age out by the same LRU. Correctness never depends on
residency — a miss just means the tokens re-prefill (the tier-1 behavior).

Thread safety: one lock around the index. The page payloads themselves are
immutable numpy trees once inserted.
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass, field
from typing import Any

import numpy as np

ENV_HOST_POOL_BYTES = "OPSAGENT_KV_HOST_POOL_BYTES"
DEFAULT_HOST_POOL_BYTES = 1 << 30  # 1 GiB


def host_pool_capacity_bytes(override: int | None = None) -> int:
    """Resolve the pool byte bound: explicit override > env > default."""
    if override is not None and override > 0:
        return int(override)
    raw = os.environ.get(ENV_HOST_POOL_BYTES, "")
    try:
        v = int(raw)
        if v > 0:
            return v
    except ValueError:
        pass
    return DEFAULT_HOST_POOL_BYTES


def _chain_key(tokens: "np.ndarray") -> bytes:
    """Digest of the FULL token prefix this page chain covers. The digest
    is the dict key; the entry stores the tokens for verification so a
    hash collision can never alias two different histories' KV."""
    return hashlib.blake2b(
        np.ascontiguousarray(tokens, dtype=np.int32).tobytes(), digest_size=16
    ).digest()


def chain_key_hex(tokens) -> str:
    """Public form of the chain digest (hex), shared with the fleet layer:
    the replica registry advertises these keys as its prefix digest, the
    router scores prompt affinity against them, and the replica-to-replica
    KV transfer uses the underlying token chains as its wire format."""
    return _chain_key(np.asarray(tokens, dtype=np.int32)).hex()


def tree_nbytes(data: Any) -> int:
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree_util.tree_leaves(data)
    )


@dataclass
class HostPage:
    """One offloaded page: the KV content of ``tokens[-page_size:]`` given
    the preceding context ``tokens[:-page_size]``."""

    tokens: np.ndarray          # int32 [n_pages * page_size] full prefix
    data: Any                   # numpy pytree mirroring one cache page
    nbytes: int = 0
    last_use: int = 0
    key: bytes = field(default=b"", repr=False)


class HostPagePool:
    def __init__(
        self,
        page_size: int,
        capacity_bytes: int | None = None,
    ):
        self.page_size = page_size
        self.capacity_bytes = host_pool_capacity_bytes(capacity_bytes)
        self._lock = threading.Lock()
        self._entries: dict[bytes, HostPage] = {}
        self._clock = 0
        self.used_bytes = 0
        # cumulative stats (scraped into opsagent_kv_host_pool_* gauges
        # and the allocator's accounting() dict)
        self.inserts = 0
        self.hits = 0        # pages served by match()
        self.misses = 0      # match() walks ended by a missing page
        self.drops = 0       # LRU drops under the byte bound
        self.rejects = 0     # single pages larger than the whole bound

    # -- writing -----------------------------------------------------------
    def put(self, tokens: list[int] | np.ndarray, data: Any) -> bool:
        """Insert one page: ``tokens`` is the FULL page-aligned prefix
        (length a multiple of page_size, the last page_size of which this
        page holds), ``data`` a host numpy pytree of the page content.
        Returns False when rejected (oversized / unaligned)."""
        toks = np.ascontiguousarray(tokens, dtype=np.int32)
        if toks.size == 0 or toks.size % self.page_size != 0:
            return False
        nbytes = tree_nbytes(data)
        if nbytes > self.capacity_bytes:
            with self._lock:
                self.rejects += 1
            return False
        key = _chain_key(toks)
        with self._lock:
            self._clock += 1
            old = self._entries.get(key)
            if old is not None:
                # Same chain re-offloaded (e.g. park after a trie hit):
                # refresh recency, keep the existing payload — KV content
                # for one token history is deterministic modulo rounding,
                # and first-writer-wins keeps restores stable.
                old.last_use = self._clock
                return True
            ent = HostPage(
                tokens=toks, data=data, nbytes=nbytes,
                last_use=self._clock, key=key,
            )
            self._entries[key] = ent
            self.used_bytes += nbytes
            self.inserts += 1
            self._enforce_bound_locked()
        return True

    def _enforce_bound_locked(self) -> None:
        while self.used_bytes > self.capacity_bytes and self._entries:
            victim = min(self._entries.values(), key=lambda e: e.last_use)
            del self._entries[victim.key]
            self.used_bytes -= victim.nbytes
            self.drops += 1

    # -- reading -----------------------------------------------------------
    def match(
        self,
        tokens: list[int] | np.ndarray,
        start_page: int = 0,
        max_pages: int | None = None,
    ) -> list[HostPage]:
        """Longest resident page chain covering ``tokens`` starting at
        page index ``start_page`` (pages before it are assumed served by
        the HBM trie). Returns the entries in chain order; an empty list
        when the first wanted page is absent."""
        toks = np.ascontiguousarray(tokens, dtype=np.int32)
        P = self.page_size
        total = toks.size // P
        out: list[HostPage] = []
        with self._lock:
            self._clock += 1
            stamp = self._clock
            for i in range(start_page, total):
                if max_pages is not None and len(out) >= max_pages:
                    break
                key = _chain_key(toks[: (i + 1) * P])
                ent = self._entries.get(key)
                if ent is None or not np.array_equal(
                    ent.tokens, toks[: (i + 1) * P]
                ):
                    self.misses += 1
                    break
                ent.last_use = stamp
                out.append(ent)
            self.hits += len(out)
        return out

    def coverage(
        self, tokens: list[int] | np.ndarray, start_page: int = 0
    ) -> int:
        """Non-mutating probe: how many consecutive pages of ``tokens``
        from ``start_page`` are resident. Unlike :meth:`match` this
        neither bumps recency nor counts hit/miss stats — it answers
        "would a restore cover this?" for the fault-in tier decision
        without perturbing the LRU."""
        toks = np.ascontiguousarray(tokens, dtype=np.int32)
        P = self.page_size
        n = 0
        with self._lock:
            for i in range(start_page, toks.size // P):
                ent = self._entries.get(_chain_key(toks[: (i + 1) * P]))
                if ent is None or not np.array_equal(
                    ent.tokens, toks[: (i + 1) * P]
                ):
                    break
                n += 1
        return n

    def drop_chain(self, tokens: list[int] | np.ndarray) -> int:
        """Drop every resident page of this token chain (tests / explicit
        invalidation). Returns the number of pages dropped."""
        toks = np.ascontiguousarray(tokens, dtype=np.int32)
        P = self.page_size
        n = 0
        with self._lock:
            for i in range(toks.size // P):
                ent = self._entries.pop(_chain_key(toks[: (i + 1) * P]), None)
                if ent is not None:
                    self.used_bytes -= ent.nbytes
                    n += 1
        return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.used_bytes = 0

    def digests(self) -> list[str]:
        """Hex chain keys of every resident page (the fleet registry's
        host-tier half of the replica prefix digest)."""
        with self._lock:
            return [k.hex() for k in self._entries]

    def entries_for(self, tokens: list[int] | np.ndarray) -> list[HostPage]:
        """Alias of :meth:`match` from page 0 — the export side of a
        replica-to-replica chain transfer (serving/fleet/transfer.py)."""
        return self.match(tokens, start_page=0)

    # -- accounting --------------------------------------------------------
    @property
    def num_pages(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "pages": len(self._entries),
                "bytes": self.used_bytes,
                "capacity_bytes": self.capacity_bytes,
                "inserts": self.inserts,
                "hits": self.hits,
                "misses": self.misses,
                "drops": self.drops,
                "rejects": self.rejects,
            }
