"""Hierarchical KV cache: the host-RAM offload tier.

The HBM ``PageAllocator`` trie (serving/kvcache.py) is tier 1; this package
is tier 2 — a bounded host-RAM pool of evicted/parked KV page content, plus
the device<->host page-copy engine and the policy layer that decides when
pages move. The missing state between "prefix-cache hit" and "full
re-prefill": a session whose pages were evicted under HBM pressure (or
parked while its ReAct loop blocks on tool execution) restores them with a
page copy instead of re-running prefill.

Layout:

- ``pool``   — :class:`HostPagePool`: token-chain-keyed host page store
  with byte-bounded LRU (``OPSAGENT_KV_HOST_POOL_BYTES``).
- ``copy``   — :class:`PageCopyEngine`: the jitted device->host gather and
  host->device scatter programs (fixed page-count buckets so the
  zero-post-warmup-compiles invariant survives), double-buffered pulls.
- ``manager``— :class:`OffloadManager`: ties pool + copier to one engine's
  cache/allocator and owns the spill/restore/park flows + telemetry.
"""

from __future__ import annotations

from .copy import PageCopyEngine  # noqa: F401
from .manager import OffloadManager  # noqa: F401
from .pool import (  # noqa: F401
    DEFAULT_HOST_POOL_BYTES,
    ENV_HOST_POOL_BYTES,
    HostPagePool,
    host_pool_capacity_bytes,
)
