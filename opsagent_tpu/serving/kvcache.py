"""Host-side paged KV-cache bookkeeping, with prefix caching.

The device holds the pages (``models.llama.make_cache``); this module owns
the free list, per-sequence page tables, and the **prefix trie**: finished
sequences donate their full pages (keyed by page-aligned token content) so a
later request whose prompt shares the prefix skips re-prefilling it. The
ReAct loop re-sends the whole chat history every iteration (reference
pkg/assistants/simple.go:497-515) — prefix reuse turns that O(n²) re-prefill
into O(n) (SURVEY.md §5 checkpoint note, §7 step 5).

States of a page:
- **free**: on the free list.
- **owned**: exclusively held by a live sequence (its tail / generated pages).
- **shared**: in the trie with refcount = number of live sequences using it.
- **cached**: in the trie with refcount 0 — content retained, evictable LRU
  when the free list runs dry.

Allocation is O(pages) against a free list plus O(prompt/page_size) trie
walks; page counts are small (thousands).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

import numpy as np


class OutOfPages(Exception):
    """No free KV pages right now; the scheduler should queue the request."""


class PromptTooLong(Exception):
    """The request can NEVER be admitted (exceeds max_pages_per_seq or the
    largest prefill bucket); fail fast instead of queueing."""


class InvalidRequest(ValueError):
    """Malformed request (client's fault, HTTP 400) — distinct from engine
    bugs that happen to raise ValueError, which must stay 5xx."""


@dataclass
class SeqAlloc:
    seq_id: int
    pages: list[int] = field(default_factory=list)
    length: int = 0          # tokens currently in cache
    num_shared: int = 0      # leading pages borrowed from the prefix trie


@dataclass
class TrieNode:
    """One cached page: identified by (parent page, its page of tokens)."""

    page: int
    parent: int                      # parent page id, or -1 at the root
    key: tuple[int, ...]             # the page_size tokens this page holds
    refcount: int = 0                # live sequences sharing this page
    children: int = 0                # child nodes (only leaves are evictable)
    last_use: int = 0                # LRU stamp


class PageAllocator:
    def __init__(
        self,
        num_pages: int,
        page_size: int,
        max_pages_per_seq: int,
        prefix_cache: bool = True,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.prefix_cache = prefix_cache
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._seqs: dict[int, SeqAlloc] = {}
        self._next_id = 0
        # Prefix trie: (parent_page, token_tuple) -> TrieNode; page -> node.
        self._trie: dict[tuple[int, tuple[int, ...]], TrieNode] = {}
        self._by_page: dict[int, TrieNode] = {}
        self._clock = itertools.count()
        self.hit_tokens = 0   # cumulative prefix-cache hits (stats)
        self.miss_tokens = 0
        self.evictions = 0    # cumulative trie-leaf evictions (stats)

    # -- queries -----------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free) + sum(
            1 for n in self._by_page.values()
            if n.refcount == 0 and n.children == 0
        )

    def accounting(self) -> dict[str, int]:
        """Page-conservation snapshot: every page is exactly one of free,
        trie-resident (shared or cached), or exclusively owned by a live
        sequence — so free + trie + owned == num_pages always. The
        concurrency stress test asserts this under load (the Python answer
        to the reference's missing `go test -race`, SURVEY section 5)."""
        owned = sum(
            len(s.pages) - s.num_shared for s in self._seqs.values()
        )
        return {
            "free": len(self._free),
            "trie": len(self._by_page),
            "owned": owned,
            "total": len(self._free) + len(self._by_page) + owned,
        }

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_admit(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= self.free_pages

    def length(self, seq_id: int) -> int:
        return self._seqs[seq_id].length

    # -- prefix trie -------------------------------------------------------
    def match_prefix(self, tokens: list[int]) -> list[int]:
        """Longest cached page-aligned prefix of ``tokens``; returns the page
        ids WITHOUT taking references (call ``allocate`` with the result)."""
        if not self.prefix_cache:
            return []
        P = self.page_size
        stamp = next(self._clock)
        pages: list[int] = []
        parent = -1
        for i in range(len(tokens) // P):
            node = self._trie.get((parent, tuple(tokens[i * P:(i + 1) * P])))
            if node is None:
                break
            node.last_use = stamp  # matched chains are fresh, not LRU bait
            pages.append(node.page)
            parent = node.page
        return pages

    def _take_free_page(self) -> int:
        """Pop a free page, evicting the LRU unreferenced trie leaf if the
        free list is dry. Raises OutOfPages when nothing is evictable."""
        if self._free:
            return self._free.pop()
        victim: TrieNode | None = None
        for node in self._by_page.values():
            if node.refcount == 0 and node.children == 0:
                if victim is None or node.last_use < victim.last_use:
                    victim = node
        if victim is None:
            raise OutOfPages("no free pages and no evictable cached pages")
        self._evict(victim)
        return self._free.pop()

    def _evict(self, node: TrieNode) -> None:
        self.evictions += 1
        del self._trie[(node.parent, node.key)]
        del self._by_page[node.page]
        if node.parent >= 0 and node.parent in self._by_page:
            self._by_page[node.parent].children -= 1
        self._free.append(node.page)

    def _register_pages(self, seq: SeqAlloc, tokens: list[int]) -> list[int]:
        """Donate a finished sequence's full pages to the trie; returns the
        pages to put back on the free list (duplicates of already-cached
        content, the partial last page, over-allocated pages). ``tokens`` =
        the sequence's full token history (prompt + generated).

        Invariant: pages[0:num_shared] ARE trie nodes we hold a reference on
        (matched at admission against these exact tokens), so the walk just
        releases those references; owned full pages either become new trie
        nodes (kept) or are duplicates of a concurrently-registered chain
        (freed)."""
        P = self.page_size
        stamp = next(self._clock)
        full_pages = min(len(tokens) // P, len(seq.pages))
        absorbed: set[int] = set()
        parent = -1
        for i in range(full_pages):
            key = tuple(tokens[i * P:(i + 1) * P])
            page = seq.pages[i]
            if i < seq.num_shared:
                node = self._by_page[page]   # we hold a ref: cannot be evicted
                node.refcount -= 1
                node.last_use = stamp
                parent = page
                continue
            node = self._trie.get((parent, key))
            if node is not None:
                # Same content already cached by someone else: our page is a
                # duplicate — follow the canonical chain, free ours.
                node.last_use = stamp
                parent = node.page
                continue
            node = TrieNode(page=page, parent=parent, key=key, last_use=stamp)
            self._trie[(parent, key)] = node
            self._by_page[page] = node
            if parent >= 0 and parent in self._by_page:
                self._by_page[parent].children += 1
            absorbed.add(page)
            parent = page
        # Shared pages past the registered walk (can happen only if tokens
        # shrank, which callers never do — defensive deref).
        for i in range(full_pages, seq.num_shared):
            node = self._by_page.get(seq.pages[i])
            if node is not None:
                node.refcount -= 1
        return [
            p for i, p in enumerate(seq.pages)
            if i >= seq.num_shared and p not in absorbed
        ]

    # -- lifecycle ---------------------------------------------------------
    def allocate(
        self, num_tokens: int, prefix_pages: list[int] | None = None
    ) -> int:
        """Allocate pages for a new sequence of ``num_tokens``, reusing
        ``prefix_pages`` (from ``match_prefix``) for its head. Returns
        seq_id. Raises OutOfPages when the pool is exhausted."""
        prefix_pages = prefix_pages or []
        need_total = self.pages_needed(max(1, num_tokens))
        if need_total > self.max_pages_per_seq:
            raise PromptTooLong(
                f"sequence needs {need_total} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq} "
                f"({self.max_pages_per_seq * self.page_size} tokens)"
            )
        shared = [p for p in prefix_pages if p in self._by_page][
            : need_total
        ]
        # Reference the shared chain BEFORE popping fresh pages: with the
        # refcounts at 0 the matched pages themselves would be LRU-eviction
        # candidates while _take_free_page hunts for fresh ones — handing
        # the same physical page out as both prefix and tail.
        for p in shared:
            node = self._by_page[p]
            node.refcount += 1
            node.last_use = next(self._clock)
        need_fresh = need_total - len(shared)
        fresh: list[int] = []
        try:
            for _ in range(need_fresh):
                fresh.append(self._take_free_page())
        except OutOfPages:
            self._free.extend(fresh)
            for p in shared:
                self._by_page[p].refcount -= 1
            raise
        seq = SeqAlloc(self._next_id)
        self._next_id += 1
        seq.pages = shared + fresh
        seq.num_shared = len(shared)
        seq.length = num_tokens
        self._seqs[seq.seq_id] = seq
        self.hit_tokens += len(shared) * self.page_size
        self.miss_tokens += max(
            0, num_tokens - len(shared) * self.page_size
        )
        return seq.seq_id

    def extend(self, seq_id: int, new_tokens: int = 1) -> None:
        """Account for appended tokens, growing by a page when crossing a
        boundary. Raises OutOfPages when the pool is exhausted (caller may
        preempt another sequence and retry)."""
        seq = self._seqs[seq_id]
        target = seq.length + new_tokens
        while len(seq.pages) * self.page_size < target:
            if len(seq.pages) >= self.max_pages_per_seq:
                raise OutOfPages(f"seq {seq_id} hit max_pages_per_seq")
            seq.pages.append(self._take_free_page())
        seq.length = target

    def extend_upto(self, seq_id: int, want: int) -> int:
        """Best-effort ``extend``: grow by as many of ``want`` tokens as the
        per-seq cap and page pool allow; returns the number granted (0 when
        the sequence cannot grow at all). Used by block decode to pre-book
        pages for a whole dispatch, then ``truncate`` back what the device
        did not use."""
        seq = self._seqs[seq_id]
        got = min(want, len(seq.pages) * self.page_size - seq.length)
        seq.length += got
        while got < want:
            if len(seq.pages) >= self.max_pages_per_seq:
                break
            try:
                seq.pages.append(self._take_free_page())
            except OutOfPages:
                break
            take = min(want - got, self.page_size)
            seq.length += take
            got += take
        return got

    def truncate(self, seq_id: int, new_length: int) -> None:
        """Shrink a sequence's accounted length (block-decode rollback of
        pre-booked-but-unused tokens), releasing whole pages that fall past
        the new length. Never touches shared (prefix-trie) pages: truncation
        targets are >= the prompt length, whose pages cover the shared
        chain."""
        seq = self._seqs[seq_id]
        if new_length > seq.length:
            raise ValueError(
                f"truncate to {new_length} > current length {seq.length}"
            )
        seq.length = new_length
        keep = max(self.pages_needed(max(1, new_length)), seq.num_shared)
        while len(seq.pages) > keep:
            self._free.append(seq.pages.pop())

    def free(self, seq_id: int, tokens: list[int] | None = None) -> None:
        """Release a sequence. With ``tokens`` (its full token history) and
        prefix caching on, full pages are donated to the trie instead of
        freed; shared pages are dereferenced either way."""
        seq = self._seqs.pop(seq_id, None)
        if seq is None:
            return
        if self.prefix_cache and tokens is not None:
            self._free.extend(self._register_pages(seq, tokens))
        else:
            for i, p in enumerate(seq.pages):
                if i < seq.num_shared:
                    node = self._by_page.get(p)
                    if node is not None:
                        node.refcount -= 1
                else:
                    self._free.append(p)

    # -- device views ------------------------------------------------------
    def page_table_row(self, seq_id: int) -> np.ndarray:
        """This sequence's page table padded to max_pages_per_seq with -1."""
        row = np.full((self.max_pages_per_seq,), -1, np.int32)
        pages = self._seqs[seq_id].pages
        row[: len(pages)] = pages
        return row

    def batch_views(
        self, seq_ids: list[int], batch_size: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(page_table [batch, MaxP], lengths [batch], active [batch]) for a
        decode batch; unused slots are inactive with empty tables."""
        table = np.full((batch_size, self.max_pages_per_seq), -1, np.int32)
        lengths = np.zeros((batch_size,), np.int32)
        active = np.zeros((batch_size,), bool)
        for i, sid in enumerate(seq_ids):
            if sid is None:
                continue
            table[i] = self.page_table_row(sid)
            lengths[i] = self._seqs[sid].length
            active[i] = True
        return table, lengths, active
