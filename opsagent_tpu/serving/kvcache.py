"""Host-side paged KV-cache bookkeeping.

The device holds the pages (``models.llama.make_cache``); this module owns
the free list and per-sequence page tables. Allocation is O(pages) with a
simple free list — the page count is small (thousands) and allocation happens
once per admitted request plus on page-boundary crossings during decode.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


class OutOfPages(Exception):
    """No free KV pages right now; the scheduler should queue the request."""


class PromptTooLong(Exception):
    """The request can NEVER be admitted (exceeds max_pages_per_seq or the
    largest prefill bucket); fail fast instead of queueing."""


class InvalidRequest(ValueError):
    """Malformed request (client's fault, HTTP 400) — distinct from engine
    bugs that happen to raise ValueError, which must stay 5xx."""


@dataclass
class SeqAlloc:
    seq_id: int
    pages: list[int] = field(default_factory=list)
    length: int = 0  # tokens currently in cache


class PageAllocator:
    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._seqs: dict[int, SeqAlloc] = {}
        self._next_id = 0

    # -- queries -----------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_admit(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= len(self._free)

    def length(self, seq_id: int) -> int:
        return self._seqs[seq_id].length

    # -- lifecycle ---------------------------------------------------------
    def allocate(self, num_tokens: int) -> int:
        """Allocate pages for a new sequence of ``num_tokens``; returns seq_id."""
        need = self.pages_needed(max(1, num_tokens))
        if need > self.max_pages_per_seq:
            raise PromptTooLong(
                f"sequence needs {need} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq} "
                f"({self.max_pages_per_seq * self.page_size} tokens)"
            )
        if need > len(self._free):
            raise OutOfPages(f"need {need} pages, {len(self._free)} free")
        seq = SeqAlloc(self._next_id)
        self._next_id += 1
        for _ in range(need):
            seq.pages.append(self._free.pop())
        seq.length = num_tokens
        self._seqs[seq.seq_id] = seq
        return seq.seq_id

    def extend(self, seq_id: int, new_tokens: int = 1) -> None:
        """Account for appended tokens, growing by a page when crossing a
        boundary. Raises OutOfPages when the pool is exhausted (caller may
        preempt another sequence and retry)."""
        seq = self._seqs[seq_id]
        target = seq.length + new_tokens
        while len(seq.pages) * self.page_size < target:
            if not self._free:
                raise OutOfPages(f"seq {seq_id} needs a page, none free")
            if len(seq.pages) >= self.max_pages_per_seq:
                raise OutOfPages(f"seq {seq_id} hit max_pages_per_seq")
            seq.pages.append(self._free.pop())
        seq.length = target

    def free(self, seq_id: int) -> None:
        seq = self._seqs.pop(seq_id, None)
        if seq is not None:
            self._free.extend(seq.pages)

    # -- device views ------------------------------------------------------
    def page_table_row(self, seq_id: int) -> np.ndarray:
        """This sequence's page table padded to max_pages_per_seq with -1."""
        row = np.full((self.max_pages_per_seq,), -1, np.int32)
        pages = self._seqs[seq_id].pages
        row[: len(pages)] = pages
        return row

    def batch_views(
        self, seq_ids: list[int], batch_size: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(page_table [batch, MaxP], lengths [batch], active [batch]) for a
        decode batch; unused slots are inactive with empty tables."""
        table = np.full((batch_size, self.max_pages_per_seq), -1, np.int32)
        lengths = np.zeros((batch_size,), np.int32)
        active = np.zeros((batch_size,), bool)
        for i, sid in enumerate(seq_ids):
            if sid is None:
                continue
            table[i] = self.page_table_row(sid)
            lengths[i] = self._seqs[sid].length
            active[i] = True
        return table, lengths, active
