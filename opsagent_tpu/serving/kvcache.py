"""Host-side paged KV-cache bookkeeping, with prefix caching.

The device holds the pages (``models.llama.make_cache``); this module owns
the free list, per-sequence page tables, and the **prefix trie**: finished
sequences donate their full pages (keyed by page-aligned token content) so a
later request whose prompt shares the prefix skips re-prefilling it. The
ReAct loop re-sends the whole chat history every iteration (reference
pkg/assistants/simple.go:497-515) — prefix reuse turns that O(n²) re-prefill
into O(n) (SURVEY.md §5 checkpoint note, §7 step 5).

States of a page:
- **free**: on the free list.
- **owned**: exclusively held by a live sequence (its tail / generated pages).
- **shared**: in the trie with refcount = number of live sequences using it.
- **cached**: in the trie with refcount 0 — content retained, evictable LRU
  when the free list runs dry.

Allocation is O(pages) against a free list plus O(prompt/page_size) trie
walks; page counts are small (thousands).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

import numpy as np


class OutOfPages(Exception):
    """No free KV pages right now; the scheduler should queue the request."""


class PromptTooLong(Exception):
    """The request can NEVER be admitted (exceeds max_pages_per_seq or the
    largest prefill bucket); fail fast instead of queueing."""


class InvalidRequest(ValueError):
    """Malformed request (client's fault, HTTP 400) — distinct from engine
    bugs that happen to raise ValueError, which must stay 5xx."""


@dataclass
class SeqAlloc:
    seq_id: int
    pages: list[int] = field(default_factory=list)
    length: int = 0          # tokens currently in cache
    num_shared: int = 0      # leading pages borrowed from the prefix trie


@dataclass
class TrieNode:
    """One cached page: identified by (parent page, its page of tokens)."""

    page: int
    parent: int                      # parent page id, or -1 at the root
    key: tuple[int, ...]             # the page_size tokens this page holds
    refcount: int = 0                # live sequences sharing this page
    children: int = 0                # child nodes (only leaves are evictable)
    last_use: int = 0                # LRU stamp


class PageAllocator:
    def __init__(
        self,
        num_pages: int,
        page_size: int,
        max_pages_per_seq: int,
        prefix_cache: bool = True,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.prefix_cache = prefix_cache
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._seqs: dict[int, SeqAlloc] = {}
        self._next_id = 0
        # Prefix trie: (parent_page, token_tuple) -> TrieNode; page -> node.
        self._trie: dict[tuple[int, tuple[int, ...]], TrieNode] = {}
        self._by_page: dict[int, TrieNode] = {}
        self._clock = itertools.count()
        self.hit_tokens = 0   # cumulative prefix-cache hits (stats)
        self.miss_tokens = 0
        self.evictions = 0    # cumulative trie-leaf evictions (stats)
        # Hierarchical KV tier hooks (serving/offload): ``_spill`` is
        # called with (page, full token chain) right before a trie node's
        # content is dropped from HBM, so the host tier can keep it;
        # ``_host_pool`` is a HostPagePool surfaced through accounting().
        self._spill = None
        self._host_pool = None

    # -- offload tier hooks ------------------------------------------------
    def set_spill(self, fn) -> None:
        """Install the device->host spill hook: ``fn(page, chain_tokens)``
        fires inside every trie eviction BEFORE the page returns to the
        free list (chain_tokens = the page-aligned token prefix whose KV
        the page holds). Exceptions are swallowed — losing a spill only
        costs a future re-prefill, never correctness."""
        self._spill = fn

    def attach_host_pool(self, pool) -> None:
        """Surface a HostPagePool's residency in ``accounting()`` (the
        page-conservation snapshot stays about HBM; host fields ride
        alongside)."""
        self._host_pool = pool

    def _chain_tokens(self, node: TrieNode) -> list[int]:
        """The full page-aligned token prefix ``node``'s page covers,
        reconstructed by walking parent links. Empty when the chain is
        broken (cannot happen for live trie nodes — a parent with children
        is not evictable — but defended anyway)."""
        keys: list[tuple[int, ...]] = []
        cur: TrieNode | None = node
        while cur is not None:
            keys.append(cur.key)
            if cur.parent < 0:
                return [t for k in reversed(keys) for t in k]
            cur = self._by_page.get(cur.parent)
        return []

    def trie_chains(self) -> list[list[int]]:
        """Full page-aligned token chain of every trie-resident page (one
        chain per node, each covering the node and all its ancestors).
        Feeds the fleet registry's prefix digest: the router scores
        longest-cached-prefix affinity against these chains' digests."""
        out: list[list[int]] = []
        for node in self._by_page.values():
            chain = self._chain_tokens(node)
            if chain:
                out.append(chain)
        return out

    # -- queries -----------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free) + sum(
            1 for n in self._by_page.values()
            if n.refcount == 0 and n.children == 0
        )

    def accounting(self) -> dict[str, int]:
        """Page-conservation snapshot: every page is exactly one of free,
        trie-resident (shared or cached), or exclusively owned by a live
        sequence — so free + trie + owned == num_pages always. The
        concurrency stress test asserts this under load (the Python answer
        to the reference's missing `go test -race`, SURVEY section 5)."""
        owned = sum(
            len(s.pages) - s.num_shared for s in self._seqs.values()
        )
        out = {
            "free": len(self._free),
            "trie": len(self._by_page),
            "owned": owned,
            "total": len(self._free) + len(self._by_page) + owned,
        }
        if self._host_pool is not None:
            # Tier-2 residency rides along (NOT part of the HBM page
            # conservation sum — host pages are copies, not allocations).
            st = self._host_pool.stats()
            out["host_pool_pages"] = st["pages"]
            out["host_pool_bytes"] = st["bytes"]
            out["host_pool_capacity_bytes"] = st["capacity_bytes"]
        return out

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_admit(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= self.free_pages

    def length(self, seq_id: int) -> int:
        return self._seqs[seq_id].length

    # -- prefix trie -------------------------------------------------------
    def match_prefix(self, tokens: list[int]) -> list[int]:
        """Longest cached page-aligned prefix of ``tokens``; returns the page
        ids WITHOUT taking references (call ``allocate`` with the result)."""
        if not self.prefix_cache:
            return []
        P = self.page_size
        stamp = next(self._clock)
        pages: list[int] = []
        parent = -1
        for i in range(len(tokens) // P):
            node = self._trie.get((parent, tuple(tokens[i * P:(i + 1) * P])))
            if node is None:
                break
            node.last_use = stamp  # matched chains are fresh, not LRU bait
            pages.append(node.page)
            parent = node.page
        return pages

    def _take_free_page(self) -> int:
        """Pop a free page, evicting the LRU unreferenced trie leaf if the
        free list is dry. Raises OutOfPages when nothing is evictable."""
        if self._free:
            return self._free.pop()
        victim: TrieNode | None = None
        for node in self._by_page.values():
            if node.refcount == 0 and node.children == 0:
                if victim is None or node.last_use < victim.last_use:
                    victim = node
        if victim is None:
            raise OutOfPages("no free pages and no evictable cached pages")
        self._evict(victim)
        return self._free.pop()

    def _evict(self, node: TrieNode) -> None:
        if self._spill is not None:
            # Host tier: copy the content out before the page is reused.
            # The chain is reconstructed BEFORE the node leaves the trie.
            try:
                chain = self._chain_tokens(node)
                if chain:
                    self._spill(node.page, chain)
            except Exception:  # noqa: BLE001 - offload is best-effort
                pass
        self.evictions += 1
        del self._trie[(node.parent, node.key)]
        del self._by_page[node.page]
        if node.parent >= 0 and node.parent in self._by_page:
            self._by_page[node.parent].children -= 1
        self._free.append(node.page)

    def evict_chain(self, pages: list[int]) -> int:
        """Evict a matched prefix chain (``match_prefix`` result) AND the
        trie subtree hanging off its tail, spilling every page through the
        offload hook; used by tool-time parking to free HBM a blocked
        session will not touch for seconds. The subtree matters because
        the parked history is RE-tokenized from chat messages: the
        generated turn's content usually re-renders to different token
        ids than the engine emitted, so the session's own generated pages
        sit BELOW the matched chain as a divergent continuation — exactly
        the pages parking exists to free. Pages another live sequence
        still references (refcount > 0) are left in place, as is
        everything above them. Returns pages evicted."""
        if not pages:
            return 0
        kids: dict[int, list[int]] = {}
        for node in self._by_page.values():
            kids.setdefault(node.parent, []).append(node.page)
        n = 0

        def _evict_down(page: int) -> bool:
            nonlocal n
            node = self._by_page.get(page)
            if node is None:
                return True
            clear = True
            for c in kids.get(page, ()):
                clear = _evict_down(c) and clear
            if clear and node.refcount == 0 and node.children == 0:
                self._evict(node)
                n += 1
                return True
            return False

        # Tail's whole subtree first (leaf-first), then the chain upward.
        if not _evict_down(pages[-1]):
            return n
        for p in reversed(pages[:-1]):
            node = self._by_page.get(p)
            if node is None or node.refcount > 0 or node.children > 0:
                break
            self._evict(node)
            n += 1
        return n

    def promote_prefix(self, seq_id: int, tokens: list[int]) -> int:
        """Register a LIVE sequence's leading full pages into the prefix
        trie as shared references (extending ``num_shared``), so pages
        just restored from the host tier become prefix hits for concurrent
        admissions immediately — not only after the sequence finishes.
        ``tokens`` bounds the promotion (its full pages). Stops early if
        an equal-content chain already exists under a DIFFERENT page (a
        live page table cannot be rewritten to dedup). Returns the number
        of pages promoted."""
        if not self.prefix_cache:
            return 0
        seq = self._seqs[seq_id]
        P = self.page_size
        stamp = next(self._clock)
        full = min(len(tokens) // P, len(seq.pages))
        parent = -1 if seq.num_shared == 0 else seq.pages[seq.num_shared - 1]
        promoted = 0
        for i in range(seq.num_shared, full):
            key = tuple(tokens[i * P : (i + 1) * P])
            page = seq.pages[i]
            node = self._trie.get((parent, key))
            if node is not None and node.page != page:
                break
            if node is None:
                node = TrieNode(
                    page=page, parent=parent, key=key,
                    refcount=1, last_use=stamp,
                )
                self._trie[(parent, key)] = node
                self._by_page[page] = node
                if parent >= 0 and parent in self._by_page:
                    self._by_page[parent].children += 1
            else:
                node.refcount += 1
                node.last_use = stamp
            seq.num_shared = i + 1
            promoted += 1
            parent = page
        return promoted

    def pages_of(self, seq_id: int) -> list[int]:
        """Snapshot of a sequence's page list (restore targeting)."""
        return list(self._seqs[seq_id].pages)

    def _register_pages(self, seq: SeqAlloc, tokens: list[int]) -> list[int]:
        """Donate a finished sequence's full pages to the trie; returns the
        pages to put back on the free list (duplicates of already-cached
        content, the partial last page, over-allocated pages). ``tokens`` =
        the sequence's full token history (prompt + generated).

        Invariant: pages[0:num_shared] ARE trie nodes we hold a reference on
        (matched at admission against these exact tokens), so the walk just
        releases those references; owned full pages either become new trie
        nodes (kept) or are duplicates of a concurrently-registered chain
        (freed)."""
        P = self.page_size
        stamp = next(self._clock)
        full_pages = min(len(tokens) // P, len(seq.pages))
        absorbed: set[int] = set()
        parent = -1
        for i in range(full_pages):
            key = tuple(tokens[i * P:(i + 1) * P])
            page = seq.pages[i]
            if i < seq.num_shared:
                node = self._by_page[page]   # we hold a ref: cannot be evicted
                node.refcount -= 1
                node.last_use = stamp
                parent = page
                continue
            node = self._trie.get((parent, key))
            if node is not None:
                # Same content already cached by someone else: our page is a
                # duplicate — follow the canonical chain, free ours.
                node.last_use = stamp
                parent = node.page
                continue
            node = TrieNode(page=page, parent=parent, key=key, last_use=stamp)
            self._trie[(parent, key)] = node
            self._by_page[page] = node
            if parent >= 0 and parent in self._by_page:
                self._by_page[parent].children += 1
            absorbed.add(page)
            parent = page
        # Shared pages past the registered walk (can happen only if tokens
        # shrank, which callers never do — defensive deref).
        for i in range(full_pages, seq.num_shared):
            node = self._by_page.get(seq.pages[i])
            if node is not None:
                node.refcount -= 1
        return [
            p for i, p in enumerate(seq.pages)
            if i >= seq.num_shared and p not in absorbed
        ]

    # -- lifecycle ---------------------------------------------------------
    def allocate(
        self, num_tokens: int, prefix_pages: list[int] | None = None
    ) -> int:
        """Allocate pages for a new sequence of ``num_tokens``, reusing
        ``prefix_pages`` (from ``match_prefix``) for its head. Returns
        seq_id. Raises OutOfPages when the pool is exhausted."""
        prefix_pages = prefix_pages or []
        need_total = self.pages_needed(max(1, num_tokens))
        if need_total > self.max_pages_per_seq:
            raise PromptTooLong(
                f"sequence needs {need_total} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq} "
                f"({self.max_pages_per_seq * self.page_size} tokens)"
            )
        shared = [p for p in prefix_pages if p in self._by_page][
            : need_total
        ]
        # Reference the shared chain BEFORE popping fresh pages: with the
        # refcounts at 0 the matched pages themselves would be LRU-eviction
        # candidates while _take_free_page hunts for fresh ones — handing
        # the same physical page out as both prefix and tail.
        for p in shared:
            node = self._by_page[p]
            node.refcount += 1
            node.last_use = next(self._clock)
        need_fresh = need_total - len(shared)
        fresh: list[int] = []
        try:
            for _ in range(need_fresh):
                fresh.append(self._take_free_page())
        except OutOfPages:
            self._free.extend(fresh)
            for p in shared:
                self._by_page[p].refcount -= 1
            raise
        seq = SeqAlloc(self._next_id)
        self._next_id += 1
        seq.pages = shared + fresh
        seq.num_shared = len(shared)
        seq.length = num_tokens
        self._seqs[seq.seq_id] = seq
        self.hit_tokens += len(shared) * self.page_size
        self.miss_tokens += max(
            0, num_tokens - len(shared) * self.page_size
        )
        return seq.seq_id

    def extend(self, seq_id: int, new_tokens: int = 1) -> None:
        """Account for appended tokens, growing by a page when crossing a
        boundary. Raises OutOfPages when the pool is exhausted (caller may
        preempt another sequence and retry)."""
        seq = self._seqs[seq_id]
        target = seq.length + new_tokens
        while len(seq.pages) * self.page_size < target:
            if len(seq.pages) >= self.max_pages_per_seq:
                raise OutOfPages(f"seq {seq_id} hit max_pages_per_seq")
            seq.pages.append(self._take_free_page())
        seq.length = target

    def extend_upto(self, seq_id: int, want: int) -> int:
        """Best-effort ``extend``: grow by as many of ``want`` tokens as the
        per-seq cap and page pool allow; returns the number granted (0 when
        the sequence cannot grow at all). Used by block decode to pre-book
        pages for a whole dispatch, then ``truncate`` back what the device
        did not use."""
        seq = self._seqs[seq_id]
        got = min(want, len(seq.pages) * self.page_size - seq.length)
        seq.length += got
        while got < want:
            if len(seq.pages) >= self.max_pages_per_seq:
                break
            try:
                seq.pages.append(self._take_free_page())
            except OutOfPages:
                break
            take = min(want - got, self.page_size)
            seq.length += take
            got += take
        return got

    def truncate(self, seq_id: int, new_length: int) -> None:
        """Shrink a sequence's accounted length (block-decode rollback of
        pre-booked-but-unused tokens), releasing whole pages that fall past
        the new length. Never touches shared (prefix-trie) pages: truncation
        targets are >= the prompt length, whose pages cover the shared
        chain."""
        seq = self._seqs[seq_id]
        if new_length > seq.length:
            raise ValueError(
                f"truncate to {new_length} > current length {seq.length}"
            )
        seq.length = new_length
        keep = max(self.pages_needed(max(1, new_length)), seq.num_shared)
        while len(seq.pages) > keep:
            self._free.append(seq.pages.pop())

    def free(self, seq_id: int, tokens: list[int] | None = None) -> None:
        """Release a sequence. With ``tokens`` (its full token history) and
        prefix caching on, full pages are donated to the trie instead of
        freed; shared pages are dereferenced either way."""
        seq = self._seqs.pop(seq_id, None)
        if seq is None:
            return
        if self.prefix_cache and tokens is not None:
            self._free.extend(self._register_pages(seq, tokens))
        else:
            for i, p in enumerate(seq.pages):
                if i < seq.num_shared:
                    node = self._by_page.get(p)
                    if node is not None:
                        node.refcount -= 1
                else:
                    self._free.append(p)

    # -- device views ------------------------------------------------------
    def page_table_row(self, seq_id: int) -> np.ndarray:
        """This sequence's page table padded to max_pages_per_seq with -1."""
        row = np.full((self.max_pages_per_seq,), -1, np.int32)
        pages = self._seqs[seq_id].pages
        row[: len(pages)] = pages
        return row

    def batch_views(
        self, seq_ids: list[int], batch_size: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(page_table [batch, MaxP], lengths [batch], active [batch]) for a
        decode batch; unused slots are inactive with empty tables."""
        table = np.full((batch_size, self.max_pages_per_seq), -1, np.int32)
        lengths = np.zeros((batch_size,), np.int32)
        active = np.zeros((batch_size,), bool)
        for i, sid in enumerate(seq_ids):
            if sid is None:
                continue
            table[i] = self.page_table_row(sid)
            lengths[i] = self._seqs[sid].length
            active[i] = True
        return table, lengths, active
