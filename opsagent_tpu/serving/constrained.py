"""Constrained decoding: JSON-schema → byte DFA → token-level logit masks.

The reference keeps its ReAct loop alive with a JSON-repair ladder
(reference pkg/utils/json.go:16-190 and the 4-stage tolerant parse in
pkg/handlers/execute.go:250-404) because remote models emit broken JSON.
This module deletes that failure class at the source: the serving engine
masks logits each decode step so the model can only emit bytes a JSON
schema's automaton accepts (SURVEY.md §7 step 6).

Pipeline:

1. A tiny regex AST (literal byte-sets, sequence, alternation, repetition)
   compiled via Thompson construction + subset construction into a byte-level
   DFA. JSON nesting is context-free, but bounding the depth (default 4
   for schemaless json_object — the NFA grows ~4^depth since objects and
   arrays each embed two copies of the inner value; schema-guided DFAs stay
   tiny) makes the language regular — the schema compiler unrolls nesting.
2. ``TokenFSM`` lifts the DFA to the tokenizer's vocabulary: for a DFA state
   the set of admissible tokens is "every byte of the token survives the
   DFA". Masks are computed lazily per state and cached — generation loops
   through a handful of distinct states (string-body states self-loop), so
   the cache stays tiny even for 128k-token vocabularies. A native C++
   matcher can precompute the full [states, vocab] table; this module is the
   reference implementation and fallback.
3. ``JsonConstraint`` is the engine-facing ``mask_fn`` (Engine.add_request's
   hook): feed it the generated-token list, get a [vocab] bool mask. EOS is
   admissible exactly in accepting states.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Iterable

import numpy as np

# -- regex AST --------------------------------------------------------------
# Nodes: ("lit", frozenset[int]) | ("seq", [n...]) | ("alt", [n...])
#        | ("star", n) | ("opt", n) | ("plus", n)

Node = tuple


def lit(chars: Iterable[int] | bytes | str) -> Node:
    if isinstance(chars, str):
        chars = chars.encode("utf-8")
    return ("lit", frozenset(chars))


def text(s: str) -> Node:
    return ("seq", [lit(bytes([b])) for b in s.encode("utf-8")])


def seq(*nodes: Node) -> Node:
    return ("seq", list(nodes))


def alt(*nodes: Node) -> Node:
    return ("alt", list(nodes))


def star(node: Node) -> Node:
    return ("star", node)


def opt(node: Node) -> Node:
    return ("opt", node)


def plus(node: Node) -> Node:
    return ("plus", node)


# -- NFA (Thompson) ---------------------------------------------------------
@dataclass
class _NFA:
    # transitions[state] = list of (byteset | None for epsilon, next_state)
    transitions: list[list[tuple[frozenset | None, int]]] = field(
        default_factory=list
    )

    def new_state(self) -> int:
        self.transitions.append([])
        return len(self.transitions) - 1

    def add(self, s: int, byteset: frozenset | None, t: int) -> None:
        self.transitions[s].append((byteset, t))


def _build(nfa: _NFA, node: Node) -> tuple[int, int]:
    """Compile a node; returns (start, end) NFA states."""
    kind = node[0]
    if kind == "lit":
        s, e = nfa.new_state(), nfa.new_state()
        nfa.add(s, node[1], e)
        return s, e
    if kind == "seq":
        s = e = nfa.new_state()
        for child in node[1]:
            cs, ce = _build(nfa, child)
            nfa.add(e, None, cs)
            e = ce
        return s, e
    if kind == "alt":
        s, e = nfa.new_state(), nfa.new_state()
        for child in node[1]:
            cs, ce = _build(nfa, child)
            nfa.add(s, None, cs)
            nfa.add(ce, None, e)
        return s, e
    if kind in ("star", "opt", "plus"):
        cs, ce = _build(nfa, node[1])
        s, e = nfa.new_state(), nfa.new_state()
        nfa.add(s, None, cs)
        if kind != "plus":
            nfa.add(s, None, e)
        nfa.add(ce, None, e)
        if kind != "opt":
            nfa.add(ce, None, cs)
        return s, e
    raise ValueError(f"unknown regex node {kind!r}")


# -- DFA (subset construction) ----------------------------------------------
@dataclass
class ByteDFA:
    """Dense byte-level DFA: next[state*256 + byte] -> state or -1 (dead)."""

    next: np.ndarray          # [num_states * 256] int32
    accept: np.ndarray        # [num_states] bool
    start: int = 0

    @property
    def num_states(self) -> int:
        return len(self.accept)

    def step(self, state: int, byte: int) -> int:
        if state < 0:
            return -1
        return int(self.next[state * 256 + byte])

    def run(self, state: int, data: bytes) -> int:
        for b in data:
            state = self.step(state, b)
            if state < 0:
                return -1
        return state


def compile_regex(node: Node) -> ByteDFA:
    nfa = _NFA()
    start, end = _build(nfa, node)

    def eclose(states: frozenset[int]) -> frozenset[int]:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for byteset, t in nfa.transitions[s]:
                if byteset is None and t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = eclose(frozenset([start]))
    state_ids: dict[frozenset, int] = {start_set: 0}
    worklist = [start_set]
    rows: list[np.ndarray] = []
    accept: list[bool] = []
    while worklist:
        cur = worklist.pop()
        sid = state_ids[cur]
        while len(rows) <= sid:
            rows.append(np.full((256,), -1, np.int32))
            accept.append(False)
        accept[sid] = end in cur
        # Group reachable targets per byte.
        per_byte: dict[int, set[int]] = {}
        for s in cur:
            for byteset, t in nfa.transitions[s]:
                if byteset is None:
                    continue
                for b in byteset:
                    per_byte.setdefault(b, set()).add(t)
        for b, targets in per_byte.items():
            tset = eclose(frozenset(targets))
            if tset not in state_ids:
                state_ids[tset] = len(state_ids)
                worklist.append(tset)
            rows[sid][b] = state_ids[tset]
    # Worklist order may have appended rows out of order; normalize.
    n = len(state_ids)
    nxt = np.full((n, 256), -1, np.int32)
    acc = np.zeros((n,), bool)
    for sset, sid in state_ids.items():
        if sid < len(rows):
            nxt[sid] = rows[sid]
            acc[sid] = accept[sid]
    return ByteDFA(next=nxt.reshape(-1), accept=acc, start=0)


# -- JSON schema → regex ----------------------------------------------------
# Inter-token whitespace, bounded to at most 2 chars (not star): models
# emit compact or single-space JSON, and an unbounded run both lets a
# degenerate/adversarial decode burn its whole budget on "\n\n\n..." and
# inflates the DFA. Legal-JSON *parsing* is unaffected — this grammar
# only shapes what we GENERATE.
_WS = seq(opt(lit(b" \t\n\r")), opt(lit(b" \t\n\r")))

# String body: any byte except '"', '\' and C0 controls, or an escape.
_STRING_CHAR = lit(frozenset(range(0x20, 0x100)) - {0x22, 0x5C})
_ESCAPE = seq(
    lit(b"\\"),
    alt(
        lit(b'"\\/bfnrt'),
        seq(lit(b"u"), *([lit(b"0123456789abcdefABCDEF")] * 4)),
    ),
)
_STRING = seq(lit(b'"'), star(alt(_STRING_CHAR, _ESCAPE)), lit(b'"'))
_NUMBER = seq(
    opt(lit(b"-")),
    alt(lit(b"0"), seq(lit(b"123456789"), star(lit(b"0123456789")))),
    opt(seq(lit(b"."), plus(lit(b"0123456789")))),
    opt(seq(lit(b"eE"), opt(lit(b"+-")), plus(lit(b"0123456789")))),
)
_BOOL = alt(text("true"), text("false"))
_NULL = text("null")


def _json_value(depth: int) -> Node:
    """Any JSON value with nesting bounded at ``depth``."""
    leaves = [_STRING, _NUMBER, _BOOL, _NULL]
    if depth <= 0:
        return alt(*leaves)
    inner = _json_value(depth - 1)
    obj = seq(
        lit(b"{"), _WS,
        opt(seq(
            _STRING, _WS, lit(b":"), _WS, inner,
            star(seq(_WS, lit(b","), _WS, _STRING, _WS, lit(b":"), _WS, inner)),
        )),
        _WS, lit(b"}"),
    )
    arr = seq(
        lit(b"["), _WS,
        opt(seq(inner, star(seq(_WS, lit(b","), _WS, inner)))),
        _WS, lit(b"]"),
    )
    return alt(*leaves, obj, arr)


def schema_to_regex(schema: dict[str, Any] | None, depth: int = 4) -> Node:
    """JSON-schema subset → regex. Supported: type object (properties in
    declaration order, all listed properties required), string, number,
    integer, boolean, null, array (items), enum (of strings), and {} / None
    meaning "any JSON value"."""
    if not schema:
        return _json_value(depth)
    if "enum" in schema:
        opts = [text(json_quote(v)) for v in schema["enum"]]
        return alt(*opts)
    t = schema.get("type")
    if t == "object" or (t is None and "properties" in schema):
        props = schema.get("properties", {})
        if not props:
            return _json_value(depth)
        parts: list[Node] = [lit(b"{"), _WS]
        for i, (key, sub) in enumerate(props.items()):
            if i:
                parts += [_WS, lit(b","), _WS]
            parts += [
                text(f'"{key}"'), _WS, lit(b":"), _WS,
                schema_to_regex(sub, depth - 1),
            ]
        parts += [_WS, lit(b"}")]
        return seq(*parts)
    if t == "array":
        inner = schema_to_regex(schema.get("items"), depth - 1)
        rest = star(seq(_WS, lit(b","), _WS, inner))
        body = seq(inner, rest)
        if not schema.get("minItems"):
            body = opt(body)  # minItems >= 1 forbids the empty array
        return seq(lit(b"["), _WS, body, _WS, lit(b"]"))
    if t == "string":
        return _STRING
    if t in ("number", "integer"):
        return _NUMBER
    if t == "boolean":
        return _BOOL
    if t == "null":
        return _NULL
    return _json_value(depth)


def json_quote(value: Any) -> str:
    import json

    return json.dumps(value)


# -- Token-level FSM --------------------------------------------------------
# The native matcher eagerly materializes [states, vocab] mask + destination
# tables (~5 bytes/entry). A schemaless json_object DFA is ~14k states; at a
# real 131k vocab that would be multi-GB, triggered by one API request — so
# the eager path is gated on a total-entries budget and everything larger
# stays on the lazy per-state numpy path (states actually visited during a
# generation number in the dozens).
NATIVE_TABLE_BUDGET = int(
    os.environ.get("OPSAGENT_FSM_NATIVE_BUDGET", 64_000_000)
)

# Longest forced run the fast-forward path will splice in one dispatch.
# Bounds both the precompute loop (a cyclic grammar could force forever)
# and the q_len of the multi-token append, which must fit a mixed bucket.
FORCED_RUN_CAP = int(os.environ.get("OPSAGENT_FFWD_RUN_CAP", 12))


class TokenFSM:
    """Lifts a byte DFA to token-level masks over a tokenizer vocabulary.

    Masks are computed lazily per DFA state and cached — but each state's
    mask is VECTORIZED over the vocabulary (token bytes packed into a dense
    [vocab, maxlen] matrix, advanced one byte-position per numpy op), so a
    cold state costs milliseconds even at 128k tokens. That matters because
    the engine asks for masks while holding its dispatch lock. The C++
    matcher in ``opsagent_tpu.native`` precomputes the same tables eagerly."""

    def __init__(self, dfa: ByteDFA, token_bytes: list[bytes], eos_id: int):
        self.dfa = dfa
        self.token_bytes = token_bytes
        self.eos_id = eos_id
        self.vocab_size = len(token_bytes)
        self._mask_cache: dict[int, np.ndarray] = {}
        self._dense: tuple[np.ndarray, np.ndarray] | None = None
        self._forced: dict[int, int | None] = {}
        self._forced_runs: dict[int, list[int]] = {}
        self._forced_table: tuple[np.ndarray, np.ndarray] | None = None
        self._lens = np.array([len(tb) for tb in token_bytes], np.int32)
        maxlen = max(1, int(self._lens.max()))
        self._bytes = np.zeros((self.vocab_size, maxlen), np.int32)
        for tid, tb in enumerate(token_bytes):
            if tb:
                self._bytes[tid, : len(tb)] = np.frombuffer(tb, np.uint8)
        # Native C++ tables when available AND within the memory budget:
        # full eager precompute, O(row copy) per step. Falls back to the
        # lazy numpy path silently.
        self._native = None
        if dfa.num_states * self.vocab_size <= NATIVE_TABLE_BUDGET:
            try:
                from ..native import NativeFSMTables, get_lib

                if get_lib() is not None:
                    self._native = NativeFSMTables(
                        dfa.next, dfa.accept, token_bytes, eos_id
                    )
            except Exception:  # noqa: BLE001 - fallback is always correct
                self._native = None

    def _walk(self, state: int) -> tuple[np.ndarray, np.ndarray]:
        """THE vectorized byte-walk: ([V] allow-mask, [V] final DFA state)
        for one source state. Single source of truth for the lazy host
        masks AND the dense device tables."""
        nxt = self.dfa.next
        st = np.full((self.vocab_size,), state, np.int32)
        alive = self._lens > 0  # empty byte-strings (specials) forbidden
        for j in range(self._bytes.shape[1]):
            has = j < self._lens
            step = alive & has
            idx = np.where(step, st, 0) * 256 + self._bytes[:, j]
            st = np.where(step, nxt[idx], st)
            alive &= ~has | (st >= 0)
        mask = alive
        if self.dfa.accept[state]:
            mask = mask.copy()
            mask[self.eos_id] = True
        return mask, st

    def mask_for_state(self, state: int) -> np.ndarray:
        if self._native is not None:
            return self._native.mask_for_state(state)
        cached = self._mask_cache.get(state)
        if cached is not None:
            return cached
        mask = np.zeros((self.vocab_size,), bool)
        if state >= 0:
            mask, _ = self._walk(state)
        self._mask_cache[state] = mask
        return mask

    def advance(self, state: int, token_id: int) -> int:
        if self._native is not None:
            return self._native.advance(state, token_id)
        return self.dfa.run(state, self.token_bytes[token_id])

    def _mask_dest_row(self, state: int) -> tuple[np.ndarray, np.ndarray]:
        """One state's (allow-mask [V], destination [V]) for the device
        tables, from the shared ``_walk``. Disallowed tokens' dest is 0
        (never taken — the mask blocks them); the eos column stays in
        place (eos ends generation, it is never advanced through the
        DFA). Seeds the host mask cache as a side effect."""
        mask, st = self._walk(state)
        if self._native is None:
            self._mask_cache.setdefault(state, mask)
        # Device-table numbering: DFA state s lives at row s+1 (row 0 is
        # the FREE sentinel), so shift destinations by one.
        dest = np.where(mask, np.maximum(st, 0) + 1, 0).astype(np.int32)
        dest[self.eos_id] = state + 1
        return mask, dest

    def dense_tables(self) -> tuple[np.ndarray, np.ndarray] | None:
        """Full ([S+1, V] allow-mask, [S+1, V] dest) tables for DEVICE-side
        FSM stepping inside the fused decode block (SURVEY §7's
        "constrained-decode FSM on device without host sync per token").
        ROW 0 is the FREE sentinel — everything allowed, dest = 0 — so
        unconstrained rows sharing the batch ride at the zero-initialized
        state no matter which schema's tables are loaded; DFA state s is
        row s+1. Returns None when the tables exceed the memory budget
        (the lazy host path still works). Built once and cached."""
        if self._dense is not None:
            return self._dense
        S, V = self.dfa.num_states, self.vocab_size
        # Same total-entries budget (and so the same operator knob) as the
        # native-table gate above.
        if (S + 1) * V > NATIVE_TABLE_BUDGET:
            return None
        mask = np.zeros((S + 1, V), bool)
        dest = np.zeros((S + 1, V), np.int32)
        mask[0] = True
        for s in range(S):
            mask[s + 1], dest[s + 1] = self._mask_dest_row(s)
        self._dense = (mask, dest)
        return self._dense

    # -- Forced-token fast-forward ------------------------------------
    # A state whose mask admits exactly ONE token is a speculator with
    # acceptance = 1.0 by construction: the masked sample produces that
    # token at ANY temperature (softmax over a single admissible logit),
    # so the engine may emit it without a forward pass. Runs of such
    # states — JSON punctuation, known key names, enum close-quotes —
    # are precomputed here and spliced in one multi-token dispatch.

    def forced_token(self, state: int) -> int | None:
        """The single legal token at ``state``, or None when the mask
        admits zero or several. Cached per state."""
        if state in self._forced:
            return self._forced[state]
        tok: int | None = None
        if state >= 0:
            idx = np.flatnonzero(self.mask_for_state(state))
            if idx.size == 1:
                tok = int(idx[0])
        self._forced[state] = tok
        return tok

    def forced_run(self, state: int) -> list[int]:
        """The maximal run of forced tokens starting at ``state``, capped
        at FORCED_RUN_CAP. A forced eos ends the run (inclusive) — there
        is no state after eos. Empty when the state's mask is not a
        singleton. Cached per state."""
        cached = self._forced_runs.get(state)
        if cached is not None:
            return cached
        run: list[int] = []
        st = state
        while len(run) < FORCED_RUN_CAP:
            tok = self.forced_token(st)
            if tok is None:
                break
            run.append(tok)
            if tok == self.eos_id:
                break
            st = self.advance(st, tok)
            if st < 0:  # defensive: a forced token never leaves the DFA
                break
        self._forced_runs[state] = run
        return run

    def forced_run_table(self) -> tuple[np.ndarray, np.ndarray] | None:
        """Dense ([S+1, L] int32 token-ids, [S+1] int32 run-lengths) in
        the device-table numbering of ``dense_tables`` (row 0 = FREE
        sentinel, no run; DFA state s at row s+1), for device residency
        alongside the mask/dest tables. None under the same memory
        budget gate. Built once and cached."""
        if self._forced_table is not None:
            return self._forced_table
        if self.dense_tables() is None:
            return None
        S = self.dfa.num_states
        L = max(1, FORCED_RUN_CAP)
        toks = np.zeros((S + 1, L), np.int32)
        lens = np.zeros((S + 1,), np.int32)
        for s in range(S):
            run = self.forced_run(s)
            lens[s + 1] = len(run)
            toks[s + 1, : len(run)] = run
        self._forced_table = (toks, lens)
        return self._forced_table


class JsonConstraint:
    """Engine-facing ``mask_fn``: tracks DFA state incrementally across the
    generated-token list the engine passes each step."""

    def __init__(self, fsm: TokenFSM):
        self.fsm = fsm
        self._state = fsm.dfa.start
        self._consumed = 0

    def __call__(self, tokens: list[int]) -> np.ndarray:
        return self.fsm.mask_for_state(self.dfa_state(tokens))

    def dfa_state(self, tokens: list[int]) -> int:
        """The DFA state after consuming ``tokens`` — the same
        incremental sync ``__call__`` performs, without building a mask.
        The fast-forward planner uses this to ask for forced runs."""
        if len(tokens) < self._consumed:  # new sequence reusing the object
            self._state, self._consumed = self.fsm.dfa.start, 0
        for tok in tokens[self._consumed:]:
            if tok != self.fsm.eos_id:
                self._state = self.fsm.advance(self._state, tok)
        self._consumed = len(tokens)
        return self._state

    def forced_run(self, tokens: list[int]) -> list[int]:
        """The forced run from the state after ``tokens`` (possibly
        empty; capped at FORCED_RUN_CAP; a forced eos ends it)."""
        st = self.dfa_state(tokens)
        return [] if st < 0 else self.fsm.forced_run(st)


def device_table_fsm(mask_fn) -> TokenFSM | None:
    """The TokenFSM behind an engine ``mask_fn`` when — and only when —
    its dense device tables are available: the eligibility test for the
    async mixed lane (serving/async_runtime.py), where the grammar mask
    must come from ON-DEVICE state because the row's sampled tokens are
    never on host at mask time. Plain-callable masks (including the
    salvage wrappers the scheduler installs after a restart/park) and
    schemas whose tables exceed the memory budget return None — those
    rows ride the hosted/split lane instead."""
    if not isinstance(mask_fn, JsonConstraint):
        return None
    return mask_fn.fsm if mask_fn.fsm.dense_tables() is not None else None


# Client-supplied schemas each pin a compiled TokenFSM ([vocab, maxlen]
# byte matrix + per-state masks), so the per-tokenizer cache is a bounded
# LRU, and schemas whose DFA explodes are rejected up front (the API maps
# ValueError to HTTP 400).
FSM_CACHE_CAPACITY = int(os.environ.get("OPSAGENT_FSM_CACHE_CAPACITY", 8))
MAX_DFA_STATES = int(os.environ.get("OPSAGENT_FSM_MAX_STATES", 100_000))


def json_constraint(
    tokenizer,
    schema: dict[str, Any] | None = None,
    depth: int = 4,
) -> JsonConstraint:
    """Build a fresh per-request constraint; the underlying TokenFSM is
    cached per (schema, depth) ON the tokenizer object itself — a bounded
    LRU, so varied client schemas cannot grow memory without limit — and
    dies with its tokenizer (a global keyed on id() could go stale when
    CPython reuses a freed object's address). Cache bookkeeping runs under
    a per-tokenizer lock (API requests hit this path from many threads);
    the slow DFA/table compile runs OUTSIDE it — two racing compiles of
    the same schema waste one compile, never correctness."""
    import json
    import threading

    lock = tokenizer.__dict__.setdefault("_fsm_lock", threading.Lock())
    cache = tokenizer.__dict__.setdefault("_fsm_cache", {})
    key = (json.dumps(schema, sort_keys=True), depth)
    with lock:
        fsm = cache.pop(key, None)
        if fsm is not None:
            cache[key] = fsm  # reinsert at the back = most recently used
    if fsm is None:
        dfa = compile_regex(schema_to_regex(schema, depth))
        if dfa.num_states > MAX_DFA_STATES:
            raise ValueError(
                f"json schema compiles to {dfa.num_states} DFA states "
                f"(limit {MAX_DFA_STATES}); simplify the schema or reduce "
                f"nesting depth"
            )
        tb = [tokenizer.token_bytes(t) for t in range(tokenizer.vocab_size)]
        fsm = TokenFSM(dfa, tb, tokenizer.eos_id)
        with lock:
            cache[key] = fsm
            while len(cache) > FSM_CACHE_CAPACITY:
                cache.pop(next(iter(cache)), None)
    return JsonConstraint(fsm)


# The ReAct wire format the agent loop speaks (reference pkg/tools/tool.go:29-38).
TOOLPROMPT_SCHEMA: dict[str, Any] = {
    "type": "object",
    "properties": {
        "question": {"type": "string"},
        "thought": {"type": "string"},
        "action": {
            "type": "object",
            "properties": {
                "name": {"type": "string"},
                "input": {"type": "string"},
            },
        },
        "observation": {"type": "string"},
        "final_answer": {"type": "string"},
    },
}
