"""The serving engine: jitted prefill/decode over a paged KV cache.

This is the compute core that replaces the reference's remote LLM round trip
(reference pkg/assistants/simple.go:343,515 -> pkg/llms/openai.go:69). Design
points (SURVEY.md section 7):

- **Two XLA programs**: prefill (one sequence, bucketed lengths) and a
  fixed-batch decode step. Static shapes only — bucketing avoids
  recompilation; the decode batch is padded with inactive slots.
- **Paged KV cache**: device pages + host PageAllocator; the cache pytree is
  donated through every call, so it lives in HBM with no copies.
- **Tensor parallelism**: params/cache placed with NamedShardings over the
  (dp, sp, tp) mesh; jit propagates, XLA emits the ICI collectives.
- **Greedy-by-default sampling** on device, with per-request temperature /
  top-k / top-p and a constrained-decoding mask hook.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from ..models.config import ModelConfig, get_config_preset
from ..parallel.mesh import make_mesh, shard_params
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats
from .kvcache import InvalidRequest, PageAllocator, OutOfPages
from .sampler import SamplingParams, sample
from .tokenizer import Tokenizer, load_tokenizer

log = get_logger("engine")


@dataclass
class EngineConfig:
    model: str = "tiny-test"
    checkpoint: str = ""
    tokenizer: str = ""
    dtype: Any = jnp.bfloat16
    tp: int = 0                      # 0 = all devices
    dp: int = 1
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 320   # 5120 tokens: largest bucket + generation
    max_batch_size: int = 8
    # Decode steps fused into one device dispatch (1 = step-at-a-time).
    # Each dispatch costs a host->device round trip plus ONE device->host
    # token pull, so per-token overhead scales as RTT / decode_block.
    decode_block: int = 16
    prefill_buckets: tuple[int, ...] = (64, 128, 256, 512, 1024, 2048, 4096)
    max_new_tokens_default: int = 1024
    seed: int = 0
    prefix_cache: bool = True


@dataclass
class Sequence:
    """Host-side state of one in-flight generation."""

    seq_id: int
    prompt_len: int
    prompt_ids: list[int] = field(default_factory=list)
    tokens: list[int] = field(default_factory=list)   # generated tokens
    params: SamplingParams = field(default_factory=SamplingParams)
    done: bool = False
    finish_reason: str = ""        # "stop" | "length" | "preempted"
    mask_fn: Callable[[list[int]], np.ndarray] | None = None  # constrained decode
    stream: Callable[[int], None] | None = None
    ttft_s: float = 0.0
    started_s: float = field(default_factory=time.perf_counter)


class Engine:
    """Single-process serving engine (thread-safe via one lock: JAX dispatch
    is serialized per device anyway; the scheduler provides concurrency)."""

    def __init__(
        self,
        cfg: EngineConfig,
        model_cfg: ModelConfig | None = None,
        params: Any | None = None,
        tokenizer: Tokenizer | None = None,
    ):
        self.cfg = cfg
        self.model_cfg = model_cfg or get_config_preset(cfg.model)
        self.tokenizer = tokenizer or load_tokenizer(
            cfg.tokenizer, vocab_size=self.model_cfg.vocab_size
        )
        n_dev = len(jax.devices())
        tp = cfg.tp if cfg.tp > 0 else max(
            1, n_dev // cfg.dp if n_dev % cfg.dp == 0 else 1
        )
        # kv heads must divide cleanly over tp; fall back gracefully.
        while tp > 1 and self.model_cfg.num_kv_heads % tp != 0:
            tp -= 1
        self.mesh = make_mesh(tp=tp, dp=cfg.dp)
        self.lock = threading.RLock()

        key = jax.random.PRNGKey(cfg.seed)
        if params is None:
            if cfg.checkpoint:
                from ..models.loader import load_checkpoint

                params = load_checkpoint(cfg.checkpoint, self.model_cfg, cfg.dtype)
            else:
                log.warning(
                    "no checkpoint given: initializing RANDOM weights for %s",
                    self.model_cfg.name,
                )
                params = llama.init_params(self.model_cfg, key, dtype=cfg.dtype)
        self.params = shard_params(params, llama.param_specs(self.model_cfg), self.mesh)
        cache = llama.make_cache(
            self.model_cfg, cfg.num_pages, cfg.page_size, dtype=cfg.dtype
        )
        self.cache = shard_params(cache, llama.cache_specs(self.model_cfg), self.mesh)
        self.alloc = PageAllocator(
            cfg.num_pages, cfg.page_size, cfg.max_pages_per_seq,
            prefix_cache=cfg.prefix_cache,
        )
        self.sequences: dict[int, Sequence] = {}
        self._sample_key = jax.random.PRNGKey(cfg.seed + 1)

        mc, dt = self.model_cfg, cfg.dtype
        from ..ops.attention import paged_attention_backend

        self.attn_impl = paged_attention_backend(tp=tp)
        log.info("paged decode attention impl: %s (tp=%d)", self.attn_impl, tp)

        def _prefill(params, tokens, lengths, cache, table):
            return llama.prefill(params, mc, tokens, lengths, cache, table, dtype=dt)

        def _prefill_prefix(params, tokens, start, lengths, cache, table):
            return llama.prefill_with_prefix(
                params, mc, tokens, start, lengths, cache, table, dtype=dt
            )

        def _decode_sample(
            params, tokens, lengths, cache, table, active,
            key, temps, top_k, top_p, mask,
        ):
            """One fused decode+sample dispatch (one round trip, not two)."""
            logits, cache = llama.decode_step(
                params, mc, tokens, lengths, cache, table, active, dtype=dt,
                attn_impl=self.attn_impl,
            )
            tok = sample(logits, key, temps, top_k, top_p, mask)
            return tok.astype(jnp.int32), cache

        def _decode_block(
            params, tokens, write_at, active, budgets, cache, table,
            key, temps, top_k, top_p, greedy,
        ):
            from .decode_loop import decode_block

            return decode_block(
                params, mc, tokens, write_at, active, budgets, cache, table,
                key, temps, top_k, top_p,
                jnp.int32(self.tokenizer.eos_id),
                jnp.int32(self.tokenizer.pad_id),
                n_steps=self.cfg.decode_block,
                greedy=greedy,
                dtype=dt,
                attn_impl=self.attn_impl,
            )

        self._prefill_jit = jax.jit(_prefill, donate_argnames=("cache",))
        self._prefill_prefix_jit = jax.jit(
            _prefill_prefix, donate_argnames=("cache",)
        )
        self._decode_sample_jit = jax.jit(
            _decode_sample, donate_argnames=("cache",)
        )
        self._decode_block_jit = jax.jit(
            _decode_block, donate_argnames=("cache",), static_argnames=("greedy",)
        )
        self._sample_jit = jax.jit(sample)

    # -- bucketing ---------------------------------------------------------
    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket holding n tokens. Tails longer than the
        largest bucket are CHUNKED through it by add_request, so bucket
        choice never rejects a prompt — only the page budget
        (max_pages_per_seq) bounds prompt length."""
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return b
        return self.cfg.prefill_buckets[-1]

    # -- request lifecycle -------------------------------------------------
    def add_request(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams | None = None,
        mask_fn: Callable[[list[int]], np.ndarray] | None = None,
        stream: Callable[[int], None] | None = None,
    ) -> int:
        """Admit a request: allocate pages, run prefill, sample the first
        token. Returns the sequence id. Raises OutOfPages when full."""
        sampling = sampling or SamplingParams()
        n = len(prompt_ids)
        if n == 0:
            raise InvalidRequest("empty prompt")
        with self.lock:
            perf = get_perf_stats()
            t0 = time.perf_counter()
            # Prefix cache: reuse full pages of the prompt MINUS its last
            # token (at least one tail token must be prefilled to produce
            # the next-token logits).
            prefix_pages = self.alloc.match_prefix(prompt_ids[: n - 1])
            matched = len(prefix_pages) * self.cfg.page_size
            seq_id = self.alloc.allocate(n, prefix_pages=prefix_pages)
            try:
                seq = Sequence(
                    seq_id, n, prompt_ids=list(prompt_ids),
                    params=sampling, mask_fn=mask_fn, stream=stream,
                )
                self.sequences[seq_id] = seq
                table = self.alloc.page_table_row(seq_id)[None, :]
                logits = self._prefill_chunked(
                    prompt_ids, matched, jnp.asarray(table)
                )
                token = int(self._sample_one(logits, [seq])[0])
                seq.ttft_s = time.perf_counter() - t0
                perf.record_metric("engine.ttft", seq.ttft_s * 1e3, "ms")
                perf.record_metric("engine.prefill_tokens", n - matched, "tok")
                if matched:
                    perf.record_metric("engine.prefix_hit_tokens", matched, "tok")
                self._accept_token(seq, token)
            except Exception:
                # Failed admissions (prefill OOM, raising mask_fn, a raising
                # stream callback on the first token, ...) must not leak
                # pages or a stale Sequence: the scheduler only learns
                # seq_ids of successful admissions.
                self.sequences.pop(seq_id, None)
                self.alloc.free(seq_id)
                raise
            return seq_id

    def _prefill_chunked(
        self, prompt_ids: list[int], matched: int, table: jax.Array
    ) -> jax.Array:
        """Prefill everything past ``matched`` in bucket-sized chunks, each
        chunk attending over all cache content before it (the prefix pages
        plus previously prefilled chunks). Returns the last position's
        logits. Chunking keeps admission independent of prefix-cache state:
        a prompt longer than the largest bucket still prefills — the same
        XLA programs, run ceil(tail/bucket) times."""
        n = len(prompt_ids)
        biggest = self.cfg.prefill_buckets[-1]
        done = matched
        logits = None
        with self.mesh:
            while done < n:
                chunk = min(n - done, biggest)
                bucket = self._bucket(chunk)
                tokens = np.full((1, bucket), self.tokenizer.pad_id, np.int32)
                tokens[0, :chunk] = prompt_ids[done:done + chunk]
                if done:
                    logits, self.cache = self._prefill_prefix_jit(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray([done], jnp.int32),
                        jnp.asarray([chunk], jnp.int32),
                        self.cache,
                        table,
                    )
                else:
                    logits, self.cache = self._prefill_jit(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray([chunk], jnp.int32),
                        self.cache,
                        table,
                    )
                done += chunk
        return logits

    def _sampling_arrays(
        self, seqs: list[Sequence | None], B: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray | None]:
        """Per-slot (temps, top_k, top_p, allowed-mask-or-None) arrays."""
        temps = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        mask = None
        for i, s in enumerate(seqs):
            if s is None:
                continue
            temps[i] = s.params.temperature
            top_k[i] = s.params.top_k
            top_p[i] = s.params.top_p
            if s.mask_fn is not None:
                if mask is None:
                    mask = np.ones((B, self.model_cfg.vocab_size), bool)
                m = s.mask_fn(s.tokens)
                # Checkpoints pad the embedding vocab past the tokenizer's
                # (e.g. Qwen 152064 vs ~151.7k): padded ids are forbidden on
                # constrained rows — the tokenizer could never decode them.
                n = min(len(m), mask.shape[1])
                mask[i, :n] = m[:n]
                mask[i, n:] = False
        return temps, top_k, top_p, mask

    def _sample_one(self, logits: jax.Array, seqs: list[Sequence]) -> np.ndarray:
        B = logits.shape[0]
        temps, top_k, top_p, mask = self._sampling_arrays(seqs, B)
        self._sample_key, sub = jax.random.split(self._sample_key)
        tok = self._sample_jit(
            logits,
            sub,
            jnp.asarray(temps),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
            None if mask is None else jnp.asarray(mask),
        )
        return np.asarray(tok)

    def _accept_token(self, seq: Sequence, token: int) -> None:
        seq.tokens.append(token)
        if seq.stream is not None:
            seq.stream(token)
        if token == self.tokenizer.eos_id:
            seq.done = True
            seq.finish_reason = "stop"
        elif len(seq.tokens) >= seq.params.max_tokens:
            seq.done = True
            seq.finish_reason = "length"
        elif seq.params.stop and self._hit_stop_string(seq):
            seq.done = True
            seq.finish_reason = "stop"

    def _hit_stop_string(self, seq: Sequence) -> bool:
        """Check the decoded tail for any stop string, so generation halts at
        the stop instead of burning decode steps to max_tokens. The window is
        sized in TOKENS: one char can span up to 4 byte-level tokens (UTF-8),
        so a char-sized window would miss long multi-byte stop strings."""
        longest = max(len(s) for s in seq.params.stop)
        tail_tokens = seq.tokens[-(longest * 4 + 8) :]
        tail = self.tokenizer.decode(tail_tokens)
        return any(s in tail for s in seq.params.stop)

    def step(self, seq_ids: list[int] | None = None) -> dict[int, int]:
        """One decode step over up to max_batch_size running sequences.
        Returns {seq_id: new_token} for sequences that advanced."""
        with self.lock:
            running = [
                s for s in self.sequences.values() if not s.done
            ] if seq_ids is None else [
                self.sequences[i] for i in seq_ids if not self.sequences[i].done
            ]
            running = running[: self.cfg.max_batch_size]
            if not running:
                return {}
            B = self.cfg.max_batch_size
            # Account for the token each sequence is about to write. A
            # sequence that cannot grow (pool exhausted or per-seq page cap)
            # is finished as truncated instead of killing the whole step.
            grown: list[Sequence] = []
            for s in running:
                try:
                    self.alloc.extend(s.seq_id, 1)
                    grown.append(s)
                except OutOfPages:
                    s.done = True
                    s.finish_reason = "length"
                    log.warning(
                        "seq %d truncated: KV page budget exhausted", s.seq_id
                    )
            running = grown
            if not running:
                return {}
            ids: list[int | None] = [s.seq_id for s in running]
            ids += [None] * (B - len(ids))
            table, lengths, active = self.alloc.batch_views(ids, B)
            # lengths now include the new token; decode wants the write
            # offset (tokens already present before this step).
            write_at = lengths.copy()
            for i, s in enumerate(running):
                write_at[i] = lengths[i] - 1
            tokens = np.zeros((B,), np.int32)
            for i, s in enumerate(running):
                tokens[i] = s.tokens[-1] if s.tokens else self.tokenizer.bos_id
            slots = running + [None] * (B - len(running))
            temps, top_k, top_p, mask = self._sampling_arrays(slots, B)
            self._sample_key, sub = jax.random.split(self._sample_key)
            with self.mesh:
                sampled, self.cache = self._decode_sample_jit(
                    self.params,
                    jnp.asarray(tokens),
                    jnp.asarray(write_at),
                    self.cache,
                    jnp.asarray(table),
                    jnp.asarray(active),
                    sub,
                    jnp.asarray(temps),
                    jnp.asarray(top_k),
                    jnp.asarray(top_p),
                    None if mask is None else jnp.asarray(mask),
                )
            sampled = np.asarray(sampled)
            out: dict[int, int] = {}
            for i, s in enumerate(running):
                tok = int(sampled[i])
                self._accept_token(s, tok)
                out[s.seq_id] = tok
            get_perf_stats().record_metric("engine.decode_tokens", len(running), "tok")
            return out

    def step_block(self, seq_ids: list[int] | None = None) -> dict[int, list[int]]:
        """Advance running sequences by up to ``cfg.decode_block`` tokens in
        ONE device dispatch (one token pull per block instead of per step).
        Rows with a constrained-decoding mask advance one fused step per
        call instead (masks are host-computed per token); unconstrained
        rows in the same batch still block-decode. Returns
        {seq_id: accepted tokens} for sequences that advanced."""
        with self.lock:
            running = [
                s for s in self.sequences.values() if not s.done
            ] if seq_ids is None else [
                self.sequences[i] for i in seq_ids if not self.sequences[i].done
            ]
            running = running[: self.cfg.max_batch_size]
            if not running:
                return {}
            block = self.cfg.decode_block
            masked = [s for s in running if s.mask_fn is not None]
            plain = [s for s in running if s.mask_fn is None]
            if block <= 1 or (masked and not plain):
                return {
                    sid: [tok]
                    for sid, tok in self.step(
                        [s.seq_id for s in running]
                    ).items()
                }
            out_masked: dict[int, list[int]] = {}
            if masked:
                # Mixed batch: constrained rows need a host-computed logits
                # mask per token, so they advance one fused step per call
                # while the unconstrained rows still block-decode. (Their
                # inter-token latency grows by the block's device time —
                # the device-side FSM is the planned fix.)
                out_masked = {
                    sid: [tok]
                    for sid, tok in self.step(
                        [s.seq_id for s in masked]
                    ).items()
                }
                running = [s for s in plain if not s.done]
                if not running:
                    return out_masked
            B = self.cfg.max_batch_size
            # Pre-book pages for the whole block; rows that cannot grow at
            # all right now are truncated (consistent with step()).
            grown: list[Sequence] = []
            budgets: list[int] = []
            base_len: list[int] = []
            for s in running:
                want = min(block, s.params.max_tokens - len(s.tokens))
                want = max(want, 1)
                before = self.alloc.length(s.seq_id)
                got = self.alloc.extend_upto(s.seq_id, want)
                if got == 0:
                    s.done = True
                    s.finish_reason = "length"
                    log.warning(
                        "seq %d truncated: KV page budget exhausted", s.seq_id
                    )
                    continue
                grown.append(s)
                budgets.append(got)
                base_len.append(before)
            if not grown:
                return out_masked
            ids: list[int | None] = [s.seq_id for s in grown]
            ids += [None] * (B - len(ids))
            table, _, active = self.alloc.batch_views(ids, B)
            write_at = np.zeros((B,), np.int32)
            budget_arr = np.zeros((B,), np.int32)
            tokens = np.zeros((B,), np.int32)
            for i, s in enumerate(grown):
                write_at[i] = base_len[i]
                budget_arr[i] = budgets[i]
                tokens[i] = s.tokens[-1] if s.tokens else self.tokenizer.bos_id
            slots = grown + [None] * (B - len(grown))
            temps, top_k, top_p, _ = self._sampling_arrays(slots, B)
            greedy = bool(np.all(temps <= 0.0))
            perf = get_perf_stats()
            t_disp = time.perf_counter()
            with self.mesh:
                toks, self.cache, self._sample_key = self._decode_block_jit(
                    self.params,
                    jnp.asarray(tokens),
                    jnp.asarray(write_at),
                    jnp.asarray(active),
                    jnp.asarray(budget_arr),
                    self.cache,
                    jnp.asarray(table),
                    self._sample_key,
                    jnp.asarray(temps),
                    jnp.asarray(top_k),
                    jnp.asarray(top_p),
                    greedy=greedy,
                )
            t_pull = time.perf_counter()
            toks = np.asarray(toks)  # the ONE device->host pull per block
            t_done = time.perf_counter()
            perf.record_metric(
                "engine.block_dispatch", (t_pull - t_disp) * 1e3, "ms"
            )
            perf.record_metric("engine.block_pull", (t_done - t_pull) * 1e3, "ms")
            out: dict[int, list[int]] = dict(out_masked)
            produced = 0
            first_exc: BaseException | None = None
            for i, s in enumerate(grown):
                n0 = len(s.tokens)
                try:
                    for j in range(budgets[i]):
                        self._accept_token(s, int(toks[i, j]))
                        if s.done:
                            break
                except Exception as e:  # noqa: BLE001 - raising stream cb
                    # A raising stream callback must not skip the page
                    # rollback (that would poison the prefix cache with
                    # pages whose KV content outruns the accepted tokens).
                    if first_exc is None:
                        first_exc = e
                    s.done = True
                    s.finish_reason = s.finish_reason or "error"
                finally:
                    accepted = s.tokens[n0:]
                    # Roll the pre-booked pages back to what was accepted:
                    # the cache holds [prompt + generated[:-1]] (the last
                    # sampled token is never written) = base_len + accepted.
                    self.alloc.truncate(
                        s.seq_id, base_len[i] + len(accepted)
                    )
                    out[s.seq_id] = accepted
                    produced += len(accepted)
            get_perf_stats().record_metric("engine.decode_tokens", produced, "tok")
            if first_exc is not None:
                raise first_exc
            return out

    def finish(self, seq_id: int) -> list[int]:
        """Release resources; returns the generated tokens. Full pages are
        donated to the prefix trie keyed by their exact token history (the
        cache holds prompt + generated[:-1]: the last sampled token is never
        written back by a decode step)."""
        with self.lock:
            seq = self.sequences.pop(seq_id)
            self.alloc.free(seq_id, tokens=seq.prompt_ids + seq.tokens[:-1])
            return seq.tokens

    # -- convenience (tests / bench) ----------------------------------------
    def generate(
        self,
        prompts: list[list[int]],
        sampling: SamplingParams | None = None,
    ) -> list[list[int]]:
        """Synchronous batch generation with continuous decode stepping."""
        ids = [self.add_request(p, sampling) for p in prompts]
        pending = {i for i in ids if not self.sequences[i].done}
        while pending:
            self.step_block(sorted(pending))
            pending = {i for i in pending if not self.sequences[i].done}
        return [self.finish(i) for i in ids]
