"""The serving engine: jitted prefill/decode over a paged KV cache.

This is the compute core that replaces the reference's remote LLM round trip
(reference pkg/assistants/simple.go:343,515 -> pkg/llms/openai.go:69). Design
points (SURVEY.md section 7):

- **Two XLA programs**: prefill (one sequence, bucketed lengths) and a
  fixed-batch decode step. Static shapes only — bucketing avoids
  recompilation; the decode batch is padded with inactive slots.
- **Paged KV cache**: device pages + host PageAllocator; the cache pytree is
  donated through every call, so it lives in HBM with no copies.
- **Tensor parallelism**: params/cache placed with NamedShardings over the
  (dp, sp, tp) mesh; jit propagates, XLA emits the ICI collectives.
- **Greedy-by-default sampling** on device, with per-request temperature /
  top-k / top-p and a constrained-decoding mask hook.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models import llama
from ..models.config import ModelConfig, get_config_preset
from ..parallel.mesh import make_mesh, shard_params
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats
from ..utils.profiling import annotate, device_timer
from .kvcache import InvalidRequest, PageAllocator, OutOfPages
from .sampler import SamplingParams, sample
from .tokenizer import Tokenizer, load_tokenizer

log = get_logger("engine")


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache so engine restarts reuse
    compiled prefill/decode programs instead of paying tens of seconds of
    XLA compilation per bucket (VERDICT: 56 s engine init / 18 s first
    admission, all compile time). Idempotent. Returns the active cache
    directory (what ``Engine.snapshot`` packages as a build artifact).

    ``OPSAGENT_COMPILE_CACHE_DIR`` is the one knob (also settable via
    ``serve-engine --compile-cache-dir``; ``OPSAGENT_COMPILE_CACHE`` is
    the accepted legacy spelling): "" or "0" disables, a path overrides
    the per-platform default. ``OPSAGENT_COMPILE_CACHE_MIN_S`` overrides
    the minimum compile seconds persisted — ``snapshot create`` and the
    bench cold-start stage set it to 0 so every warmed program lands in
    the cache regardless of how fast it compiled."""
    import os

    if not path:
        path = os.environ.get("OPSAGENT_COMPILE_CACHE_DIR")
        if path is None:
            path = os.environ.get("OPSAGENT_COMPILE_CACHE")  # legacy name
        if path is not None and (not path or path == "0"):
            return None  # explicitly disabled ("" or "0")
    if not path:
        # Per-platform cache dirs, with CPU caches additionally keyed by
        # the host's CPU feature set: XLA:CPU stores AOT machine code, and
        # an image snapshot can carry ~/.cache across machines — loading
        # an avx512-targeted AOT entry on a host without those features
        # risks SIGILL (observed as cpu_aot_loader warnings). TPU caches
        # stay shared: their entries are keyed by compiler/device version.
        try:
            plat = jax.default_backend()
        except Exception:  # noqa: BLE001
            plat = "unknown"
        tag = plat
        if plat == "cpu":
            import hashlib

            try:
                with open("/proc/cpuinfo") as f:
                    flags = next(
                        (ln for ln in f if ln.startswith("flags")), ""
                    )
                tag += "-" + hashlib.sha1(flags.encode()).hexdigest()[:8]
            except OSError:
                pass
        path = os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "opsagent_tpu", f"xla-{tag}",
        )
    try:
        os.makedirs(path, exist_ok=True)
        # JAX materialises its cache object lazily and then keeps it for
        # the life of the process, so updating jax_compilation_cache_dir
        # alone would silently keep reading/writing the OLD directory.
        # Reset the instance whenever the directory actually changes
        # (snapshot create / restore / tests re-point the cache mid-run).
        if getattr(jax.config, "jax_compilation_cache_dir", None) != path:
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # noqa: BLE001 - private API, best-effort
                pass
        jax.config.update("jax_compilation_cache_dir", path)
        # Default threshold skips small programs; the TTFT budget cares
        # about every bucket, so cache anything that took >=1 s to build.
        try:
            min_s = float(
                os.environ.get("OPSAGENT_COMPILE_CACHE_MIN_S", "1.0")
            )
        except ValueError:
            min_s = 1.0
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_s
        )
    except Exception as e:  # noqa: BLE001 - cache is best-effort
        log.warning("compilation cache unavailable (%s)", e)
        return None
    return path


def _merge_pulls(out: dict[int, list[int]], pulled: dict[int, list[int]]) -> None:
    """Fold one pulled block's tokens into an accumulated result. Plain
    dict.update would REPLACE a sequence's list when several pulled blocks
    carry tokens for it (multi-block drains), dropping tokens."""
    for sid, toks in pulled.items():
        out.setdefault(sid, []).extend(toks)


@dataclass
class EngineConfig:
    model: str = "tiny-test"
    checkpoint: str = ""
    tokenizer: str = ""
    dtype: Any = jnp.bfloat16
    tp: int = 0                      # 0 = all devices
    dp: int = 1
    # Sequence/context parallelism for PREFILL: with sp > 1 each prefill
    # chunk's attention runs as ragged ring attention sharded over the sp
    # mesh axis (parallel.ring), spreading the chunk's O(S^2) attention
    # over sp devices — covering prompts up to the largest bucket in one
    # sharded pass (BASELINE config 4 scale). Prefix-tail chunks (prompts
    # beyond the largest bucket, or prefix-cache hits) attend over paged
    # cache and stay on the pjit-partitioned gather path; decode has no
    # sequence axis to shard.
    sp: int = 1
    # Expert parallelism for MoE models: experts (weights AND grouped-
    # dispatch compute) shard over the ep mesh axis (DeepSeek-V3-class
    # scale-out). No effect on dense models.
    ep: int = 1
    # Speculative decoding (prompt-lookup / n-gram drafting): draft this
    # many tokens per decode iteration from the sequence's own history and
    # verify them in one multi-position forward — 1..k+1 tokens per
    # weight-streaming pass. Exact for greedy sampling (the agent-loop
    # default); non-greedy batches fall back to the vanilla pipeline.
    # Default 0 BY MEASUREMENT (PERF.md r04): on the trained-agent ReAct
    # workload lookup drafts hit only ~6.6 % — replies share JSON keys
    # with the prompt but the values are novel — so dispatches per token
    # rise and speculation is a net loss there. Enable for workloads with
    # genuinely repetitive continuations (templated YAML etc.).
    speculative_k: int = 0
    speculative_ngram: int = 2
    # Max admitting sequences prefilled per batched dispatch (scheduler
    # groups same-bucket chunks; rows pad to powers of two). Divides
    # per-session TTFT under concurrent admissions by up to this factor.
    prefill_batch: int = 4
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 320   # 5120 tokens: largest bucket + generation
    max_batch_size: int = 8
    # Decode steps fused into one device dispatch (1 = step-at-a-time).
    # Each dispatch costs a host->device round trip plus ONE device->host
    # token pull, so per-token overhead scales as RTT / decode_block.
    decode_block: int = 32
    # Dispatches allowed in flight beyond the one being pulled. With the
    # decode loop state device-resident (decode_loop.decode_block_carry),
    # block k+1..k+depth are enqueued before block k's tokens are pulled,
    # so the pull RTT and host bookkeeping overlap device compute. 0 =
    # synchronous (pull immediately after each dispatch).
    pipeline_depth: int = 2
    prefill_buckets: tuple[int, ...] = (64, 128, 256, 512, 1024, 2048, 4096)
    # Mixed prefill+decode batching (Sarathi-style piggybacking over the
    # ragged paged-attention op): the scheduler tick packs every decode
    # lane (one token each) PLUS up to max_step_tokens of chunked-prefill
    # tokens into ONE device dispatch (step_mixed), so prefill rides the
    # decode dispatch's weight stream instead of buying its own — on a
    # weight-streaming-bound model the split tick streams ~all weights
    # TWICE per tick (one prefill program + one decode program). Decode
    # lanes get budget first; the remainder goes to the oldest admitting
    # prompts. The split path remains the fallback (flag off, no admitting
    # prompts, or rows needing host-side per-token work).
    mixed_batching: bool = True
    # Per-mixed-dispatch token budget: decode lanes (1 token each) are
    # funded first, remaining budget seats prefill chunk tokens.
    max_step_tokens: int = 256
    # Chunk-size buckets for the mixed program's query axis: the chunk
    # pads to the smallest bucket holding it, so XLA compiles one program
    # per bucket (warmed by warmup()) and the post-warmup-zero-compiles
    # invariant survives arbitrary batch compositions. Kept modest — the
    # ragged Pallas kernel's VMEM accumulator scales with the bucket.
    mixed_buckets: tuple[int, ...] = (16, 32, 64, 128)
    # One-step-lookahead ASYNC mixed ticks (serving/async_runtime.py):
    # depth of the mixed-tick dispatch pipeline. At 2 (default) tick
    # t+1's dispatch is enqueued BEFORE tick t's tokens are pulled — the
    # decode lanes' sampled-token feedback stays device-resident (a
    # carry, like the block-decode loop) — so host post-processing
    # (detokenize, stop/EOS scan, streaming, trie bookkeeping) overlaps
    # device compute. Stop-string/EOS detection lags one tick: the
    # finished row's one overshoot token is discarded and its page
    # booking rolled back. 1 = today's synchronous tick. Constrained
    # rows ride the async lane only with dense device FSM tables;
    # hosted-mask/logprobs/bias rows route to the sync lanes.
    async_depth: int = 2
    max_new_tokens_default: int = 1024
    seed: int = 0
    prefix_cache: bool = True
    # Hierarchical KV cache: the host-RAM offload tier (serving/offload).
    # With offload on, trie evictions SPILL page content to a bounded
    # host pool instead of dropping it, tool-time parking
    # (park_chain / park_sequence) proactively frees HBM while a session
    # blocks on tool execution, and admission RESTORES pooled pages with
    # a device copy instead of re-prefilling them. Requires prefix_cache.
    offload: bool = False
    # Pages per copy dispatch (the copy engine's largest bucket); page-id
    # vectors pad to (1, offload_copy_pages) so the restore path stays
    # inside the zero-post-warmup-compiles invariant.
    offload_copy_pages: int = 8
    # Host pool byte bound; 0 = $OPSAGENT_KV_HOST_POOL_BYTES or 1 GiB.
    host_pool_bytes: int = 0
    # Weight-only quantization: "" (compute dtype) or "int8" (per-channel
    # symmetric, models.quant). Halves weight HBM traffic — the decode
    # bottleneck — and the footprint: Llama-3-8B fits a 16 GB v5e chip
    # only at int8.
    quantize: str = ""
    # KV-cache quantization: "" (pages in compute dtype) or "int8" (pages
    # int8 + per-token-per-head f32 scales, ops.attention.QuantizedPages).
    # Halves decode-step KV reads — the dominant non-weight HBM term at
    # serving shapes (PERF.md roofline: ~4 GB/step at the 8B bench
    # config). Forces the xla paged-attention backend (the Pallas kernels
    # stream raw pages); unsupported for MLA latent caches.
    kv_quantize: str = ""
    # Weight-stream backend for the quantized decode/mixed hot path: ""
    # (resolve from $OPSAGENT_WEIGHT_STREAM, default "xla") or explicit
    # "xla" / "pallas-dma". "pallas-dma" streams int8/int4 weight tiles
    # HBM->VMEM through double-buffered DMA slots under the layer scan
    # (ops.quant_matmul_pallas) so layer l+1's weights load during layer
    # l's compute; "xla" keeps the fused dequantize-in-operand-read path.
    # Default xla BY MEASUREMENT policy (same rule as the paged-attention
    # backend): the ragged-sweep bench covers the axis, and the default
    # flips only on on-chip evidence. Resolved ONCE at engine init (like
    # attn_impl): requires quantized weights and tp == 1, else falls back
    # to xla with a log line; the resolved value is in impl_info().
    weight_stream: str = ""
    # Grammar-accelerated decoding: when a constrained row's FSM state
    # admits exactly ONE legal token (JSON punctuation, known key names,
    # enum close-quotes), emit the whole forced run with NO per-token
    # forward pass — spliced as one multi-token append through the mixed
    # program's q_len>1 path. Acceptance = 1.0 by construction (the
    # masked sample can only produce the forced token), so greedy output
    # is byte-identical with the flag off; the skipped dispatches are
    # exact counts reported by opsagent_ffwd_*_total. Rows without dense
    # device FSM tables (hosted masks, budget-exceeded schemas), with
    # logprobs, or with logit bias are ineligible and decode normally.
    grammar_ffwd: bool = True
    # Compile every serving program (all prefill buckets + decode) at
    # construction time so the first real request never pays XLA compile
    # (the TTFT budget is 500 ms; a cold bucket compile is tens of seconds).
    warmup: bool = False


@dataclass
class Sequence:
    """Host-side state of one in-flight generation."""

    seq_id: int
    prompt_len: int
    prompt_ids: list[int] = field(default_factory=list)
    tokens: list[int] = field(default_factory=list)   # generated tokens
    params: SamplingParams = field(default_factory=SamplingParams)
    done: bool = False
    finish_reason: str = ""        # "stop" | "length" | "preempted"
    mask_fn: Callable[[list[int]], np.ndarray] | None = None  # constrained decode
    stream: Callable[[int], None] | None = None
    ttft_s: float = 0.0
    started_s: float = field(default_factory=time.perf_counter)
    # Per generated token, when params.logprobs: {"logprob": float,
    # "top": [(token_id, logprob), ...][:params.top_logprobs]}.
    logprob_data: list[dict] = field(default_factory=list)
    # Cached static logit_bias row [V] (built on first use).
    static_bias: Any = None
    # Incremental {token_id: count} for presence/frequency penalties —
    # maintained by _accept_token so penalized long generations stay
    # O(distinct tokens) per step instead of re-counting the history.
    penalty_counts: dict | None = None
    # Observability (obs.trace): the request's span handle under which the
    # engine records prefill/decode phase children, the open decode span,
    # and the previous accepted-token timestamp for the inter-token-latency
    # histogram. All optional — untraced traffic pays one None check.
    trace: Any = None
    decode_span: Any = None
    last_tok_s: float = 0.0


class Engine:
    """Single-process serving engine (thread-safe via one lock: JAX dispatch
    is serialized per device anyway; the scheduler provides concurrency)."""

    def __init__(
        self,
        cfg: EngineConfig,
        model_cfg: ModelConfig | None = None,
        params: Any | None = None,
        tokenizer: Tokenizer | None = None,
        params_quantized: bool = False,
    ):
        """``params_quantized``: the caller-supplied ``params`` tree is
        ALREADY in the quantized layout matching ``cfg.quantize`` (the
        snapshot-restore path) — apply ``quantize_specs`` only, never
        ``quantize_params`` (re-quantizing int8 weights would corrupt
        them)."""
        self.cfg = cfg
        self.compile_cache_dir = enable_compilation_cache()
        self.model_cfg = model_cfg or get_config_preset(cfg.model)
        if self.model_cfg.moe is not None:
            # Serving pins the EXACT all-experts dispatch: the grouped
            # capacity path can drop assignments under skewed routing and
            # its activation depends on chunk token count, which varies
            # with prefix-cache residency — a request's output must not
            # depend on what happens to be cached. Training keeps grouped
            # dispatch (models.llama._moe_mlp).
            from dataclasses import replace

            self.model_cfg = replace(
                self.model_cfg,
                moe=replace(self.model_cfg.moe, grouped_dispatch_min_tokens=0),
            )
        self.tokenizer = tokenizer or load_tokenizer(
            cfg.tokenizer, vocab_size=self.model_cfg.vocab_size
        )
        n_dev = len(jax.devices())
        slots = cfg.dp * cfg.sp * cfg.ep
        if cfg.sp > 1:
            # Fail fast with the config knob named, instead of an opaque
            # shard_map divisibility error at first prefill.
            bad = [b for b in cfg.prefill_buckets if b % cfg.sp]
            if bad:
                raise ValueError(
                    f"sp={cfg.sp} must divide every prefill bucket "
                    f"(offending: {bad})"
                )
            if slots * max(1, cfg.tp) > n_dev:
                raise ValueError(
                    f"dp={cfg.dp} * sp={cfg.sp} * ep={cfg.ep} * "
                    f"tp={max(1, cfg.tp)} exceeds {n_dev} devices"
                )
        tp = cfg.tp if cfg.tp > 0 else max(
            1, n_dev // slots if n_dev % slots == 0 else 1
        )
        # kv heads AND the vocab (embedding/lm-head shard dim) must divide
        # cleanly over tp; fall back gracefully. Vocab matters for
        # HF-derived configs (config_from_hf): a tokenizer-sized odd
        # vocab with auto-tp would otherwise fail at shard_params.
        while tp > 1 and (
            self.model_cfg.num_kv_heads % tp != 0
            or self.model_cfg.vocab_size % tp != 0
        ):
            tp -= 1
        self.mesh = make_mesh(tp=tp, dp=cfg.dp, sp=cfg.sp, ep=cfg.ep)
        self.lock = threading.RLock()
        # Re-entrancy guard for mesh_ctx (per-thread): the jit cache keys
        # on the mesh-context STACK, so `with mesh:` nested inside another
        # `with mesh:` compiles a separate program from a single-level
        # entry with an identical signature (measured r04: warmup-compiled
        # sampler programs were recompiled inside the serving window).
        # Every engine jit call enters the mesh through mesh_ctx so the
        # ambient depth is exactly one, no matter how call paths compose.
        self._mesh_tls = threading.local()

        if cfg.kv_quantize and cfg.kv_quantize != "int8":
            raise ValueError(
                f"kv_quantize={cfg.kv_quantize!r}: only 'int8' is supported"
            )
        if cfg.kv_quantize and self.model_cfg.mla is not None:
            # MLA's latent cache feeds weight-absorbed matmuls (quantizing
            # the shared latent is a different fidelity question), and the
            # materialized layout packs mixed-width k/v planes; neither is
            # validated under int8 pages — reject rather than silently
            # degrade a V3-class deployment.
            raise ValueError("kv_quantize is not supported for MLA models")
        if cfg.quantize and cfg.quantize not in ("int8", "int4"):
            raise ValueError(
                f"quantize={cfg.quantize!r}: supported values are "
                f"'int8' (per-channel) and 'int4' (group-wise)"
            )
        key = jax.random.PRNGKey(cfg.seed)
        specs = llama.param_specs(self.model_cfg)
        t_load = time.perf_counter()
        if cfg.quantize and params is None and not cfg.checkpoint:
            # Random + int8 (benchmarks, smoke runs): build the int8 tree
            # directly ON DEVICE — a full-precision host-side init +
            # quantize takes tens of minutes at 8B, and shipping 8+ GB of
            # host-generated weights over a tunneled device link is
            # slower still (or kills the link).
            from ..models.quant import quantize_specs

            log.warning(
                "no checkpoint given: initializing RANDOM %s weights "
                "for %s", cfg.quantize, self.model_cfg.name,
            )
            params = llama.init_params_random_quantized(
                self.model_cfg, cfg.seed, dtype=cfg.dtype,
                mode=cfg.quantize,
            )
            specs = quantize_specs(specs, mode=cfg.quantize)
        else:
            # With quantization, checkpoint weights must be loaded and
            # quantized on the HOST: the full-precision tree is the thing
            # that does not fit the chip (Llama-3-8B bf16 = 16 GB on a
            # 16 GB v5e). Only the int8 tree is device_put onto the mesh.
            from contextlib import nullcontext

            host = (
                jax.default_device(jax.local_devices(backend="cpu")[0])
                if cfg.quantize and params is None else nullcontext()
            )
            with host:
                if params is None:
                    if cfg.checkpoint:
                        from ..models.loader import load_checkpoint

                        params = load_checkpoint(
                            cfg.checkpoint, self.model_cfg, cfg.dtype
                        )
                    else:
                        log.warning(
                            "no checkpoint given: initializing RANDOM "
                            "weights for %s", self.model_cfg.name,
                        )
                        params = llama.init_params(
                            self.model_cfg, key, dtype=cfg.dtype
                        )
                if cfg.quantize:
                    from ..models.quant import quantize_params, quantize_specs

                    if not params_quantized:
                        params = quantize_params(params, mode=cfg.quantize)
                        log.info(
                            "weights quantized to %s (%s scales)",
                            cfg.quantize,
                            "per-output-channel" if cfg.quantize == "int8"
                            else "group-wise",
                        )
                    specs = quantize_specs(specs, mode=cfg.quantize)
        self.params = shard_params(params, specs, self.mesh)
        # Block on the transfers so weights_load_s measures the actual
        # host->HBM move, not just the device_put enqueue.
        jax.block_until_ready(self.params)
        # /healthz "init" block: how this replica came up. warmup() adds
        # its wall time; the snapshot restore path stamps its source +
        # fingerprint after construction.
        self.init_stats: dict[str, Any] = {
            "weights_load_s": round(time.perf_counter() - t_load, 3),
            "warmup_s": 0.0,
            "restore_source": "",
            "snapshot_fingerprint": "",
        }
        cache = llama.make_cache(
            self.model_cfg, cfg.num_pages, cfg.page_size, dtype=cfg.dtype,
            kv_quantize=cfg.kv_quantize,
        )
        self.cache = shard_params(
            cache,
            llama.cache_specs(self.model_cfg, kv_quantize=cfg.kv_quantize),
            self.mesh,
        )
        self.alloc = PageAllocator(
            cfg.num_pages, cfg.page_size, cfg.max_pages_per_seq,
            prefix_cache=cfg.prefix_cache,
        )
        # Host-RAM offload tier: spills ride every trie eviction, restores
        # ride admission (begin_request). Parking APIs: park_chain (tool
        # windows), park_sequence (admission-pressure LRU).
        self.offload = None
        if cfg.offload and cfg.prefix_cache:
            from .offload import HostPagePool, OffloadManager, PageCopyEngine

            self.offload = OffloadManager(
                HostPagePool(
                    cfg.page_size,
                    capacity_bytes=cfg.host_pool_bytes or None,
                ),
                PageCopyEngine(
                    mesh_ctx=self.mesh_ctx,
                    copy_pages=cfg.offload_copy_pages,
                ),
                cfg.page_size,
            )
            self.alloc.attach_host_pool(self.offload.pool)
            self.alloc.set_spill(self._spill_page)
        # Fleet-global KV fault-in client (fleet/pagestore.py). Wired by
        # the router (in-process) or run_engine_server (--join-fleet);
        # None = the peer-fetch tier is off and admission behaves as
        # before (trie -> host pool -> re-prefill).
        self.pagestore = None
        self._digests_truncated = False
        self.sequences: dict[int, Sequence] = {}
        self._evictions_seen = 0  # delta-sync base for the obs counter
        self._sample_key = jax.random.PRNGKey(cfg.seed + 1)
        # Weight-stream backend, resolved ONCE here like attn_impl below:
        # the env knob records what was asked for; self.weight_stream_impl
        # is what actually runs (impl_info / healthz / bench rows).
        ws = cfg.weight_stream or os.environ.get(
            "OPSAGENT_WEIGHT_STREAM", ""
        ) or "xla"
        if ws not in ("xla", "pallas-dma"):
            raise ValueError(
                f"weight_stream={ws!r}: expected 'xla' or 'pallas-dma'"
            )
        if ws == "pallas-dma" and cfg.quantize not in ("int8", "int4"):
            # The kernel streams NARROW storage types; full-precision
            # weights have nothing to dequantize in-register.
            log.info(
                "weight_stream=pallas-dma needs quantize=int8|int4 "
                "(got %r): falling back to xla", cfg.quantize or "none",
            )
            ws = "xla"
        if ws == "pallas-dma" and tp > 1:
            # Row-parallel projections (wo, wd) would need a psum epilogue
            # around the shard_mapped kernel; until that is wired and
            # measured, sharded engines keep the XLA path.
            log.info(
                "weight_stream=pallas-dma is single-shard only for now "
                "(tp=%d): falling back to xla", tp,
            )
            ws = "xla"
        self.weight_stream_impl = ws
        if ws != "xla":
            log.info("weight stream impl: %s", ws)
        # Goodput ledger: the static roofline cost model pricing every
        # dispatch from its batch composition (obs/attribution.py). Pure
        # host float math — nothing here is jitted or device-resident, so
        # the zero-post-warmup-compiles invariant is untouched.
        self.attr = obs.attribution.Attribution.for_engine(
            self.model_cfg, cfg, weight_stream=ws
        )
        obs.attribution.set_current(self.attr)

        mc, dt = self.model_cfg, cfg.dtype
        from ..ops.attention import paged_attention_backend

        self.attn_impl = paged_attention_backend()
        if self.model_cfg.mla is not None and self.attn_impl != "xla":
            # MLA's qk head dim (nope+rope, e.g. 192) breaks the Pallas
            # kernels' last-dim tiling assumptions; the gather path is
            # shape-agnostic.
            log.info(
                "mla model: forcing xla paged attention (was %s)",
                self.attn_impl,
            )
            self.attn_impl = "xla"
        # kv_quantize no longer forces a backend: int8 pages + scales flow
        # through ALL impls — the XLA gather, the manual-DMA kernels, AND
        # the (B, MaxP) grid kernels (score-space scale path) — so the
        # requested backend resolves as asked and the ragged sweep's
        # pallas+int8KV cell measures the grid kernel, not a silent xla
        # fallback.
        from ..ops.attention import pallas_interpret

        if (
            self.attn_impl == "pallas-dma"
            and self.model_cfg.head_dim_ % 128 != 0
            and not pallas_interpret()
        ):
            # Mosaic requires manual-DMA memref slices to be 128-aligned
            # on the minormost dim (measured on-chip r04: bench-1b's
            # head_dim=64 fails to compile with "Slice shape along
            # dimension 3 must be aligned to tiling (128)"). Interpret
            # mode (the CPU sweep smoke) has no Mosaic, so the gate only
            # applies to compiled runs.
            log.info(
                "pallas-dma needs head_dim %% 128 == 0 (got %d): "
                "falling back to xla paged attention",
                self.model_cfg.head_dim_,
            )
            self.attn_impl = "xla"
        log.info(
            "paged decode attention impl: %s (tp=%d%s)",
            self.attn_impl, tp,
            ", shard_map over tp"
            if self.attn_impl.startswith("pallas") and tp > 1
            else "",
        )

        # sp > 1: shard long-context prefill attention over the sp axis as
        # a ragged ring (each sequence masks by its own length inside every
        # ring step). Decode and the prefix-chunk path stay on paged ops.
        if cfg.sp > 1:
            from ..parallel.ring import make_ring_attention

            prefill_attn = make_ring_attention(self.mesh)
        else:
            prefill_attn = None

        def _prefill(params, tokens, lengths, cache, table):
            return llama.prefill(
                params, mc, tokens, lengths, cache, table, dtype=dt,
                prefill_attn=prefill_attn,
            )

        def _prefill_prefix(params, tokens, start, lengths, cache, table):
            return llama.prefill_with_prefix(
                params, mc, tokens, start, lengths, cache, table, dtype=dt
            )

        def _decode_sample(
            params, tokens, lengths, cache, table, active,
            key, temps, top_k, top_p, mask, bias=None,
        ):
            """One fused decode+sample dispatch (one round trip, not two).
            ``bias`` [B, V] is the additive logit adjustment carrying
            OpenAI logit_bias and presence/frequency penalties."""
            logits, cache = llama.decode_step(
                params, mc, tokens, lengths, cache, table, active, dtype=dt,
                attn_impl=self.attn_impl, mesh=self.mesh,
                weight_stream=self.weight_stream_impl,
            )
            if bias is not None:
                logits = logits + bias
            tok = sample(logits, key, temps, top_k, top_p, mask)
            return tok.astype(jnp.int32), cache

        def _decode_sample_lp(
            params, tokens, lengths, cache, table, active,
            key, temps, top_k, top_p, mask, bias=None,
        ):
            """Fused decode+sample that ALSO returns the sampled token's
            logprob and the top-20 alternatives (the OpenAI logprobs API
            caps top_logprobs at 20; a fixed width keeps the shape
            static). Used for rows whose request asked for logprobs.
            Logprobs reflect the post-bias distribution — the one actually
            sampled from."""
            logits, cache = llama.decode_step(
                params, mc, tokens, lengths, cache, table, active, dtype=dt,
                attn_impl=self.attn_impl, mesh=self.mesh,
                weight_stream=self.weight_stream_impl,
            )
            if bias is not None:
                logits = logits + bias
            tok = sample(logits, key, temps, top_k, top_p, mask)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            chosen = jnp.take_along_axis(
                lp, tok[:, None].astype(jnp.int32), axis=1
            )[:, 0]
            # Padded embedding vocab (e.g. Qwen): padded ids carry
            # arbitrary untrained logits and the tokenizer cannot render
            # them — keep them out of the top-20 alternatives.
            tv = min(self.tokenizer.vocab_size, mc.vocab_size)
            if tv < mc.vocab_size:
                lp = jnp.where(
                    jnp.arange(mc.vocab_size)[None, :] < tv, lp, -jnp.inf
                )
            tl, ti = jax.lax.top_k(lp, 20)
            return tok.astype(jnp.int32), chosen, ti.astype(jnp.int32), tl, cache

        def _mixed_sample(
            params, tokens, starts, qlens, cache, table,
            key, temps, top_k, top_p,
        ):
            """One fused mixed prefill+decode dispatch: ragged forward over
            decode rows (q_len=1) and prefill chunk rows (q_len=chunk) in
            the same batch, then one sample over every row's last-valid
            logits. Rows whose chunk does not finish its prompt get their
            sampled token discarded on host; q_len=0 rows are inert."""
            logits, cache = llama.mixed_step(
                params, mc, tokens, starts, qlens, cache, table, dtype=dt,
                attn_impl=self.attn_impl, mesh=self.mesh,
                weight_stream=self.weight_stream_impl,
            )
            tok = sample(logits, key, temps, top_k, top_p, None)
            return tok.astype(jnp.int32), cache

        def _decode_pipeline(
            params, carry_tok, carry_at, carry_eos, key,
            override, ov_tok, ov_at, alive, budgets, cache, table,
            temps, top_k, top_p, greedy,
            fsm_mask=None, fsm_dest=None, carry_fsm=None, ov_fsm=None,
        ):
            from .decode_loop import decode_block_carry

            return decode_block_carry(
                params, mc, carry_tok, carry_at, carry_eos, key,
                override, ov_tok, ov_at, alive, budgets, cache, table,
                temps, top_k, top_p,
                jnp.int32(self.tokenizer.eos_id),
                jnp.int32(self.tokenizer.pad_id),
                fsm_mask=fsm_mask, fsm_dest=fsm_dest,
                carry_fsm=carry_fsm, ov_fsm=ov_fsm,
                n_steps=self.cfg.decode_block,
                greedy=greedy,
                dtype=dt,
                attn_impl=self.attn_impl,
                mesh=self.mesh,
                weight_stream=self.weight_stream_impl,
            )

        self._prefill_jit = jax.jit(_prefill, donate_argnames=("cache",))
        self._prefill_prefix_jit = jax.jit(
            _prefill_prefix, donate_argnames=("cache",)
        )
        self._decode_sample_jit = jax.jit(
            _decode_sample, donate_argnames=("cache",)
        )
        self._decode_sample_lp_jit = jax.jit(
            _decode_sample_lp, donate_argnames=("cache",)
        )
        self._decode_pipeline_jit = jax.jit(
            _decode_pipeline,
            donate_argnames=("cache", "carry_tok", "carry_at", "carry_eos", "key"),
            static_argnames=("greedy",),
        )
        def _mixed_carry(
            params, tokens, use_carry, carry_tok, starts, qlens, emits,
            cache, table, key, temps, top_k, top_p,
            fsm_mask=None, fsm_dest=None, carry_fsm=None, ov_fsm=None,
        ):
            """The async variant of ``_mixed_sample``: decode lanes splice
            their input token from the previous dispatch's device-resident
            output (``carry_tok``), so tick t+1 dispatches before tick t's
            tokens ever reach the host (serving/async_runtime.py)."""
            from .decode_loop import mixed_step_carry

            return mixed_step_carry(
                params, mc, tokens, use_carry, carry_tok, starts, qlens,
                emits, cache, table, key, temps, top_k, top_p,
                dtype=dt, attn_impl=self.attn_impl, mesh=self.mesh,
                weight_stream=self.weight_stream_impl,
                fsm_mask=fsm_mask, fsm_dest=fsm_dest,
                carry_fsm=carry_fsm, ov_fsm=ov_fsm,
            )

        self._mixed_sample_jit = jax.jit(
            _mixed_sample, donate_argnames=("cache",)
        )
        # carry_tok is deliberately NOT donated: it is pulled to host at
        # commit time, one dispatch after it fed the next tick.
        self._mixed_carry_jit = jax.jit(
            _mixed_carry, donate_argnames=("cache",)
        )
        self._sample_jit = jax.jit(sample)

        # Speculative decode pipeline (greedy batches, speculative_k > 0):
        # scan steps sized so the worst case (everything accepted) emits
        # exactly one decode_block of tokens per dispatch.
        self._spec_steps = max(
            1, cfg.decode_block // (cfg.speculative_k + 1)
        )
        self._hist = None  # device [B, H] token history for drafting
        self._ov_hist_zeros = None  # cached all-zeros ov_hist (no overrides)
        self._bias_buf = None  # reused host [B, V] logit-bias batch buffer
        self._fsm_dev: dict = {}  # id(fsm) -> (fsm, device mask, device dest)

        def _spec_pipeline(
            params, carry_tok, carry_at, carry_eos, carry_hist,
            override, ov_tok, ov_at, ov_hist, alive, budgets, cache, table,
        ):
            from .decode_loop import speculative_block_carry

            return speculative_block_carry(
                params, mc, carry_tok, carry_at, carry_eos, carry_hist,
                override, ov_tok, ov_at, ov_hist, alive, budgets, cache,
                table,
                jnp.int32(self.tokenizer.eos_id),
                jnp.int32(self.tokenizer.pad_id),
                n_steps=self._spec_steps,
                k=cfg.speculative_k,
                ngram=cfg.speculative_ngram,
                dtype=dt,
            )

        self._spec_pipeline_jit = jax.jit(
            _spec_pipeline,
            donate_argnames=(
                "cache", "carry_tok", "carry_at", "carry_eos", "carry_hist"
            ),
        )

        # -- pipelined decode state (see step_block) -------------------------
        B = cfg.max_batch_size
        self._lanes: list[int | None] = [None] * B   # lane -> seq_id
        self._lane_of: dict[int, int] = {}           # seq_id -> lane
        self._carry: tuple | None = None  # device (tok, at, eos, fsm, key)
        from collections import deque

        self._inflight: deque = deque()              # dispatched, unpulled
        self._inflight_steps: dict[int, int] = {}    # seq_id -> booked steps
        self._prefilling: dict[int, int] = {}        # seq_id -> tokens done

        # -- async mixed pipeline (see step_mixed_async) ---------------------
        from .async_runtime import AsyncMixedRuntime

        self._async = AsyncMixedRuntime(self)
        # Device-resident carries for the async mixed program: the
        # previous dispatch's sampled tokens / FSM states. Seeded by
        # warmup so every runtime dispatch sees program-output sharding
        # (the host-array variant compiles only once, inside warmup).
        self._async_carry = None
        self._async_fsm_carry = None
        # Constrained sequences already counted as ffwd-ineligible (the
        # fallback reason fires once per sequence, not once per tick).
        self._ffwd_noted: set[int] = set()
        # Wall-clock stamp of the last mixed dispatch's enqueue return,
        # shared by the sync and async tick paths: the gap to the next
        # dispatch is the opsagent_step_host_gap_seconds observable.
        self._mixed_gap_stamp: float | None = None

        if cfg.warmup:
            self.warmup()

    # Program groups compiled by warmup(). "full" is every serving program;
    # the narrower levels exist because XLA compile time is the scarce
    # resource under an external wall clock (VERDICT r2: full warmup's
    # cross-product of programs timed out the driver bench) — a benchmark
    # that only dispatches plain prefill + greedy block decode should only
    # pay for those.
    WARMUP_LEVELS: dict = {
        "bench": frozenset({"prefill", "sample", "decode_greedy"}),
        "bench-spec": frozenset(
            {"prefill", "sample", "decode_greedy", "spec"}
        ),
        # The ragged-backend sweep drives the engine through sync
        # step_mixed only (admission chunks AND decode ticks both ride
        # the mixed program), so it needs exactly the mixed family — one
        # compile per mixed bucket, tracing through the RESOLVED
        # attn_impl, which is how each sweep cell's kernel gets compiled
        # before the timed window. Paying for the prefill/decode-block
        # cross-product per sweep cell would blow the stage budget.
        "bench-mixed": frozenset({"mixed"}),
        # "fsm" rides along: sessions workloads carry schema-constrained
        # rows since the grammar fast-forward bench, and a constrained
        # row's first block dispatch must not compile under load.
        "sessions": frozenset({
            "prefill", "prefill_prefix", "prefill_batched", "sample",
            "decode_greedy", "mixed", "mixed_async", "fsm", "ffwd",
            "offload",
        }),
        "full": frozenset({
            "prefill", "prefill_prefix", "prefill_batched", "sample",
            "decode_single", "logprobs", "decode_greedy", "decode_sampled",
            "fsm", "spec", "mixed", "mixed_async", "ffwd", "offload",
        }),
    }

    @contextlib.contextmanager
    def mesh_ctx(self):
        """Enter ``self.mesh`` at depth exactly one per thread: nested
        entries are no-ops. The jit cache keys on the mesh-context stack,
        so a nested `with mesh:` silently recompiles programs an outer
        single-level entry already compiled (see __init__'s _mesh_tls
        note)."""
        if getattr(self._mesh_tls, "active", False):
            yield
            return
        self._mesh_tls.active = True
        try:
            with self.mesh:
                yield
        finally:
            self._mesh_tls.active = False

    def impl_info(self) -> dict[str, str]:
        """The RESOLVED execution modes: attention impl after every
        fallback gate (MLA, kv-quantize, head-dim alignment), the
        weight-stream backend after ITS gates (quantize present, tp == 1),
        plus weight and KV quantization. Folded into ``/healthz`` and
        every bench result line's ``extra`` so sweep rows and fleet
        snapshots are self-describing — the env knob records what was
        ASKED for, this records what actually runs."""
        return {
            "attn_impl": self.attn_impl,
            "weight_stream": self.weight_stream_impl,
            "quantize": self.cfg.quantize or "none",
            "kv_quantize": self.cfg.kv_quantize or "none",
        }

    def _warmup_precompile_jobs(
        self, progs: frozenset
    ) -> list[tuple[str, Any]]:
        """(group, thunk) jobs for warmup's parallel pre-compile pass:
        each thunk is ``jit_fn.lower(args).compile()`` with the EXACT
        concrete arrays the sequential dispatch loop will pass (same
        avals, shardings, donation), so the executable it writes into the
        persistent compilation cache is the one the dispatch loop reads
        back. ``lower()`` only traces — nothing executes, no donated
        buffer is consumed, and ``self.cache`` is untouched.

        Only the straight-line program families are listed. The
        carry-chained variants (mixed_async, ffwd, pipeline second call,
        spec) take device OUTPUTS as inputs — their argument shardings
        only exist after the first dispatch — so they stay sequential.
        """
        B = self.cfg.max_batch_size
        MaxP = self.cfg.max_pages_per_seq
        jobs: list[tuple[str, Any]] = []

        def add(group: str, fn, *args, **kw):
            jobs.append((group, lambda: fn.lower(*args, **kw).compile()))

        drop1 = jnp.full((1, MaxP), -1, jnp.int32)
        for bucket in self.cfg.prefill_buckets:
            toks = jnp.zeros((1, bucket), jnp.int32)
            ln = jnp.asarray([bucket], jnp.int32)
            if "prefill" in progs:
                add(
                    "prefill", self._prefill_jit,
                    self.params, toks, ln, self.cache, drop1,
                )
            if "prefill_prefix" in progs:
                add(
                    "prefill_prefix", self._prefill_prefix_jit,
                    self.params, toks, jnp.asarray([0], jnp.int32), ln,
                    self.cache, drop1,
                )
            if "prefill_batched" in progs:
                ceil = 1
                while ceil < self.cfg.prefill_batch:
                    ceil *= 2
                bp = 2
                while bp <= ceil:
                    add(
                        "prefill_batched", self._prefill_prefix_jit,
                        self.params,
                        jnp.zeros((bp, bucket), jnp.int32),
                        jnp.zeros((bp,), jnp.int32),
                        jnp.zeros((bp,), jnp.int32),
                        self.cache,
                        jnp.full((bp, MaxP), -1, jnp.int32),
                    )
                    bp *= 2
        dropB = jnp.full((B, MaxP), -1, jnp.int32)
        zi = jnp.zeros((B,), jnp.int32)
        zf = jnp.zeros((B,), jnp.float32)
        of = jnp.ones((B,), jnp.float32)
        inactive = jnp.zeros((B,), bool)
        # Lowering only consumes the key's aval, so peeling a split off
        # the live key WITHOUT advancing self._sample_key is safe here.
        sub = jax.random.split(self._sample_key)[1]
        if "mixed" in progs and self.cfg.mixed_batching:
            for sb in self.cfg.mixed_buckets:
                add(
                    "mixed", self._mixed_sample_jit,
                    self.params, jnp.zeros((B, sb), jnp.int32), zi, zi,
                    self.cache, dropB, sub, zf, zi, of,
                )
        biasB = None
        if "decode_single" in progs or "logprobs" in progs:
            biasB = jnp.zeros((B, self.model_cfg.vocab_size), jnp.float32)
        if "decode_single" in progs:
            for b in (None, biasB):
                add(
                    "decode_single", self._decode_sample_jit,
                    self.params, zi, zi, self.cache, dropB, inactive,
                    sub, zf, zi, of, None, *(() if b is None else (b,)),
                )
        if "logprobs" in progs:
            for b in (None, biasB):
                add(
                    "logprobs", self._decode_sample_lp_jit,
                    self.params, zi, zi, self.cache, dropB, inactive,
                    sub, zf, zi, of, None, b,
                )
        for greedy in (True, False):
            if ("decode_greedy" if greedy else "decode_sampled") not in progs:
                continue
            add(
                "decode_block", self._decode_pipeline_jit,
                self.params,
                jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), bool), sub,
                jnp.zeros((B,), bool), zi, zi, inactive, zi,
                self.cache, dropB, zf, zi, of,
                greedy=greedy,
                fsm_mask=None, fsm_dest=None,
                carry_fsm=jnp.zeros((B,), jnp.int32), ov_fsm=zi,
            )
        return jobs

    def warmup(self, level: str = "full") -> float:
        """Compile serving programs ahead of the first request: each
        prefill bucket (plain + prefix form), the pipelined decode block
        (greedy and sampled variants), the single-step decode, and the
        sampler. All warmup calls write through all-dropped page tables
        (-1 entries) with inactive rows, so device cache content and host
        page accounting are untouched. Returns wall seconds spent.

        ``level`` picks the program subset (WARMUP_LEVELS): "full" for
        serving, "sessions" for the concurrent-sessions path (batched
        admission + prefix prefill + greedy decode), "bench" for the
        minimal throughput-bench path (plain prefill + greedy decode).

        Combined with ``enable_compilation_cache`` this is one-time cost
        per (model, shape) config; subsequent engine starts replay the
        persistent cache instead of re-invoking XLA."""
        progs = self.WARMUP_LEVELS[level]
        t0 = time.perf_counter()
        B = self.cfg.max_batch_size
        MaxP = self.cfg.max_pages_per_seq
        # Compile-watchdog phase bracket: compiles inside count as
        # "warmup"; once any warmup completes, a compile during serving is
        # an anomaly (ring dump + opsagent_post_warmup_compiles).
        with obs.flight.warmup_phase(), self.lock, self.mesh_ctx():
            # Re-warming a LIVE engine: settle in-flight decode state first,
            # exactly like the legacy step path (warmup's throwaway carries
            # would otherwise desync lanes still referenced by pulls).
            self._async_settle()
            self._flush_and_invalidate()
            # Parallel pre-compile (OPSAGENT_WARMUP_PARALLEL, default on):
            # lower+compile the straight-line program families on a thread
            # pool FIRST, so XLA builds them concurrently; the sequential
            # dispatch loop below then reads each executable back from the
            # persistent compilation cache instead of compiling serially.
            # Gated on the cache being active — without it the AOT
            # executables are unreachable from the dispatch path and the
            # pass would compile everything twice. Worker-thread compiles
            # still count as "warmup" to the compile watchdog: the
            # warmup_phase bracket is a process-wide flag, not
            # thread-local. Sub-threshold programs (compile faster than
            # OPSAGENT_COMPILE_CACHE_MIN_S) recompile in the dispatch
            # loop; by definition that re-pay is cheap.
            par = os.environ.get("OPSAGENT_WARMUP_PARALLEL", "1") not in (
                "", "0",
            )
            if par and self.compile_cache_dir:
                jobs = self._warmup_precompile_jobs(progs)
                if len(jobs) > 1:
                    import concurrent.futures as _cf

                    def _run(item):
                        group, thunk = item
                        jt0 = time.perf_counter()
                        try:
                            with self.mesh_ctx():
                                thunk()
                        except Exception:  # noqa: BLE001 - best-effort
                            log.exception(
                                "parallel warmup pre-compile failed for "
                                "%s (non-fatal; sequential pass covers it)",
                                group,
                            )
                        return group, time.perf_counter() - jt0

                    workers = min(
                        len(jobs), max(2, (os.cpu_count() or 4) // 2), 8
                    )
                    groups: dict[str, float] = dict(
                        self.init_stats.get("warmup_groups", {})
                    )
                    with _cf.ThreadPoolExecutor(
                        max_workers=workers, thread_name_prefix="warmup"
                    ) as ex:
                        for group, secs in ex.map(_run, jobs):
                            groups[group] = round(
                                groups.get(group, 0.0) + secs, 3
                            )
                    self.init_stats["warmup_groups"] = groups
                    log.info(
                        "parallel warmup pre-compile: %d programs on %d "
                        "threads, per-group seconds %s",
                        len(jobs), workers, groups,
                    )
            drop1 = jnp.full((1, MaxP), -1, jnp.int32)
            logits = None
            for bucket in self.cfg.prefill_buckets:
                toks = jnp.zeros((1, bucket), jnp.int32)
                ln = jnp.asarray([bucket], jnp.int32)
                if "prefill" in progs:
                    logits, self.cache = self._prefill_jit(
                        self.params, toks, ln, self.cache, drop1
                    )
                if "prefill_prefix" in progs:
                    logits, self.cache = self._prefill_prefix_jit(
                        self.params, toks, jnp.asarray([0], jnp.int32), ln,
                        self.cache, drop1,
                    )
                # Batched-admission variants: every power of two up to the
                # PADDED ceiling (prefill_batch=6 pads to 8 at runtime), and
                # the sampler at the same widths (several same-bucket rows
                # can finish in one dispatch).
                if "prefill_batched" in progs:
                    ceil = 1
                    while ceil < self.cfg.prefill_batch:
                        ceil *= 2
                    bp = 2
                    while bp <= ceil:
                        lg, self.cache = self._prefill_prefix_jit(
                            self.params,
                            jnp.zeros((bp, bucket), jnp.int32),
                            jnp.zeros((bp,), jnp.int32),
                            jnp.zeros((bp,), jnp.int32),
                            self.cache,
                            jnp.full((bp, MaxP), -1, jnp.int32),
                        )
                        self._sample_one(lg, [])
                        bp *= 2
            if "sample" in progs and logits is not None:
                self._sample_one(logits, [])
            dropB = jnp.full((B, MaxP), -1, jnp.int32)
            zi = jnp.zeros((B,), jnp.int32)
            zf = jnp.zeros((B,), jnp.float32)
            of = jnp.ones((B,), jnp.float32)
            inactive = jnp.zeros((B,), bool)
            toks = None
            # Mixed prefill+decode programs: one per chunk bucket (the
            # query axis is the only shape that varies — decode-lane count
            # and chunk sizes within a bucket are DATA, not shape — so
            # this cross-product keeps mixed dispatches compile-free no
            # matter the batch composition). q_lens all zero: inert rows,
            # no KV writes, all-dropped tables.
            if "mixed" in progs and self.cfg.mixed_batching:
                for sb in self.cfg.mixed_buckets:
                    self._sample_key, sub = jax.random.split(self._sample_key)
                    toks, self.cache = self._mixed_sample_jit(
                        self.params,
                        jnp.zeros((B, sb), jnp.int32),
                        zi, zi,
                        self.cache,
                        dropB,
                        sub, zf, zi, of,
                    )
            # Carry-chained ASYNC mixed programs: per bucket, TWO chained
            # calls (the first sees fresh host carries, every later one
            # the previous dispatch's outputs — different input
            # shardings, hence two jit entries; see warm_pipeline's
            # note), with and without dense FSM tables when "fsm" is in
            # the level. The final carries are KEPT — runtime dispatches
            # always chain from program outputs, so the host-array
            # variant never recompiles inside the serving window.
            if (
                "mixed_async" in progs and self.cfg.mixed_batching
                and self.cfg.async_depth > 1
            ):
                a_carry = self._async_carry
                a_fsm = self._async_fsm_carry
                if a_carry is None:
                    a_carry = jnp.zeros((B,), jnp.int32)
                if a_fsm is None:
                    a_fsm = jnp.zeros((B,), jnp.int32)
                fsm_tabs: list[tuple] = [(None, None)]
                if "fsm" in progs:
                    try:
                        from .constrained import (
                            TOOLPROMPT_SCHEMA, json_constraint,
                        )

                        con = json_constraint(
                            self.tokenizer, TOOLPROMPT_SCHEMA
                        )
                        if con.fsm.dense_tables() is not None:
                            fsm_tabs.append(
                                self._fsm_device_tables(con.fsm)
                            )
                    except Exception:  # noqa: BLE001 - best-effort
                        log.exception(
                            "ToolPrompt async-FSM warmup failed (non-fatal)"
                        )
                zb = jnp.zeros((B,), bool)
                for sb in self.cfg.mixed_buckets:
                    for fm, fd in fsm_tabs:
                        for _ in range(2):
                            self._sample_key, sub = jax.random.split(
                                self._sample_key
                            )
                            a_carry, self.cache, a_fsm = (
                                self._mixed_carry_jit(
                                    self.params,
                                    jnp.zeros((B, sb), jnp.int32),
                                    zb, a_carry, zi, zi, zb,
                                    self.cache, dropB,
                                    sub, zf, zi, of,
                                    fsm_mask=fm, fsm_dest=fd,
                                    carry_fsm=a_fsm, ov_fsm=zi,
                                )
                            )
                self._async_carry = a_carry
                self._async_fsm_carry = a_fsm
                toks = a_carry
            # Grammar fast-forward programs: the multi-token forced-run
            # append reuses _mixed_carry_jit with dense FSM tables and
            # FRESH host carries every call (the sync ffwd path never
            # chains device carries), so both the host-array and chained
            # variants must exist PER BUCKET. Warmed independently of
            # async_depth — depth-1 engines take this path too. At
            # depth>1 with "fsm" in the level the mixed_async block above
            # already compiled these entries; re-dispatching is a cache
            # hit, not a recompile.
            if (
                "ffwd" in progs and self.cfg.mixed_batching
                and self.cfg.grammar_ffwd
            ):
                try:
                    from .constrained import (
                        TOOLPROMPT_SCHEMA, json_constraint,
                    )

                    con = json_constraint(self.tokenizer, TOOLPROMPT_SCHEMA)
                    if con.fsm.dense_tables() is not None:
                        fm, fd = self._fsm_device_tables(con.fsm)
                        zb = jnp.zeros((B,), bool)
                        for sb in self.cfg.mixed_buckets:
                            f_carry = jnp.zeros((B,), jnp.int32)
                            f_fsm = jnp.zeros((B,), jnp.int32)
                            for _ in range(2):
                                self._sample_key, sub = jax.random.split(
                                    self._sample_key
                                )
                                f_carry, self.cache, f_fsm = (
                                    self._mixed_carry_jit(
                                        self.params,
                                        jnp.zeros((B, sb), jnp.int32),
                                        zb, f_carry, zi, zi, zb,
                                        self.cache, dropB,
                                        sub, zf, zi, of,
                                        fsm_mask=fm, fsm_dest=fd,
                                        carry_fsm=f_fsm, ov_fsm=zi,
                                    )
                                )
                                toks = f_carry
                except Exception:  # noqa: BLE001 - warmup is best-effort
                    log.exception(
                        "grammar-ffwd FSM warmup failed (non-fatal)"
                    )
            if "decode_single" in progs:
                self._sample_key, sub = jax.random.split(self._sample_key)
                _, self.cache = self._decode_sample_jit(
                    self.params, zi, zi, self.cache, dropB, inactive,
                    sub, zf, zi, of, None,
                )
            # Bias / logprobs variants: the first logit_bias, penalty, or
            # logprobs request must not pay an XLA compile under the
            # engine lock.
            biasB = None
            if "decode_single" in progs or "logprobs" in progs:
                biasB = jnp.zeros(
                    (B, self.model_cfg.vocab_size), jnp.float32
                )
            if "decode_single" in progs:
                self._sample_key, sub = jax.random.split(self._sample_key)
                _, self.cache = self._decode_sample_jit(
                    self.params, zi, zi, self.cache, dropB, inactive,
                    sub, zf, zi, of, None, biasB,
                )
            if "logprobs" in progs:
                for b in (None, biasB):
                    self._sample_key, sub = jax.random.split(self._sample_key)
                    _, _, _, _, self.cache = self._decode_sample_lp_jit(
                        self.params, zi, zi, self.cache, dropB, inactive,
                        sub, zf, zi, of, None, b,
                    )
            greedy_variants = [
                g for g in (True, False)
                if ("decode_greedy" if g else "decode_sampled") in progs
            ]
            def warm_pipeline(greedy: bool, fm=None, fd=None):
                """TWO chained calls mirroring step_block's exact argument
                structure (carry_fsm/ov_fsm always passed): the first
                dispatch sees fresh host arrays, every later one sees the
                previous dispatch's OUTPUTS as carries — different input
                shardings, hence a second jit cache entry. Both must
                compile here or the second real block pays XLA inside the
                serving window."""
                self._sample_key, sub = jax.random.split(self._sample_key)
                carry = (
                    jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
                    jnp.zeros((B,), bool), jnp.zeros((B,), jnp.int32), sub,
                )
                toks = None
                for _ in range(2):
                    c_tok, c_at, c_eos, c_fsm, c_key = carry
                    toks, self.cache, carry = self._decode_pipeline_jit(
                        self.params,
                        c_tok, c_at, c_eos, c_key,
                        jnp.zeros((B,), bool), zi, zi, inactive, zi,
                        self.cache, dropB, zf, zi, of,
                        greedy=greedy,
                        fsm_mask=fm, fsm_dest=fd,
                        carry_fsm=c_fsm, ov_fsm=zi,
                    )
                return toks

            for greedy in greedy_variants:
                toks = warm_pipeline(greedy)
            # Device-FSM decode variant, pre-specialized for the agent's
            # primary constraint (the ReAct ToolPrompt schema): the first
            # constrained request must not pay the dense-table build plus
            # an XLA compile under the engine lock. Other schemas' table
            # SHAPES still compile on first use (unknowable here).
            if "fsm" in progs:
                try:
                    from .constrained import TOOLPROMPT_SCHEMA, json_constraint

                    con = json_constraint(self.tokenizer, TOOLPROMPT_SCHEMA)
                    if con.fsm.dense_tables() is not None:
                        fm, fd = self._fsm_device_tables(con.fsm)
                        for greedy in (True, False):
                            toks = warm_pipeline(greedy, fm, fd)
                except Exception:  # noqa: BLE001 - warmup is best-effort
                    log.exception("ToolPrompt FSM warmup failed (non-fatal)")
            # Offload-tier copy programs (gather + scatter per bucket):
            # the restore path runs inside admission, where an XLA compile
            # would be a post-warmup anomaly. warm() rewrites page 0 with
            # its own content — state-preserving like every warmup call.
            if "offload" in progs and self.offload is not None:
                self.cache = self.offload.copier.warm(self.cache)
            if "spec" in progs and self.cfg.speculative_k > 0:
                H = self.cfg.max_pages_per_seq * self.cfg.page_size
                ov_hist = jnp.zeros((B, H), jnp.int32)
                carry_s = (
                    jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
                    jnp.zeros((B,), bool), jnp.zeros((B, H), jnp.int32),
                )
                for _ in range(2):
                    c_tok, c_at, c_eos, c_hist = carry_s
                    toks, _, self.cache, carry_out = self._spec_pipeline_jit(
                        self.params,
                        c_tok, c_at, c_eos, c_hist,
                        jnp.zeros((B,), bool), zi, zi,
                        ov_hist, inactive, zi,
                        self.cache, dropB,
                    )
                    carry_s = carry_out
            self._carry = None  # warmup carries are throwaways
            self._hist = None
            # A real device->host pull: on async backends block_until_ready
            # returns immediately, and the point of warmup is that the
            # FIRST request finds an idle, fully-compiled device.
            if toks is not None:
                np.asarray(toks)
            elif logits is not None:
                np.asarray(logits)
        dt = time.perf_counter() - t0
        log.info("engine warmup[%s]: programs compiled in %.1f s", level, dt)
        get_perf_stats().record_metric("engine.warmup", dt * 1e3, "ms")
        obs.flight.record("warmup", level=level, seconds=round(dt, 3))
        self.init_stats["warmup_s"] = round(
            self.init_stats.get("warmup_s", 0.0) + dt, 3
        )
        return dt

    # -- snapshot/restore (serving/snapshot) --------------------------------
    def snapshot(self, path: str) -> dict:
        """Write this engine's restart snapshot (weights in device
        layout, the persistent compile cache, the paged-KV plan) under
        ``path``. Snapshot a WARMED engine under
        ``OPSAGENT_COMPILE_CACHE_MIN_S=0`` — cache entries are written at
        compile time, so programs compiled before the threshold was
        lowered are not in the artifact. Returns the manifest dict."""
        from .snapshot.writer import write_snapshot

        with self.lock:
            return write_snapshot(self, path)

    @classmethod
    def from_snapshot(
        cls,
        path: str,
        warmup: bool | str | None = None,
        tokenizer: Tokenizer | None = None,
    ) -> "Engine":
        """Restore an engine from a snapshot directory: weight leaves are
        mmap'd straight into ``device_put`` with the re-derived shardings
        (no loader round trip) and the compile cache is pre-seeded so the
        warmup sweep (``warmup=True`` for "full", or a WARMUP_LEVELS
        name) is a cache-hit replay. Refuses mismatched fingerprints,
        device counts, and leaf orders with a ``SnapshotError``."""
        from .snapshot.restore import restore_engine

        return restore_engine(path, warmup=warmup, tokenizer=tokenizer)

    # -- bucketing ---------------------------------------------------------
    def _bucket(self, n: int) -> int:
        """Smallest prefill bucket holding n tokens. Tails longer than the
        largest bucket are CHUNKED through it by add_request, so bucket
        choice never rejects a prompt — only the page budget
        (max_pages_per_seq) bounds prompt length."""
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return b
        return self.cfg.prefill_buckets[-1]

    # -- request lifecycle -------------------------------------------------
    def add_request(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams | None = None,
        mask_fn: Callable[[list[int]], np.ndarray] | None = None,
        stream: Callable[[int], None] | None = None,
        trace: Any = None,
    ) -> int:
        """Admit a request synchronously: allocate pages, run the whole
        prefill, sample the first token. Returns the sequence id. Raises
        OutOfPages when full.

        This is ``begin_request`` + ``prefill_step`` until done — the
        scheduler uses those directly so prefill CHUNKS interleave with
        decode blocks instead of stalling every running stream for the
        whole admission (VERDICT round-1 weak #7)."""
        with self.lock:
            seq_id = self.begin_request(
                prompt_ids, sampling, mask_fn, stream, trace=trace
            )
            while not self.prefill_step(seq_id):
                pass
            return seq_id

    def begin_request(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams | None = None,
        mask_fn: Callable[[list[int]], np.ndarray] | None = None,
        stream: Callable[[int], None] | None = None,
        trace: Any = None,
        expect_restore: bool = False,
    ) -> int:
        """Stage 1 of admission: allocate pages (reusing any cached prefix)
        and register the sequence in the 'prefilling' state. Cheap — no
        device work. Follow with ``prefill_step`` calls until it returns
        True; only then does the sequence decode."""
        sampling = sampling or SamplingParams()
        n = len(prompt_ids)
        if n == 0:
            raise InvalidRequest("empty prompt")
        if n >= self.model_cfg.max_position:
            # Positions past the model's rope window produce degenerate
            # attention (e.g. the DeepSeek presets clamp to the native
            # pre-YaRN window); fail the request loudly instead.
            raise InvalidRequest(
                f"prompt of {n} tokens exceeds the model's "
                f"{self.model_cfg.max_position}-position context window"
            )
        if n + sampling.max_tokens > self.model_cfg.max_position:
            # Decode must not run positions past the window either: clamp
            # the generation budget (OpenAI-style context-limit behavior —
            # the request finishes with reason "length" at the window).
            from dataclasses import replace as _dc_replace

            sampling = _dc_replace(
                sampling, max_tokens=self.model_cfg.max_position - n
            )
        # Fleet-global KV tier: if the prompt misses locally (trie AND
        # host pool), fault the missing chain in from a peer BEFORE
        # taking the engine lock — the fetch is network I/O and must not
        # stall running decode. Landed pages are host-pool entries under
        # the same chain keys, so the locked restore below picks them up
        # through the unchanged _restore_from_host path. Never raises;
        # a miss/failure just means the prefill loop covers the tokens.
        faulted_pages = self.fault_in_prefix(
            prompt_ids,
            request_id=obs.flight.request_id_of(trace) or "",
        )
        with self.lock:
            if self.offload is not None:
                # Land pending spills first: a page parked during the
                # previous tick must be matchable by THIS admission.
                self.offload.flush()
            # Prefix cache: reuse full pages of the prompt MINUS its last
            # token (at least one tail token must be prefilled to produce
            # the next-token logits).
            prefix_pages = self.alloc.match_prefix(prompt_ids[: n - 1])
            matched = len(prefix_pages) * self.cfg.page_size
            seq_id = self.alloc.allocate(n, prefix_pages=prefix_pages)
            restored = self._restore_from_host(
                seq_id, prompt_ids, n, len(prefix_pages), matched
            )
            if faulted_pages and restored and self.offload is not None:
                self.offload.note_remote_hit(
                    min(restored, faulted_pages * self.cfg.page_size)
                )
            if expect_restore and matched + restored < (
                (n - 1) // self.cfg.page_size
            ) * self.cfg.page_size:
                # A PARKED session came back and its host-pool pages were
                # gone (LRU-dropped under the byte bound, or a failed
                # restore): correctness falls back to re-prefill, but
                # silently eating that cost is how fidelity regressions
                # hide — ring-dump it.
                obs.OFFLOAD_RESTORE_FALLBACKS.inc()
                obs.flight.anomaly(
                    "restore_reprefill", seq_id=seq_id, prompt_tokens=n,
                    prefix_hit_tokens=matched, restored_tokens=restored,
                    request_id=obs.flight.request_id_of(trace),
                )
            seq = Sequence(
                seq_id, n, prompt_ids=list(prompt_ids),
                params=sampling, mask_fn=mask_fn, stream=stream,
                trace=trace,
            )
            self.sequences[seq_id] = seq
            self._prefilling[seq_id] = matched + restored
            if matched:
                get_perf_stats().record_metric(
                    "engine.prefix_hit_tokens", matched, "tok"
                )
                obs.PREFIX_HIT_TOKENS.inc(matched)
            obs.flight.record(
                "admission", seq_id=seq_id, prompt_tokens=n,
                prefix_hit_tokens=matched, restored_tokens=restored,
                request_id=obs.flight.request_id_of(trace),
            )
            self._observe_occupancy()
            return seq_id

    def _restore_from_host(
        self, seq_id: int, prompt_ids: list[int], n: int,
        shared_pages: int, matched: int,
    ) -> int:
        """Restore-instead-of-reprefill: pages of this prompt beyond the
        HBM trie hit that the host pool still holds are copied back into
        the freshly-allocated pages and re-registered into the trie
        (``promote_prefix``), so the prefill loop starts AFTER them and
        partial restores become trie hits for concurrent admissions.
        Returns restored token count (0 on miss or any failure — the
        caller's chunked prefill then covers those tokens, the tier-1
        behavior)."""
        if self.offload is None:
            return 0
        seq_pages = self.alloc.pages_of(seq_id)
        entries = self.offload.pool.match(
            prompt_ids[: n - 1],
            start_page=shared_pages,
            max_pages=len(seq_pages) - shared_pages,
        )
        if not entries:
            return 0
        dst = seq_pages[shared_pages : shared_pages + len(entries)]
        try:
            def _keep(c):
                self.cache = c

            self.cache, restored = self.offload.restore(
                self.cache, dst, entries, seq_id=seq_id, on_update=_keep
            )
        except Exception:  # noqa: BLE001 - fall back to re-prefill
            log.exception(
                "host->device KV restore failed; re-prefilling "
                "(pages will be overwritten by the prefill chunks)"
            )
            return 0
        if restored:
            self.alloc.promote_prefix(
                seq_id, prompt_ids[: matched + restored]
            )
            get_perf_stats().record_metric(
                "engine.restore_tokens", restored, "tok"
            )
        return restored

    def next_prefill_bucket(self, seq_id: int) -> int:
        """Bucket the given admitting sequence's NEXT chunk compiles into —
        the scheduler's grouping key for batched admission."""
        with self.lock:
            seq = self.sequences[seq_id]
            done = self._prefilling[seq_id]
            return self._bucket(
                min(seq.prompt_len - done, self.cfg.prefill_buckets[-1])
            )

    def prefill_batch(self, seq_ids: list[int]) -> dict[int, bool]:
        """Run ONE prefill chunk for EACH given admitting sequence in a
        single batched dispatch (same-bucket grouping is the caller's job;
        smaller chunks ride as padding). Under concurrent admissions
        (BASELINE config 5) this divides per-session TTFT by the batch
        width instead of prefilling one session per scheduler tick.

        Rows are padded to a power-of-two batch so XLA compiles a handful
        of (batch, bucket) variants, with padding rows writing through
        dropped (-1) page tables. Every row runs the prefix-attention
        program (start = tokens already prefilled; 0 for fresh prompts —
        same math, one code path batches mixed admission states).

        Returns {seq_id: fully_prefilled | Exception}: row-local failures
        (a raising stream callback or mask_fn on the first token) clean up
        and fail ONLY their own row — per-request isolation matches the
        decode path's one-bad-apple contract. A failed DISPATCH cleans up
        every batched sequence (pages freed, Sequence dropped) before the
        exception propagates."""
        with self.lock:
            self._async_settle()
            self._mixed_gap_stamp = None  # see step(): gap continuity ends
            try:
                seqs = [self.sequences[s] for s in seq_ids]
                dones = [self._prefilling[s] for s in seq_ids]
                chunks = [
                    min(seq.prompt_len - d, self.cfg.prefill_buckets[-1])
                    for seq, d in zip(seqs, dones)
                ]
                bucket = self._bucket(max(chunks))
                Bp = 1
                while Bp < len(seq_ids):
                    Bp *= 2
                tokens = np.full(
                    (Bp, bucket), self.tokenizer.pad_id, np.int32
                )
                starts = np.zeros((Bp,), np.int32)
                lens = np.zeros((Bp,), np.int32)
                tables = np.full(
                    (Bp, self.cfg.max_pages_per_seq), -1, np.int32
                )
                for i, (sid, seq, d, c) in enumerate(
                    zip(seq_ids, seqs, dones, chunks)
                ):
                    tokens[i, :c] = seq.prompt_ids[d : d + c]
                    starts[i] = d
                    lens[i] = c
                    tables[i] = self.alloc.page_table_row(sid)
                dev_out: list = []
                with annotate("engine.prefill_chunk"), \
                        device_timer("prefill_chunk", dev_out), self.mesh_ctx():
                    logits, self.cache = self._prefill_prefix_jit(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray(starts),
                        jnp.asarray(lens),
                        self.cache,
                        jnp.asarray(tables),
                    )
                    dev_out.append(logits)
                perf = get_perf_stats()
                perf.record_metric(
                    "engine.prefill_tokens", int(sum(chunks)), "tok"
                )
                obs.PREFILL_TOKENS.inc(int(sum(chunks)))
                obs.flight.record(
                    "dispatch", op="prefill_batch", seq_ids=list(seq_ids),
                    bucket=bucket, rows=len(seq_ids),
                    prefill_tokens=int(sum(chunks)),
                )
                self.attr.dispatch(
                    "prefill_batch",
                    q_tokens=int(sum(chunks)),
                    kv_read_tokens=int(
                        sum(d + c for d, c in zip(dones, chunks))
                    ),
                    kv_write_tokens=int(sum(chunks)),
                    attn_q_ctx=int(sum(
                        obs.attribution.prefill_attn_positions(d, c)
                        for d, c in zip(dones, chunks)
                    )),
                )
                out: dict[int, Any] = {}
                finished_rows = [
                    i for i, (seq, d, c) in enumerate(zip(seqs, dones, chunks))
                    if d + c >= seq.prompt_len
                ]
                # Pre-screen constrained rows: a raising mask_fn must fail
                # only its own row, and _sample_one batches every finished
                # row's masks in one call (mask_fns are pure, so the
                # screening call duplicates no state).
                bad: dict[int, Exception] = {}
                for i in finished_rows:
                    if seqs[i].mask_fn is None:
                        continue
                    try:
                        seqs[i].mask_fn(seqs[i].tokens)
                    except Exception as e:  # noqa: BLE001
                        bad[i] = e
                finished_rows = [i for i in finished_rows if i not in bad]
                first_toks = None
                if finished_rows:
                    # Sample the FULL padded batch and index on host: a
                    # device gather of `finished_rows` would specialize
                    # sample/gather programs on every distinct finished
                    # count (r04 on-chip: dozens of tiny compiles at ~1 s
                    # each over the tunneled remote-compile, all inside
                    # the serving window). Bp is already the program's
                    # padded row bucket; padding rows sample greedily
                    # into a discarded slot.
                    fset = set(finished_rows)
                    row_seqs: list[Any] = [
                        seqs[i] if i in fset else None
                        for i in range(len(seq_ids))
                    ] + [None] * (Bp - len(seq_ids))
                    first_toks = self._sample_one(logits, row_seqs)
                for i, (sid, seq, d, c) in enumerate(
                    zip(seq_ids, seqs, dones, chunks)
                ):
                    if i in bad:
                        self._drop_admission(sid)
                        out[sid] = bad[i]
                        continue
                    if d + c < seq.prompt_len:
                        self._prefilling[sid] = d + c
                        out[sid] = False
                        continue
                    del self._prefilling[sid]
                    token = int(first_toks[i])
                    seq.ttft_s = time.perf_counter() - seq.started_s
                    perf.record_metric("engine.ttft", seq.ttft_s * 1e3, "ms")
                    self._first_token_obs(seq)
                    try:
                        self._accept_token(seq, token)
                    except Exception as e:  # noqa: BLE001 - stream callback
                        self._drop_admission(sid)
                        out[sid] = e
                        continue
                    out[sid] = True
                self._observe_occupancy()
                return out
            except Exception:
                for sid in seq_ids:
                    self._drop_admission(sid)
                raise

    def _drop_admission(self, seq_id: int) -> None:
        """Clean one failed admission: pages freed, host state dropped."""
        self.sequences.pop(seq_id, None)
        self._prefilling.pop(seq_id, None)
        self.alloc.free(seq_id)

    def prefill_step(self, seq_id: int) -> bool:
        """Stage 2 of admission: run ONE bucket-sized prefill chunk,
        attending over all cache content before it (prefix pages plus
        previously prefilled chunks). Returns True when the prompt is fully
        prefilled — at which point the first token has been sampled and the
        sequence is decodable. Chunking keeps admission independent of
        prefix-cache state AND lets the scheduler slot decode blocks
        between chunks of a long prompt.

        On failure the sequence is cleaned up (pages freed, Sequence
        dropped) before the exception propagates: the scheduler only ever
        holds seq_ids whose state is live."""
        with self.lock:
            self._async_settle()
            self._mixed_gap_stamp = None  # see step(): gap continuity ends
            seq = self.sequences[seq_id]
            done = self._prefilling[seq_id]
            n = seq.prompt_len
            try:
                table = jnp.asarray(
                    self.alloc.page_table_row(seq_id)[None, :]
                )
                chunk = min(n - done, self.cfg.prefill_buckets[-1])
                bucket = self._bucket(chunk)
                tokens = np.full((1, bucket), self.tokenizer.pad_id, np.int32)
                tokens[0, :chunk] = seq.prompt_ids[done:done + chunk]
                dev_out: list = []
                with annotate("engine.prefill_chunk"), \
                        device_timer("prefill_chunk", dev_out), self.mesh_ctx():
                    if done:
                        logits, self.cache = self._prefill_prefix_jit(
                            self.params,
                            jnp.asarray(tokens),
                            jnp.asarray([done], jnp.int32),
                            jnp.asarray([chunk], jnp.int32),
                            self.cache,
                            table,
                        )
                    else:
                        logits, self.cache = self._prefill_jit(
                            self.params,
                            jnp.asarray(tokens),
                            jnp.asarray([chunk], jnp.int32),
                            self.cache,
                            table,
                        )
                    dev_out.append(logits)
                done += chunk
                perf = get_perf_stats()
                perf.record_metric("engine.prefill_tokens", chunk, "tok")
                obs.PREFILL_TOKENS.inc(chunk)
                obs.flight.record(
                    "dispatch", op="prefill_chunk", seq_id=seq_id,
                    bucket=bucket, prefill_tokens=chunk,
                    prompt_done=done, prompt_total=n,
                )
                self.attr.dispatch(
                    "prefill_chunk",
                    q_tokens=chunk,
                    kv_read_tokens=done,  # done already includes chunk
                    kv_write_tokens=chunk,
                    attn_q_ctx=obs.attribution.prefill_attn_positions(
                        done - chunk, chunk
                    ),
                )
                if done < n:
                    self._prefilling[seq_id] = done
                    return False
                del self._prefilling[seq_id]
                token = int(self._sample_one(logits, [seq])[0])
                seq.ttft_s = time.perf_counter() - seq.started_s
                perf.record_metric("engine.ttft", seq.ttft_s * 1e3, "ms")
                self._first_token_obs(seq)
                self._accept_token(seq, token)
                self._observe_occupancy()
                return True
            except Exception:
                # Failed admissions (prefill OOM, raising mask_fn, a raising
                # stream callback on the first token, ...) must not leak
                # pages or a stale Sequence.
                self.sequences.pop(seq_id, None)
                self._prefilling.pop(seq_id, None)
                self.alloc.free(seq_id)
                raise

    # -- mixed prefill+decode step -------------------------------------------
    def _mixed_bucket(self, n: int) -> int:
        """Smallest mixed-chunk bucket holding n query rows."""
        for b in self.cfg.mixed_buckets:
            if n <= b:
                return b
        return self.cfg.mixed_buckets[-1]

    def mixed_hosted(self, seq_id: int) -> bool:
        """True when this sequence needs host-side per-token work — a
        constrained-decoding mask, logprobs, or a logit bias/penalty —
        that the fused mixed program does not serve. The scheduler routes
        ticks involving such rows to the split prefill/decode path."""
        with self.lock:
            s = self.sequences.get(seq_id)
            if s is None:
                return False
            return bool(
                s.mask_fn is not None
                or s.params.logprobs
                or self._needs_bias(s)
            )

    def prefill_progress(self, seq_id: int) -> tuple[int, int]:
        """(tokens already prefilled, prompt length) for an admitting
        sequence — the scheduler's input for sizing mixed-step chunks."""
        with self.lock:
            return self._prefilling[seq_id], self.sequences[seq_id].prompt_len

    def step_mixed(
        self, decode_ids: list[int], prefill_chunks: dict[int, int]
    ) -> tuple[dict[int, list[int]], dict[int, Any]]:
        """ONE device dispatch that advances every given decode lane by
        one token AND runs one prefill chunk for each admitting sequence
        in ``prefill_chunks`` ({seq_id: chunk tokens}) — the unified mixed
        step. The chunk rows pad into the smallest ``mixed_buckets`` entry
        holding the largest chunk, decode rows ride at q_len=1, and the
        whole batch shares one weight stream (the split tick streams
        weights once for the prefill program and again for the decode
        program; on a weight-streaming-bound model that is the dominant
        per-tick cost). TTFT stops being quantized to decode-block
        boundaries because an admitting prompt advances every tick.

        The mixed path bypasses the device-resident block-decode carry, so
        any in-flight pipelined state is flushed first (same contract as
        ``step``); rows needing host-side per-token work are the caller's
        job to exclude (see ``mixed_hosted``).

        Returns ``(decode_out, prefill_out)``: decode_out maps each
        advanced decode sequence to its new token; prefill_out follows the
        ``prefill_batch`` contract ({seq_id: fully_prefilled | Exception},
        row-local failures isolated). Unlike ``step``, a raising stream
        callback on a decode row does NOT propagate — the row finishes
        with reason "error" (the scheduler's reap path surfaces it) so the
        prefill results of the same dispatch are never lost. A failed
        DISPATCH cleans up every chunk admission, rolls back the decode
        rows' one-token page bookings, and re-raises."""
        with self.lock:
            self._async_settle()
            while self._inflight or self._lane_of:
                # Settle the pipelined block-decode state: its device
                # carry tracks lane write offsets that a mixed dispatch
                # would silently desync.
                try:
                    self._flush_and_invalidate()
                except Exception:  # noqa: BLE001 - raising stream callback
                    # A pulled block's raising stream callback belongs to
                    # its OWN row (already finished as "error"; the reap
                    # path surfaces it). Propagating from here would fail
                    # this tick's innocent chunk admissions with another
                    # client's disconnect — keep draining instead.
                    log.exception(
                        "stream callback raised while settling pipelined "
                        "state for a mixed dispatch; row isolated"
                    )
            decode = [
                self.sequences[s] for s in decode_ids
                if s in self.sequences and not self.sequences[s].done
            ]
            B = self.cfg.max_batch_size
            if len(decode) + len(prefill_chunks) > B:
                raise ValueError(
                    f"mixed batch of {len(decode)} decode + "
                    f"{len(prefill_chunks)} prefill rows exceeds "
                    f"max_batch_size={B}"
                )
            # Book the token each decode row is about to write (the
            # step() contract: a row that cannot grow finishes as
            # truncated instead of killing the dispatch).
            grown: list[Sequence] = []
            for s in decode:
                try:
                    self.alloc.extend(s.seq_id, 1)
                    grown.append(s)
                except OutOfPages:
                    s.done = True
                    s.finish_reason = "length"
                    obs.PREEMPTIONS.inc()
                    obs.flight.record("preemption", seq_id=s.seq_id)
                    log.warning(
                        "seq %d truncated: KV page budget exhausted",
                        s.seq_id,
                    )
            decode = grown
            decode_out: dict[int, list[int]] = {}
            prefill_out: dict[int, Any] = {}
            if not decode and not prefill_chunks:
                return decode_out, prefill_out
            chunk_info: list[tuple[int, Sequence, int, int]] = []
            smax = 1
            for sid, want in prefill_chunks.items():
                seq = self.sequences[sid]
                done = self._prefilling[sid]
                c = min(
                    want, self.cfg.mixed_buckets[-1], seq.prompt_len - done
                )
                chunk_info.append((sid, seq, done, c))
                smax = max(smax, c)
            S = self._mixed_bucket(smax)
            tokens = np.full((B, S), self.tokenizer.pad_id, np.int32)
            starts = np.zeros((B,), np.int32)
            qlens = np.zeros((B,), np.int32)
            tables = np.full((B, self.cfg.max_pages_per_seq), -1, np.int32)
            for i, s in enumerate(decode):
                tokens[i, 0] = (
                    s.tokens[-1] if s.tokens else self.tokenizer.bos_id
                )
                # extend(1) above made alloc.length = written + 1; the row
                # writes (and attends from) the written offset.
                starts[i] = self.alloc.length(s.seq_id) - 1
                qlens[i] = 1
                tables[i] = self.alloc.page_table_row(s.seq_id)
            base = len(decode)
            for j, (sid, seq, done, c) in enumerate(chunk_info):
                tokens[base + j, :c] = seq.prompt_ids[done:done + c]
                starts[base + j] = done
                qlens[base + j] = c
                tables[base + j] = self.alloc.page_table_row(sid)
            slots: list[Sequence | None] = (
                decode + [seq for _, seq, _, _ in chunk_info]
            )
            slots += [None] * (B - len(slots))
            temps, top_k, top_p, _ = self._sampling_arrays(slots, B)
            perf = get_perf_stats()
            t_disp = time.perf_counter()
            # Host-gap observable (the async A/B's comparison basis): time
            # since the previous mixed dispatch's enqueue returned — in
            # this SYNC tick it spans the blocking token pull plus all
            # host post-processing, the span the async runtime overlaps.
            if self._mixed_gap_stamp is not None:
                gap = t_disp - self._mixed_gap_stamp
                obs.STEP_HOST_GAP_SECONDS.observe(gap, mode="sync")
                perf.record_metric("engine.step_host_gap", gap * 1e3, "ms")
            try:
                dev_out: list = []
                with annotate("engine.mixed_step"), \
                        device_timer("mixed_step", dev_out), self.mesh_ctx():
                    self._sample_key, sub = jax.random.split(self._sample_key)
                    toks_d, self.cache = self._mixed_sample_jit(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray(starts),
                        jnp.asarray(qlens),
                        self.cache,
                        jnp.asarray(tables),
                        sub,
                        jnp.asarray(temps),
                        jnp.asarray(top_k),
                        jnp.asarray(top_p),
                    )
                    dev_out.append(toks_d)
                self._mixed_gap_stamp = time.perf_counter()
                sampled = np.asarray(toks_d)
            except Exception:
                # The decode rows' +1 bookings are for tokens this failed
                # dispatch never wrote; leaving them would put an
                # unwritten hole inside the attended window next step.
                for s in decode:
                    if not s.done:
                        self.alloc.truncate(
                            s.seq_id, self.alloc.length(s.seq_id) - 1
                        )
                for sid, *_ in chunk_info:
                    self._drop_admission(sid)
                raise
            measured_s = time.perf_counter() - t_disp
            perf.record_metric(
                "engine.mixed_dispatch", measured_s * 1e3, "ms",
            )
            n_prefill = int(sum(c for *_, c in chunk_info))
            if n_prefill:
                perf.record_metric(
                    "engine.prefill_tokens", n_prefill, "tok"
                )
                obs.PREFILL_TOKENS.inc(n_prefill)
            from .decode_loop import record_mixed_dispatch

            # Attribution composition: decode lanes attend their whole
            # written context; chunk rows read their prefix + chunk. The
            # sync tick's dispatch+pull wall time is a real synchronous
            # measurement, so it also feeds the drift gauge.
            dec_ctx = int(sum(int(starts[i]) + 1 for i in range(len(decode))))
            record_mixed_dispatch(
                decode_rows=len(decode),
                prefill_tokens=n_prefill,
                budget=self.cfg.max_step_tokens,
                attr=self.attr,
                attr_kw=dict(
                    q_tokens=len(decode) + n_prefill,
                    kv_read_tokens=dec_ctx + int(
                        sum(d + c for _sid, _seq, d, c in chunk_info)
                    ),
                    kv_write_tokens=len(decode) + n_prefill,
                    attn_q_ctx=dec_ctx + int(sum(
                        obs.attribution.prefill_attn_positions(d, c)
                        for _sid, _seq, d, c in chunk_info
                    )),
                    measured_s=measured_s,
                ),
            )
            obs.flight.record(
                "dispatch", op="mixed",
                decode_seq_ids=[s.seq_id for s in decode],
                prefill_seq_ids=[sid for sid, *_ in chunk_info],
                bucket=int(S), prefill_tokens=n_prefill,
                budget=self.cfg.max_step_tokens,
            )
            for i, s in enumerate(decode):
                tok = int(sampled[i])
                dspan = s.decode_span
                try:
                    self._accept_token(s, tok)
                except Exception:  # noqa: BLE001 - raising stream callback
                    # Row-local isolation WITHOUT propagation: the reap
                    # path surfaces finish_reason "error"; raising here
                    # would lose the same dispatch's prefill results.
                    s.done = True
                    s.finish_reason = s.finish_reason or "error"
                    self.alloc.truncate(s.seq_id, self._host_written(s))
                decode_out[s.seq_id] = [tok]
                if dspan is not None:
                    dspan.child(
                        "mixed_step", t_disp, time.perf_counter(), tokens=1
                    )
            for j, (sid, seq, done, c) in enumerate(chunk_info):
                if done + c < seq.prompt_len:
                    self._prefilling[sid] = done + c
                    prefill_out[sid] = False
                    continue
                del self._prefilling[sid]
                token = int(sampled[base + j])
                seq.ttft_s = time.perf_counter() - seq.started_s
                perf.record_metric("engine.ttft", seq.ttft_s * 1e3, "ms")
                self._first_token_obs(seq)
                try:
                    self._accept_token(seq, token)
                except Exception as e:  # noqa: BLE001 - stream callback
                    self._drop_admission(sid)
                    prefill_out[sid] = e
                    continue
                prefill_out[sid] = True
            if decode:
                perf.record_metric(
                    "engine.decode_tokens", len(decode), "tok"
                )
            self._observe_occupancy()
            return decode_out, prefill_out

    # -- grammar fast-forward (forced-token runs) ----------------------------
    def _note_ffwd_ineligible(self, s: Sequence) -> None:
        """Count a constrained row that cannot fast-forward (hosted mask /
        tables over budget / logprobs / logit bias) — once per sequence,
        under the fallback reason that separates "can't fast-forward"
        from "can't async"."""
        if s.seq_id in self._ffwd_noted:
            return
        self._ffwd_noted.add(s.seq_id)
        obs.ASYNC_FALLBACKS.inc(reason="ffwd_ineligible")

    def _ffwd_candidate(self, s: Sequence) -> tuple[Any, list[int]] | None:
        """(fsm, forced run D) when this row can splice a forced run right
        now, else None. The dispatch inputs are ``[last_token] + D`` and
        the masked sample after D is itself deterministic — eos when the
        run ended there (trimmed off D: a masked sample at an eos-only
        state yields eos at ANY temperature), or D's forced successor when
        the cap cut the run short. D is clamped so the append (D plus the
        sampled token) never overshoots max_tokens or the largest mixed
        bucket."""
        if s.mask_fn is None or s.done or not s.tokens:
            return None
        if s.params.logprobs or self._needs_bias(s):
            self._note_ffwd_ineligible(s)
            return None
        from .constrained import device_table_fsm

        fsm = device_table_fsm(s.mask_fn)
        if fsm is None:
            self._note_ffwd_ineligible(s)
            return None
        run = s.mask_fn.forced_run(s.tokens)
        if run and run[-1] == fsm.eos_id:
            run = run[:-1]
        room = s.params.max_tokens - len(s.tokens) - 1
        run = run[: max(0, min(room, self.cfg.mixed_buckets[-1] - 1))]
        if not run:
            return None
        return fsm, run

    def ffwd_step(self, seq_ids: list[int]) -> dict[int, list[int]]:
        """Grammar fast-forward: for constrained rows whose current FSM
        state forces a run of singleton-mask tokens, splice the whole run
        into the paged KV as ONE multi-token append (the q_len>1 path the
        mixed program already serves for prefill chunks) and sample only
        the token AFTER the run — every forced token skips a full forward
        pass. Greedy output is byte-identical with the feature off: a
        masked sample over a singleton support produces that token at any
        temperature.

        The pre-scan never disturbs the block pipeline: rows with
        device-resident in-flight tokens have stale host token lists and
        are skipped (no flush-thrash when nothing is forced). Only when a
        settled row has a forced run does this settle the pipelines and
        dispatch. Returns {seq_id: accepted tokens} (empty when nothing
        was eligible)."""
        with self.lock:
            if not (self.cfg.grammar_ffwd and self.cfg.mixed_batching):
                return {}

            def scan(skip_inflight: bool) -> list[tuple]:
                out = []
                for sid in seq_ids:
                    s = self.sequences.get(sid)
                    if s is None or s.done:
                        continue
                    # A lane ASSIGNMENT alone does not stale the host
                    # token list — only booked steps still in flight do
                    # (the carry's pending write slot is a page booking,
                    # not a token). Scanning settled lane rows is what
                    # lets ffwd engage between blocks without flushing
                    # speculatively.
                    if skip_inflight and (
                        sid in self._inflight_steps
                        or self._async._inflight_toks.get(sid, 0)
                    ):
                        continue
                    cand = self._ffwd_candidate(s)
                    if cand is not None:
                        out.append((s, *cand))
                return out

            if not scan(True):
                return {}
            self._async_settle()
            while self._inflight or self._lane_of:
                try:
                    self._flush_and_invalidate()
                except Exception:  # noqa: BLE001 - raising stream callback
                    log.exception(
                        "stream callback raised while settling pipelined "
                        "state for a ffwd dispatch; row isolated"
                    )
            # Re-scan on settled host state: pulled blocks appended tokens
            # (and may have finished rows) during the flush.
            cands = scan(False)
            if not cands:
                return {}
            # One shared table set per dispatch: keep the first fsm's
            # group, the rest retry next tick.
            fsm0 = cands[0][1]
            cands = [
                c for c in cands if c[1] is fsm0
            ][: self.cfg.max_batch_size]
            rows: list[tuple[Sequence, list[int]]] = []
            for s, _fsm, run in cands:
                try:
                    self.alloc.extend(s.seq_id, 1 + len(run))
                except OutOfPages:
                    # Roll back partially-grabbed pages and leave the row
                    # to the normal decode path, which finishes it as
                    # "length" when the pool is truly dry.
                    self.alloc.truncate(
                        s.seq_id, self.alloc.length(s.seq_id)
                    )
                    continue
                rows.append((s, run))
            if not rows:
                return {}
            B = self.cfg.max_batch_size
            S = self._mixed_bucket(max(1 + len(r) for _, r in rows))
            tokens = np.full((B, S), self.tokenizer.pad_id, np.int32)
            starts = np.zeros((B,), np.int32)
            qlens = np.zeros((B,), np.int32)
            emits = np.zeros((B,), bool)
            ov_fsm = np.zeros((B,), np.int32)  # 0 = FREE sentinel row
            tables = np.full((B, self.cfg.max_pages_per_seq), -1, np.int32)
            temps = np.zeros((B,), np.float32)
            top_k = np.zeros((B,), np.int32)
            top_p = np.ones((B,), np.float32)
            for i, (s, run) in enumerate(rows):
                q = 1 + len(run)
                tokens[i, :q] = [s.tokens[-1]] + run
                # extend above made alloc.length = written + q; the row
                # writes its q inputs from the written offset.
                starts[i] = self.alloc.length(s.seq_id) - q
                qlens[i] = q
                emits[i] = True
                tables[i] = self.alloc.page_table_row(s.seq_id)
                # The masked sample applies the state AFTER the appended
                # run (+1: device-table row 0 is the FREE sentinel).
                ov_fsm[i] = s.mask_fn.dfa_state(s.tokens + run) + 1
                temps[i] = s.params.temperature
                top_k[i] = s.params.top_k
                top_p[i] = s.params.top_p
            fm, fd = self._fsm_device_tables(fsm0)
            zb = jnp.zeros((B,), bool)
            zi = jnp.zeros((B,), jnp.int32)
            perf = get_perf_stats()
            t_disp = time.perf_counter()
            try:
                dev_out: list = []
                with annotate("engine.ffwd_step"), \
                        device_timer("ffwd_step", dev_out), self.mesh_ctx():
                    self._sample_key, sub = jax.random.split(self._sample_key)
                    toks_d, self.cache, _fsm_d = self._mixed_carry_jit(
                        self.params,
                        jnp.asarray(tokens),
                        zb,  # use_carry: all rows override from host
                        zi,  # carry tokens (unused at use_carry=False)
                        jnp.asarray(starts),
                        jnp.asarray(qlens),
                        jnp.asarray(emits),
                        self.cache,
                        jnp.asarray(tables),
                        sub,
                        jnp.asarray(temps),
                        jnp.asarray(top_k),
                        jnp.asarray(top_p),
                        fsm_mask=fm, fsm_dest=fd,
                        carry_fsm=zi, ov_fsm=jnp.asarray(ov_fsm),
                    )
                    dev_out.append(toks_d)
                self._mixed_gap_stamp = time.perf_counter()
                sampled = np.asarray(toks_d)
            except Exception:
                # Nothing was accepted: roll every booking back to
                # written truth before surfacing the dispatch error.
                for s, _run in rows:
                    if not s.done:
                        self.alloc.truncate(s.seq_id, self._host_written(s))
                raise
            measured_s = time.perf_counter() - t_disp
            perf.record_metric(
                "engine.ffwd_dispatch", measured_s * 1e3, "ms"
            )
            obs.DECODE_DISPATCHES.inc(kind="ffwd")
            n_forced = int(sum(len(r) for _, r in rows))
            q_total = n_forced + len(rows)
            self.attr.dispatch(
                "ffwd_append",
                q_tokens=q_total,
                kv_read_tokens=int(sum(
                    int(starts[i]) + int(qlens[i])
                    for i in range(len(rows))
                )),
                kv_write_tokens=q_total,
                attn_q_ctx=int(sum(
                    obs.attribution.prefill_attn_positions(
                        int(starts[i]), int(qlens[i])
                    )
                    for i in range(len(rows))
                )),
                measured_s=measured_s,
            )
            obs.flight.record(
                "dispatch", op="ffwd",
                decode_seq_ids=[s.seq_id for s, _ in rows],
                bucket=int(S), forced_tokens=n_forced,
            )
            from .decode_loop import record_ffwd_append

            decode_out: dict[int, list[int]] = {}
            produced = 0
            for i, (s, run) in enumerate(rows):
                accepted: list[int] = []
                dspan = s.decode_span
                try:
                    for t in run:
                        if s.done:
                            break
                        self._accept_token(s, t)
                        accepted.append(t)
                    if not s.done:
                        tok = int(sampled[i])
                        self._accept_token(s, tok)
                        accepted.append(tok)
                except Exception:  # noqa: BLE001 - raising stream callback
                    # Row-local isolation, same contract as step_mixed:
                    # the reap path surfaces finish_reason "error".
                    s.done = True
                    s.finish_reason = s.finish_reason or "error"
                if s.done:
                    # Stop string / EOS / max_tokens (or a raising
                    # callback) landed mid-append: the tail of the booked
                    # run is dead content — roll back to written truth.
                    self.alloc.truncate(s.seq_id, self._host_written(s))
                n_ff = min(len(accepted), len(run))
                if n_ff:
                    record_ffwd_append(
                        s.seq_id, n_ff, attr=self.attr,
                        request_id=obs.flight.request_id_of(s.trace),
                    )
                if dspan is not None:
                    dspan.child(
                        "ffwd_step", t_disp, time.perf_counter(),
                        tokens=len(accepted),
                    )
                decode_out[s.seq_id] = accepted
                produced += len(accepted)
            if produced:
                perf.record_metric("engine.decode_tokens", produced, "tok")
            self._observe_occupancy()
            return decode_out

    def _sampling_arrays(
        self, seqs: list[Sequence | None], B: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray | None]:
        """Per-slot (temps, top_k, top_p, allowed-mask-or-None) arrays."""
        temps = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        mask = None
        for i, s in enumerate(seqs):
            if s is None:
                continue
            temps[i] = s.params.temperature
            top_k[i] = s.params.top_k
            top_p[i] = s.params.top_p
            if s.mask_fn is not None:
                if mask is None:
                    mask = np.ones((B, self.model_cfg.vocab_size), bool)
                m = s.mask_fn(s.tokens)
                # Checkpoints pad the embedding vocab past the tokenizer's
                # (e.g. Qwen 152064 vs ~151.7k): padded ids are forbidden on
                # constrained rows — the tokenizer could never decode them.
                n = min(len(m), mask.shape[1])
                mask[i, :n] = m[:n]
                mask[i, n:] = False
        return temps, top_k, top_p, mask

    def _fsm_device_tables(self, fsm) -> tuple[jax.Array, jax.Array]:
        """Device-resident ([S+1, V] mask, dest) for one TokenFSM, cached
        (keyed by identity, holding the fsm so a reused id() can't alias).
        Tiny LRU: schemas churn rarely and each table set re-specializes
        the decode-block program anyway."""
        ent = self._fsm_dev.get(id(fsm))
        if ent is not None and ent[0] is fsm:
            return ent[1], ent[2]
        mask, dest = fsm.dense_tables()
        m, d = jnp.asarray(mask), jnp.asarray(dest)
        self._fsm_dev[id(fsm)] = (fsm, m, d)
        while len(self._fsm_dev) > 2:
            self._fsm_dev.pop(next(iter(self._fsm_dev)))
        return m, d

    @staticmethod
    def _needs_bias(s: Sequence) -> bool:
        p = s.params
        return bool(
            p.logit_bias or p.presence_penalty or p.frequency_penalty
        )

    def _bias_array(
        self, seqs: list[Sequence | None], B: int
    ) -> np.ndarray | None:
        """Additive [B, V] logit bias, or None when no row needs one:
        OpenAI logit_bias entries plus presence/frequency penalties over
        each row's generated-so-far token counts (including tokens
        generated before an engine restart — params.penalty_history).
        The static logit_bias row is cached per sequence and the batch
        buffer reused, so pure-logit_bias rows cost a memcpy per step."""
        V = self.model_cfg.vocab_size
        bias = None
        for i, s in enumerate(seqs):
            if s is None or not self._needs_bias(s):
                continue
            if bias is None:
                if self._bias_buf is None or self._bias_buf.shape != (B, V):
                    self._bias_buf = np.zeros((B, V), np.float32)
                else:
                    self._bias_buf[:] = 0.0
                bias = self._bias_buf
            p = s.params
            if s.static_bias is None:
                row = np.zeros((V,), np.float32)
                for tid, b in p.logit_bias:
                    if 0 <= tid < V:
                        row[tid] += b
                s.static_bias = row
            bias[i] = s.static_bias
            if p.presence_penalty or p.frequency_penalty:
                counts = s.penalty_counts
                if counts is None and p.penalty_history:
                    # Before the first accepted token: seed from salvage.
                    counts = {}
                    for t in p.penalty_history:
                        counts[t] = counts.get(t, 0) + 1
                for tid, c in (counts or {}).items():
                    if 0 <= tid < V:
                        bias[i, tid] -= (
                            p.presence_penalty + p.frequency_penalty * c
                        )
        return bias

    def _sample_one(self, logits: jax.Array, seqs: list[Sequence]) -> np.ndarray:
        B = logits.shape[0]
        temps, top_k, top_p, mask = self._sampling_arrays(seqs, B)
        bias = self._bias_array(seqs, B)
        if bias is not None:
            logits = logits + jnp.asarray(bias)
        # Under the mesh context ALWAYS: the jit cache keys on the ambient
        # mesh, so a call outside `with self.mesh:` recompiles the sampler
        # (and the eager random.split helpers) with an identical signature
        # (r04: warmed sample programs were recompiled inside the serving
        # window because prefill_batch sampled outside the mesh block
        # warmup used).
        with self.mesh_ctx():
            self._sample_key, sub = jax.random.split(self._sample_key)
            tok = self._sample_jit(
                logits,
                sub,
                jnp.asarray(temps),
                jnp.asarray(top_k),
                jnp.asarray(top_p),
                None if mask is None else jnp.asarray(mask),
            )
        toks = np.asarray(tok)
        if any(s is not None and s.params.logprobs for s in seqs):
            # First-token logprobs (prefill's sampled token), host-side:
            # admission is not the steady-state hot loop.
            lg = np.asarray(
                jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            )
            tv = min(self.tokenizer.vocab_size, lg.shape[1])
            for i, s in enumerate(seqs):
                if s is None or not s.params.logprobs:
                    continue
                row, n = lg[i].copy(), s.params.top_logprobs
                row[tv:] = -np.inf  # padded-vocab ids: unrenderable
                top = []
                if n > 0:
                    idx = np.argpartition(-row, n)[:n]
                    idx = idx[np.argsort(-row[idx])]
                    top = [(int(j), float(row[j])) for j in idx]
                s.logprob_data.append(
                    {"logprob": float(row[int(toks[i])]), "top": top}
                )
        return toks

    # -- observability -------------------------------------------------------
    def _observe_occupancy(self) -> None:
        """Refresh the engine-step gauges (KV page utilization, batch
        occupancy) and delta-sync the allocator's cumulative prefix-trie
        eviction count into the obs counter. Cheap host math — called from
        admission, step, and finish paths under the engine lock."""
        free = self.alloc.free_pages
        obs.KV_PAGES_FREE.set(free)
        obs.KV_PAGE_UTILIZATION.set(1.0 - free / max(1, self.alloc.num_pages))
        running = sum(1 for s in self.sequences.values() if not s.done)
        obs.BATCH_OCCUPANCY.set(running / max(1, self.cfg.max_batch_size))
        obs.RUNNING_SEQUENCES.set(len(self.sequences))
        ev = self.alloc.evictions
        if ev > self._evictions_seen:
            obs.PREFIX_EVICTIONS.inc(ev - self._evictions_seen)
            obs.flight.record(
                "prefix_eviction", count=ev - self._evictions_seen
            )
            self._evictions_seen = ev

    def _first_token_obs(self, seq: Sequence) -> None:
        """Prefill finished and the first token was sampled: observe TTFT,
        record the prefill span, and open the request's decode span (per-
        dispatch block spans attach under it; closed when the sequence
        finishes). A TTFT past the SLO threshold is a flight-recorder
        anomaly: the ring dump holds the admissions and dispatch
        compositions of the seconds leading up to the slow first token."""
        obs.TTFT_SECONDS.observe(seq.ttft_s)
        cls = obs.trace.class_of(seq.trace)
        if cls:
            obs.CLASS_TTFT_SECONDS.observe(seq.ttft_s, **{"class": cls})
        obs.attribution.record_goodput(seq.ttft_s, "prefill", slo_class=cls)
        ttft_ms = round(seq.ttft_s * 1e3, 3)
        rid = obs.flight.request_id_of(seq.trace)
        obs.flight.record(
            "ttft", seq_id=seq.seq_id, ttft_ms=ttft_ms, request_id=rid
        )
        thr = obs.flight.ttft_threshold_s()
        if thr > 0 and seq.ttft_s > thr:
            obs.flight.anomaly(
                "ttft_breach", seq_id=seq.seq_id, ttft_ms=ttft_ms,
                threshold_ms=round(thr * 1e3, 3), request_id=rid,
            )
        now = time.perf_counter()
        if seq.trace is not None:
            seq.trace.child(
                "prefill", seq.started_s, now,
                prompt_tokens=seq.prompt_len,
            )
            seq.decode_span = seq.trace.start_child("decode")

    def _accept_token(self, seq: Sequence, token: int) -> None:
        seq.tokens.append(token)
        obs.DECODE_TOKENS.inc()
        now = time.perf_counter()
        if seq.last_tok_s:
            obs.ITL_SECONDS.observe(now - seq.last_tok_s)
            cls = obs.trace.class_of(seq.trace)
            if cls:
                obs.CLASS_ITL_SECONDS.observe(
                    now - seq.last_tok_s, **{"class": cls}
                )
        seq.last_tok_s = now
        p = seq.params
        if p.presence_penalty or p.frequency_penalty:
            if seq.penalty_counts is None:
                seq.penalty_counts = {}
                for t in p.penalty_history:
                    seq.penalty_counts[t] = seq.penalty_counts.get(t, 0) + 1
            seq.penalty_counts[token] = seq.penalty_counts.get(token, 0) + 1
        if seq.stream is not None:
            seq.stream(token)
        if token == self.tokenizer.eos_id:
            seq.done = True
            seq.finish_reason = "stop"
        elif len(seq.tokens) >= seq.params.max_tokens:
            seq.done = True
            seq.finish_reason = "length"
        elif seq.params.stop and self._hit_stop_string(seq):
            seq.done = True
            seq.finish_reason = "stop"
        if seq.done and seq.decode_span is not None:
            obs.attribution.record_goodput(
                seq.decode_span.duration_s(), "decode_active",
                slo_class=obs.trace.class_of(seq.trace),
            )
            seq.decode_span.close(
                tokens=len(seq.tokens), finish_reason=seq.finish_reason
            )
            seq.decode_span = None

    def _hit_stop_string(self, seq: Sequence) -> bool:
        """Check the decoded tail for any stop string, so generation halts at
        the stop instead of burning decode steps to max_tokens. The window is
        sized in TOKENS: one char can span up to 4 byte-level tokens (UTF-8),
        so a char-sized window would miss long multi-byte stop strings."""
        longest = max(len(s) for s in seq.params.stop)
        tail_tokens = seq.tokens[-(longest * 4 + 8) :]
        tail = self.tokenizer.decode(tail_tokens)
        return any(s in tail for s in seq.params.stop)

    # -- pipelined decode internals ------------------------------------------
    def _host_written(self, seq: Sequence) -> int:
        """Tokens actually written to this sequence's pages: the prompt plus
        every accepted token except the last sampled one (never written)."""
        return seq.prompt_len + max(0, len(seq.tokens) - 1)

    def _free_lane(self, seq_id: int) -> None:
        lane = self._lane_of.pop(seq_id, None)
        if lane is not None:
            self._lanes[lane] = None

    def _flush_and_invalidate(self) -> None:
        """Drain in-flight dispatches and drop the device-resident decode
        state, rolling page bookings back to written content. Called before
        the legacy single-step path touches a lane-held sequence (its
        extend/truncate bookkeeping would desync the device carry)."""
        while self._inflight:
            self._pull_oldest()
        for sid in list(self._lane_of):
            s = self.sequences.get(sid)
            if s is not None and not s.done:
                self.alloc.truncate(sid, self._host_written(s))
        self._lanes = [None] * self.cfg.max_batch_size
        self._lane_of.clear()
        self._carry = None
        self._hist = None

    def _async_settle(self) -> None:
        """Commit every in-flight ASYNC mixed tick (results buffered for
        ``async_take_results``). Sync-lane entry points call this before
        touching sequence/allocator state the lookahead pipeline may
        still reference — the async dual of ``_flush_and_invalidate``."""
        if self._async.pending:
            self._async.flush()

    # -- async mixed pipeline (serving/async_runtime.py) ---------------------
    def step_mixed_async(
        self, decode_ids: list[int], prefill_chunks: dict[int, int]
    ) -> tuple[dict[int, list[int]], dict[int, Any]]:
        """The one-step-lookahead form of ``step_mixed``: dispatch this
        tick's batch and return the COMMITTED results so far — which, at
        ``cfg.async_depth`` > 1, lag the dispatch by up to depth-1 ticks.
        Decode-lane feedback stays device-resident between dispatches, so
        the host's post-processing of tick t overlaps tick t+1's device
        execution. Same exclusions as ``step_mixed`` (the caller routes
        hosted rows away — see ``mixed_async_hosted``); same return
        contract, with tokens/list values since several commits may land
        in one call."""
        with self.lock:
            while self._inflight or self._lane_of:
                try:
                    self._flush_and_invalidate()
                except Exception:  # noqa: BLE001 - raising stream callback
                    log.exception(
                        "stream callback raised while settling pipelined "
                        "state for an async mixed dispatch; row isolated"
                    )
            return self._async.step(decode_ids, prefill_chunks)

    def async_pending(self) -> int:
        """Dispatched-but-uncommitted async mixed ticks."""
        with self.lock:
            return self._async.pending

    def mixed_gap_break(self) -> None:
        """Mark a discontinuity in the mixed-tick cadence (scheduler went
        idle): the next mixed dispatch must not count the wait as host
        gap — opsagent_step_host_gap_seconds measures back-to-back ticks
        only."""
        self._mixed_gap_stamp = None

    def async_take_results(self) -> tuple[dict[int, list[int]], dict[int, Any]]:
        """Results committed by internal pipeline settles (parking,
        warmup, sync-lane fallbacks) since the last pickup — a finished
        admission must reach the scheduler even when its commit happened
        outside ``step_mixed_async``."""
        with self.lock:
            return self._async.take_results()

    def async_drain(self) -> tuple[dict[int, list[int]], dict[int, Any]]:
        """Flush the async pipeline and return everything committed."""
        with self.lock:
            self._async.flush()
            return self._async.take_results()

    def mixed_async_hosted(self, seq_id: int) -> bool:
        """True when this sequence cannot ride the ASYNC mixed lane: it
        needs host-side per-token work (logprobs, logit bias/penalties)
        or a constrained mask without dense device tables. Such rows
        route the tick to the existing sync lanes (``mixed_hosted`` /
        split path). Note the asymmetry with ``mixed_hosted``: a
        JsonConstraint WITH device tables is async-eligible — its mask
        comes from on-device FSM state."""
        with self.lock:
            s = self.sequences.get(seq_id)
            if s is None:
                return False
            if s.params.logprobs or self._needs_bias(s):
                return True
            if s.mask_fn is None:
                return False
            from .constrained import device_table_fsm

            return device_table_fsm(s.mask_fn) is None

    def note_ffwd_ineligible(self, seq_id: int) -> None:
        """Scheduler-facing form of ``_note_ffwd_ineligible``: count a
        row that a hosted-lane fallback just made ffwd-ineligible."""
        with self.lock:
            s = self.sequences.get(seq_id)
            if s is not None:
                self._note_ffwd_ineligible(s)

    def async_row_fsm(self, seq_id: int):
        """The dense-table TokenFSM behind this row's mask, or None. The
        scheduler uses it to keep each async dispatch on ONE shared table
        set (mixed-schema ticks fall back to the sync lanes)."""
        with self.lock:
            s = self.sequences.get(seq_id)
            if s is None:
                return None
            from .constrained import device_table_fsm

            return device_table_fsm(s.mask_fn)

    def _pull_oldest(self) -> dict[int, list[int]]:
        """Pull the oldest in-flight block's tokens (the one device->host
        round trip per dispatch) and fold them into host state. Records are
        pulled FIFO, so the host always sees a row's EOS before any of its
        later pad-only blocks."""
        toks_d, lane_seqs, budgets, counts_d, t_disp = self._inflight.popleft()
        perf = get_perf_stats()
        t0 = time.perf_counter()
        toks = np.asarray(toks_d)
        counts = None if counts_d is None else np.asarray(counts_d)
        perf.record_metric(
            "engine.block_pull", (time.perf_counter() - t0) * 1e3, "ms"
        )
        out: dict[int, list[int]] = {}
        produced = 0
        first_exc: BaseException | None = None
        for lane, sid in enumerate(lane_seqs):
            if sid is None or budgets[lane] == 0:
                continue
            left = self._inflight_steps.get(sid, 0) - int(budgets[lane])
            if left > 0:
                self._inflight_steps[sid] = left
            else:
                self._inflight_steps.pop(sid, None)
            s = self.sequences.get(sid)
            if s is None or s.done:
                continue  # finished/vanished while this block was in flight
            n0 = len(s.tokens)
            # The open decode span, captured BEFORE the accept loop can
            # close it (EOS mid-block): the block's span child must attach
            # to the span that was live while the block ran.
            dspan = s.decode_span
            try:
                if counts is None:
                    for j in range(int(budgets[lane])):
                        self._accept_token(s, int(toks[lane, j]))
                        if s.done:
                            break
                else:
                    # Speculative block: toks is [B, n_steps, k+1] with an
                    # explicit accepted count per scan step (pads within a
                    # step are rejection holes, not end-of-output).
                    # Accept-rate observability: each LIVE verify step
                    # (count > 0) emitted 1 corrected/bonus token plus its
                    # accepted drafts, so mean(spec_step_tokens) - 1 over k
                    # IS the draft accept rate on this workload. Recorded
                    # per step BEFORE the done-break so post-EOS steps
                    # (drafting from dead context) cannot drag the mean.
                    for st in range(counts.shape[1]):
                        c = int(counts[lane, st])
                        if c > 0 and not s.done:
                            perf.record_metric(
                                "engine.spec_step_tokens", float(c), "tok"
                            )
                        for j in range(c):
                            self._accept_token(s, int(toks[lane, st, j]))
                            if s.done:
                                break
                        if s.done:
                            break
            except Exception as e:  # noqa: BLE001 - raising stream callback
                if first_exc is None:
                    first_exc = e
                s.done = True
                s.finish_reason = s.finish_reason or "error"
            finally:
                accepted = s.tokens[n0:]
                out[sid] = accepted
                produced += len(accepted)
                if dspan is not None and accepted:
                    # Span per pulled block: dispatch -> pull. Blocks of
                    # one sequence overlap under pipeline_depth > 0, which
                    # is the point — the trace shows the pipelining.
                    dspan.child(
                        "decode_block", t_disp, time.perf_counter(),
                        tokens=len(accepted),
                    )
                if s.done:
                    # Roll pre-booked pages back to written content. Any
                    # still-in-flight dispatch may keep writing to the freed
                    # pages, but device execution is in dispatch order: a
                    # future owner's writes always land after the stale
                    # ones, so reuse is safe without draining.
                    self.alloc.truncate(sid, self._host_written(s))
                    self._free_lane(sid)
                elif counts is not None:
                    # Speculative rows emit <= their booking (draft misses),
                    # so unspent booking would drift the allocator length
                    # ahead of content without bound, truncating long
                    # generations early. Roll back to what in-flight
                    # dispatches can still touch: written content + their
                    # bookings + the draft-overhang slack (k+1 positions a
                    # verify step writes past its accepted count).
                    keep = (
                        self._host_written(s)
                        + self._inflight_steps.get(sid, 0)
                        + self.cfg.speculative_k + 1
                    )
                    if self.alloc.length(sid) > keep:
                        self.alloc.truncate(sid, keep)
        perf.record_metric("engine.decode_tokens", produced, "tok")
        self._observe_occupancy()
        if first_exc is not None:
            raise first_exc
        return out

    def step(self, seq_ids: list[int] | None = None) -> dict[int, int]:
        """One decode step over up to max_batch_size running sequences.
        Returns {seq_id: new_token} for sequences that advanced."""
        with self.lock:
            self._async_settle()
            # Host-gap continuity ends here: a non-mixed dispatch between
            # two mixed ticks would otherwise count as a giant "gap".
            self._mixed_gap_stamp = None
            targets = (
                list(self.sequences) if seq_ids is None else list(seq_ids)
            )
            if any(sid in self._lane_of for sid in targets):
                self._flush_and_invalidate()
            running = [
                s for s in self.sequences.values() if not s.done
            ] if seq_ids is None else [
                self.sequences[i] for i in seq_ids if not self.sequences[i].done
            ]
            running = running[: self.cfg.max_batch_size]
            if not running:
                return {}
            B = self.cfg.max_batch_size
            # Account for the token each sequence is about to write. A
            # sequence that cannot grow (pool exhausted or per-seq page cap)
            # is finished as truncated instead of killing the whole step.
            grown: list[Sequence] = []
            try:
                for s in running:
                    try:
                        self.alloc.extend(s.seq_id, 1)
                        grown.append(s)
                        continue
                    except OutOfPages:
                        pass
                    # Pool dry — possibly only transiently: the pipeline's
                    # in-flight blocks pre-book pages that their pulls roll
                    # back. Drain before declaring the sequence truncated.
                    while self._inflight:
                        self._pull_oldest()
                    try:
                        self.alloc.extend(s.seq_id, 1)
                        grown.append(s)
                    except OutOfPages:
                        s.done = True
                        s.finish_reason = "length"
                        obs.PREEMPTIONS.inc()
                        obs.flight.record("preemption", seq_id=s.seq_id)
                        log.warning(
                            "seq %d truncated: KV page budget exhausted",
                            s.seq_id,
                        )
            except BaseException:
                # The drain can re-raise a stream-callback exception. Undo
                # the +1 bookings made so far — they are for a token this
                # aborted step will never dispatch; leaving them would put
                # an unwritten hole inside the attended window next step.
                for s in grown:
                    if not s.done:
                        self.alloc.truncate(
                            s.seq_id, self.alloc.length(s.seq_id) - 1
                        )
                raise
            # A mid-loop pipeline drain can finish earlier-grown sequences
            # (EOS/stop in a pulled block); they must not decode further.
            running = [s for s in grown if not s.done]
            if not running:
                return {}
            ids: list[int | None] = [s.seq_id for s in running]
            ids += [None] * (B - len(ids))
            table, lengths, active = self.alloc.batch_views(ids, B)
            # lengths now include the new token; decode wants the write
            # offset (tokens already present before this step).
            write_at = lengths.copy()
            for i, s in enumerate(running):
                write_at[i] = lengths[i] - 1
            tokens = np.zeros((B,), np.int32)
            for i, s in enumerate(running):
                tokens[i] = s.tokens[-1] if s.tokens else self.tokenizer.bos_id
            slots = running + [None] * (B - len(running))
            temps, top_k, top_p, mask = self._sampling_arrays(slots, B)
            bias = self._bias_array(slots, B)
            want_lp = any(s.params.logprobs for s in running)
            chosen_lp = top_ids = top_lps = None
            t_step = time.perf_counter()
            with self.mesh_ctx():
                # split under the mesh like warmup's, or its eager helper
                # programs recompile on the first serving-window call.
                self._sample_key, sub = jax.random.split(self._sample_key)
                args = (
                    self.params,
                    jnp.asarray(tokens),
                    jnp.asarray(write_at),
                    self.cache,
                    jnp.asarray(table),
                    jnp.asarray(active),
                    sub,
                    jnp.asarray(temps),
                    jnp.asarray(top_k),
                    jnp.asarray(top_p),
                    None if mask is None else jnp.asarray(mask),
                    None if bias is None else jnp.asarray(bias),
                )
                if want_lp:
                    sampled, chosen_lp, top_ids, top_lps, self.cache = (
                        self._decode_sample_lp_jit(*args)
                    )
                    chosen_lp = np.asarray(chosen_lp)
                    top_ids = np.asarray(top_ids)
                    top_lps = np.asarray(top_lps)
                else:
                    sampled, self.cache = self._decode_sample_jit(*args)
            sampled = np.asarray(sampled)
            from .decode_loop import record_dispatch

            step_ctx = int(sum(
                int(write_at[i]) + 1 for i in range(len(running))
            ))
            record_dispatch(
                "single", rows=len(running), steps=1,
                attr=self.attr,
                attr_kw=dict(
                    q_tokens=len(running),
                    kv_read_tokens=step_ctx,
                    kv_write_tokens=len(running),
                    attn_q_ctx=step_ctx,
                    measured_s=time.perf_counter() - t_step,
                ),
            )
            obs.flight.record(
                "dispatch", op="decode_single",
                seq_ids=[s.seq_id for s in running],
            )
            out: dict[int, int] = {}
            first_exc: BaseException | None = None
            for i, s in enumerate(running):
                tok = int(sampled[i])
                dspan = s.decode_span
                if s.params.logprobs:
                    n = s.params.top_logprobs
                    s.logprob_data.append({
                        "logprob": float(chosen_lp[i]),
                        "top": [
                            (int(top_ids[i, j]), float(top_lps[i, j]))
                            for j in range(min(n, top_ids.shape[1]))
                        ],
                    })
                try:
                    self._accept_token(s, tok)
                except Exception as e:  # noqa: BLE001 - raising stream cb
                    # Isolate the disconnected client: only ITS sequence
                    # errors (same contract as _pull_oldest); the rest of
                    # the batch keeps its tokens.
                    if first_exc is None:
                        first_exc = e
                    s.done = True
                    s.finish_reason = s.finish_reason or "error"
                    self.alloc.truncate(s.seq_id, self._host_written(s))
                # _accept_token appends before the callback runs, so even
                # an errored sequence's token is in seq.tokens (and in what
                # finish() returns) — report it, matching _pull_oldest.
                out[s.seq_id] = tok
                if dspan is not None:
                    dspan.child(
                        "decode_step", t_step, time.perf_counter(), tokens=1
                    )
            get_perf_stats().record_metric("engine.decode_tokens", len(running), "tok")
            self._observe_occupancy()
            if first_exc is not None:
                raise first_exc
            return out

    def step_block(self, seq_ids: list[int] | None = None) -> dict[int, list[int]]:
        """Advance running sequences by up to ``cfg.decode_block`` tokens per
        device dispatch, keeping ``cfg.pipeline_depth`` dispatches in flight:
        the decode loop state (last token, write offset, EOS flags, PRNG key)
        lives ON DEVICE (decode_loop.decode_block_carry), so block k+1 is
        enqueued before block k's tokens are pulled and the pull RTT overlaps
        device compute. Tokens are therefore reported up to ``depth`` blocks
        after they were generated.

        Rows with a constrained-decoding mask advance one fused step per
        call instead (masks are host-computed per token); unconstrained rows
        in the same batch still pipeline. Returns {seq_id: accepted tokens}
        for sequences that advanced this call."""
        with self.lock:
            self._async_settle()
            self._mixed_gap_stamp = None  # see step(): gap continuity ends
            running = [
                s for s in self.sequences.values() if not s.done
            ] if seq_ids is None else [
                self.sequences[i] for i in seq_ids if not self.sequences[i].done
            ]
            running = running[: self.cfg.max_batch_size]
            block = self.cfg.decode_block
            # Constrained rows whose FSM fits the device-table budget ride
            # the PIPELINED block: the grammar mask is a [B, V] table
            # gather per step and the DFA state advances on device — no
            # host sync per token (SURVEY §7's hard part). One shared
            # table set per dispatch; seated fsm lanes pin the choice, and
            # rows with a different schema fall back to host stepping.
            from .constrained import JsonConstraint

            fsm_obj = None
            for sid in self._lanes:
                s = self.sequences.get(sid) if sid is not None else None
                if (
                    s is not None and not s.done
                    and isinstance(s.mask_fn, JsonConstraint)
                ):
                    fsm_obj = s.mask_fn.fsm
                    break

            def fsm_ok(s):
                nonlocal fsm_obj
                if (
                    not isinstance(s.mask_fn, JsonConstraint)
                    or s.params.logprobs
                    or self._needs_bias(s)
                    or s.mask_fn.fsm.dense_tables() is None
                ):
                    return False
                if fsm_obj is None:
                    fsm_obj = s.mask_fn.fsm
                    return True
                return s.mask_fn.fsm is fsm_obj

            # Host-stepped rows: non-FSM constrained masks need a
            # host-computed logits mask per token; logprob rows need
            # per-token device pulls the pipelined block does not surface;
            # biased rows need the bias rebuilt per token.
            def hosted(s):
                return (
                    (s.mask_fn is not None and not fsm_ok(s))
                    or s.params.logprobs
                    or self._needs_bias(s)
                )

            masked = [s for s in running if hosted(s)]
            plain = [s for s in running if not hosted(s)]
            if running and (block <= 1 or (masked and not plain)):
                return {
                    sid: [tok]
                    for sid, tok in self.step(
                        [s.seq_id for s in running]
                    ).items()
                }
            out: dict[int, list[int]] = {}
            if masked:
                # Mixed batch: constrained rows need a host-computed logits
                # mask per token, so they advance one fused step per call
                # (step() does not flush the pipeline for lane-less masked
                # rows) while the unconstrained rows pipeline underneath.
                out.update({
                    sid: [tok]
                    for sid, tok in self.step(
                        [s.seq_id for s in masked]
                    ).items()
                })
                plain = [s for s in plain if not s.done]
            B = self.cfg.max_batch_size
            # Lane sync: free lanes of finished sequences, then seat newly
            # running ones. A lane holds its sequence for its whole life, so
            # the device carry stays valid across dispatches.
            for lane, sid in enumerate(self._lanes):
                if sid is None:
                    continue
                s = self.sequences.get(sid)
                if s is None or s.done:
                    self._lanes[lane] = None
                    self._lane_of.pop(sid, None)
            override = np.zeros((B,), bool)
            ov_tok = np.zeros((B,), np.int32)
            ov_at = np.zeros((B,), np.int32)
            ov_fsm = np.zeros((B,), np.int32)  # 0 = FREE sentinel row
            for s in plain:
                if s.seq_id in self._lane_of:
                    continue
                try:
                    lane = self._lanes.index(None)
                except ValueError:
                    break  # more running sequences than lanes: they wait
                self._lanes[lane] = s.seq_id
                self._lane_of[s.seq_id] = lane
                override[lane] = True
                ov_tok[lane] = s.tokens[-1] if s.tokens else self.tokenizer.bos_id
                # Invariant at (re)seating: alloc.length == written tokens.
                ov_at[lane] = self.alloc.length(s.seq_id)
                if isinstance(s.mask_fn, JsonConstraint):
                    # Walk the DFA over what this row generated so far;
                    # +1 because device-table row 0 is the FREE sentinel.
                    fsm = s.mask_fn.fsm
                    st = fsm.dfa.start
                    for t in s.tokens:
                        if t != fsm.eos_id:
                            st = fsm.advance(st, t)
                    ov_fsm[lane] = st + 1
            # Book pages for up to one block per lane; budgets account for
            # still-in-flight dispatches so max_tokens is never overshot.
            # Seated lanes OUTSIDE the caller's seq_ids filter keep their
            # device carry but get no budget — they do not advance.
            requested = {s.seq_id for s in plain}
            alive = np.zeros((B,), bool)
            budgets = np.zeros((B,), np.int32)
            lane_seqs: list[int | None] = [None] * B
            for lane, sid in enumerate(self._lanes):
                if sid is None:
                    continue
                if sid not in requested:
                    alive[lane] = True
                    lane_seqs[lane] = sid
                    continue
                s = self.sequences[sid]
                want = min(
                    block,
                    s.params.max_tokens - len(s.tokens)
                    - self._inflight_steps.get(sid, 0),
                )
                if want <= 0:
                    # Budget fully covered by in-flight blocks: keep the
                    # lane seated, dispatch nothing for it.
                    alive[lane] = True
                    lane_seqs[lane] = sid
                    continue
                got = self.alloc.extend_upto(sid, want)
                if got == 0:
                    # Page pool dry. Before killing the row, drain the
                    # pipeline: its in-flight blocks may hold legitimately
                    # generated tokens for this sequence (discarding them
                    # would truncate the response early), and their pulls
                    # roll back other finished rows' pages — which can make
                    # this extend succeed after all.
                    while self._inflight:
                        _merge_pulls(out, self._pull_oldest())
                    # The drain may have finished sequences whose lanes were
                    # already budgeted earlier in this loop — zero them so
                    # the dispatch does not resurrect dead rows.
                    for lx, sx in enumerate(lane_seqs):
                        if sx is not None and (
                            sx not in self.sequences or self.sequences[sx].done
                        ):
                            alive[lx] = False
                            budgets[lx] = 0
                            lane_seqs[lx] = None
                    if s.done:
                        continue  # drained blocks finished it (EOS/stop)
                    got = self.alloc.extend_upto(sid, want)
                if got == 0:
                    s.done = True
                    s.finish_reason = "length"
                    obs.PREEMPTIONS.inc()
                    obs.flight.record("preemption", seq_id=sid)
                    self.alloc.truncate(sid, self._host_written(s))
                    self._free_lane(sid)
                    override[lane] = False
                    log.warning(
                        "seq %d truncated: KV page budget exhausted", sid
                    )
                    continue
                alive[lane] = True
                budgets[lane] = got
                lane_seqs[lane] = sid
            if not budgets.any():
                # Nothing to dispatch; a pull still guarantees progress.
                if self._inflight:
                    _merge_pulls(out, self._pull_oldest())
                return out
            table, _, _ = self.alloc.batch_views(lane_seqs, B)
            slots = [
                self.sequences.get(sid) if sid is not None else None
                for sid in lane_seqs
            ]
            temps, top_k, top_p, _ = self._sampling_arrays(slots, B)
            greedy = bool(np.all(temps <= 0.0))
            # A constrained row that failed to get a lane (batch full) must
            # not force FSM tables (and disable speculation) on a dispatch
            # where no SEATED row is constrained — it isn't advancing
            # anyway. Re-derive from what actually seated.
            if fsm_obj is not None and not any(
                isinstance(
                    getattr(self.sequences.get(sid), "mask_fn", None),
                    JsonConstraint,
                )
                for sid in self._lanes
                if sid is not None
            ):
                fsm_obj = None
            if self._carry is None:
                # Under mesh_ctx like every other eager helper: the zeros/
                # split programs recompile per mesh-context depth otherwise.
                with self.mesh_ctx():
                    # Fork the decode-loop PRNG stream off the admission
                    # stream so per-step sampling never reuses an
                    # admission key.
                    self._sample_key, carry_key = jax.random.split(
                        self._sample_key
                    )
                    # Distinct arrays: the donated args must be distinct
                    # buffers (donating the same one twice is an error).
                    self._carry = (
                        jnp.zeros((B,), jnp.int32),
                        jnp.zeros((B,), jnp.int32),
                        jnp.zeros((B,), bool),
                        jnp.zeros((B,), jnp.int32),  # device FSM (0=free)
                        carry_key,
                    )
            c_tok, c_at, c_eos, c_fsm, c_key = self._carry
            perf = get_perf_stats()
            t_disp = time.perf_counter()
            speculate = (
                self.cfg.speculative_k > 0 and greedy and fsm_obj is None
            )
            counts = None
            if fsm_obj is not None:
                fsm_mask_d, fsm_dest_d = self._fsm_device_tables(fsm_obj)
            else:
                fsm_mask_d = fsm_dest_d = None
            if speculate:
                # Host history for newly seated lanes, prepared OUTSIDE the
                # dispatch timing block. Drafting is advisory (a stale row
                # only costs draft quality), and the common all-False
                # override case reuses one cached device-resident zeros
                # array instead of transferring B x H zeros per block.
                H = self.cfg.max_pages_per_seq * self.cfg.page_size
                if self._hist is None:
                    self._hist = jnp.zeros((B, H), jnp.int32)
                if override.any():
                    ov_hist = np.zeros((B, H), np.int32)
                    for lane, flag in enumerate(override):
                        if not flag:
                            continue
                        s = self.sequences.get(self._lanes[lane])
                        if s is None:
                            continue
                        ids_h = (s.prompt_ids + s.tokens)[:H]
                        ov_hist[lane, : len(ids_h)] = ids_h
                    ov_hist_dev = jnp.asarray(ov_hist)
                else:
                    if self._ov_hist_zeros is None:
                        self._ov_hist_zeros = jnp.zeros((B, H), jnp.int32)
                    ov_hist_dev = self._ov_hist_zeros
            dev_out: list = []
            with annotate("engine.decode_block"), \
                    device_timer("decode_block", dev_out), self.mesh_ctx():
                if speculate:
                    toks, counts, self.cache, carry = (
                        self._spec_pipeline_jit(
                            self.params,
                            c_tok, c_at, c_eos, self._hist,
                            jnp.asarray(override),
                            jnp.asarray(ov_tok),
                            jnp.asarray(ov_at),
                            ov_hist_dev,
                            jnp.asarray(alive),
                            jnp.asarray(budgets),
                            self.cache,
                            jnp.asarray(table),
                        )
                    )
                    n_tok, n_at, n_eos, self._hist = carry
                    self._carry = (n_tok, n_at, n_eos, c_fsm, c_key)
                else:
                    toks, self.cache, carry = self._decode_pipeline_jit(
                        self.params,
                        c_tok, c_at, c_eos, c_key,
                        jnp.asarray(override),
                        jnp.asarray(ov_tok),
                        jnp.asarray(ov_at),
                        jnp.asarray(alive),
                        jnp.asarray(budgets),
                        self.cache,
                        jnp.asarray(table),
                        jnp.asarray(temps),
                        jnp.asarray(top_k),
                        jnp.asarray(top_p),
                        greedy=greedy,
                        fsm_mask=fsm_mask_d,
                        fsm_dest=fsm_dest_d,
                        carry_fsm=c_fsm,
                        ov_fsm=jnp.asarray(ov_fsm),
                    )
                    n_tok, n_at, n_eos, n_fsm, n_key = carry
                    self._carry = (n_tok, n_at, n_eos, n_fsm, n_key)
                dev_out.append(toks)
            perf.record_metric(
                "engine.block_dispatch", (time.perf_counter() - t_disp) * 1e3,
                "ms",
            )
            if speculate:
                # Observability for the speculative path (also the signal
                # tests use to prove speculation actually engaged).
                perf.record_metric("engine.spec_blocks", 1, "blk")
            from .decode_loop import record_dispatch

            # Attribution: each budgeted lane writes `b` tokens, step j
            # attending start+j+1 positions (exact causal sum); the scan
            # streams the weights once per SCAN STEP regardless of how
            # few lanes carry budget (inactive lanes ride the stream).
            attr_q = attr_read = 0
            for lane, sid in enumerate(lane_seqs):
                b = int(budgets[lane])
                if sid is None or b == 0:
                    continue
                s0 = max(0, self.alloc.length(sid) - b)
                attr_q += b
                attr_read += b * s0 + b * (b + 1) // 2
            record_dispatch(
                "spec" if speculate else "block",
                rows=int(np.count_nonzero(budgets)),
                steps=int(budgets.max()),
                attr=self.attr,
                attr_kw=dict(
                    weight_streams=(
                        self._spec_steps if speculate
                        else self.cfg.decode_block
                    ),
                    q_tokens=attr_q,
                    kv_read_tokens=attr_read,
                    kv_write_tokens=attr_q,
                    attn_q_ctx=attr_read,
                ),
            )
            obs.flight.record(
                "dispatch", op="spec" if speculate else "decode_block",
                seq_ids=[sid for sid, b in zip(lane_seqs, budgets)
                         if sid is not None and b],
                steps=int(budgets.max()),
            )
            self._inflight.append((toks, lane_seqs, budgets, counts, t_disp))
            for sid, b in zip(lane_seqs, budgets):
                if sid is not None and b:
                    self._inflight_steps[sid] = (
                        self._inflight_steps.get(sid, 0) + int(b)
                    )
            while len(self._inflight) > self.cfg.pipeline_depth:
                _merge_pulls(out, self._pull_oldest())
            return out

    # -- hierarchical KV tier (serving/offload) ------------------------------
    def _spill_page(self, page: int, chain_tokens: list[int]) -> None:
        """PageAllocator eviction hook: enqueue a device->host copy of the
        page being dropped, keyed by its token chain, so the content
        survives in the host pool. Runs under the engine lock (eviction
        happens inside allocate/extend); the gather is dispatched here —
        ordered before any later write to the recycled page — and pulled
        at the next flush point."""
        if self.offload is not None:
            self.offload.spill(self.cache, [(page, chain_tokens)])

    def offload_flush(self) -> int:
        """Pull pending device->host page copies into the host pool (the
        double buffer's drain side). Cheap no-op when nothing is pending;
        the scheduler calls this on idle ticks and admission calls it
        before matching."""
        if self.offload is None:
            return 0
        with self.lock:
            return self.offload.flush()

    def prefix_digests(self, cap: int | None = None) -> list[str]:
        """Compact prefix digest of this replica's cached state for the
        fleet registry: hex chain keys (offload/pool.chain_key_hex) of
        every HBM-trie-resident page chain plus every host-pool page. The
        router scores a prompt's longest-cached-prefix affinity against
        this set and indexes it into the fleet page directory; ``cap``
        bounds the advertisement (explicit arg > env
        ``OPSAGENT_FLEET_DIGEST_CAP`` > 4096; newest content wins by
        iteration order — over-cap replicas just under-advertise, which
        only costs affinity/fault-in hits, never correctness). Sets
        ``digests_truncated()`` so the registry snapshot can surface
        replicas whose advertisement is clipped."""
        from .offload.pool import chain_key_hex

        if cap is None:
            try:
                cap = int(os.environ.get("OPSAGENT_FLEET_DIGEST_CAP", ""))
            except ValueError:
                cap = 0
            if cap <= 0:
                cap = 4096
        with self.lock:
            keys = [chain_key_hex(c) for c in self.alloc.trie_chains()]
        if self.offload is not None:
            keys.extend(self.offload.pool.digests())
        self._digests_truncated = len(keys) > cap
        if self._digests_truncated:
            keys = keys[-cap:]
        return keys

    def digests_truncated(self) -> bool:
        """Whether the last ``prefix_digests`` advertisement was clipped
        by the digest cap (registry snapshot: ``digest_truncated``)."""
        return self._digests_truncated

    def fault_in_prefix(
        self, prompt_ids: list[int], request_id: str = ""
    ) -> int:
        """Fleet-global KV fault-in (tier 3): when the usable prefix of
        ``prompt_ids`` misses the HBM trie AND the host pool, ask the
        fleet page directory who owns the missing chain and fetch it
        peer-to-peer into the host pool (fleet/pagestore.py), so the
        admission's ordinary host restore lands it. Probes under the
        engine lock (cheap reads), fetches OUTSIDE it. Returns pages
        landed; 0 on any miss/failure — never raises into admission.
        ``request_id`` tags the fault-in flight events with the journey
        this admission serves (fleet timeline stitching)."""
        if self.pagestore is None or self.offload is None:
            return 0
        try:
            usable = prompt_ids[: len(prompt_ids) - 1]
            total = len(usable) // self.cfg.page_size
            if total == 0:
                return 0
            with self.lock:
                self.offload.flush()
                matched = len(self.alloc.match_prefix(usable))
            covered = self.offload.pool.coverage(
                usable, start_page=matched
            )
            if matched + covered >= total:
                return 0  # local tiers cover it — no fetch
            return self.pagestore.fault_in(
                usable, start_page=matched + covered,
                request_id=request_id,
            )
        except Exception:  # noqa: BLE001 - NEVER raises into admission
            log.exception("page fault-in probe failed; re-prefilling")
            return 0

    def replicate_chain(self, token_ids: list[int]) -> int:
        """Non-destructive export support: copy this token chain's
        trie-resident pages into the host pool WITHOUT evicting them
        (spill is a pure copy; eviction is a separate step), so a peer
        fault-in can pack the chain while local sessions keep decoding
        on it. Contrast ``park_chain``, which frees the HBM pages.
        Returns pages newly copied (0 = already pool-resident or not
        trie-resident)."""
        if self.offload is None:
            return 0
        from .offload.pool import chain_key_hex

        with self.lock:
            self.offload.flush()
            pages = self.alloc.match_prefix(token_ids)
            if not pages:
                return 0
            P = self.cfg.page_size
            have = set(self.offload.pool.digests())
            chains = [
                (pg, token_ids[: (i + 1) * P])
                for i, pg in enumerate(pages)
                if chain_key_hex(token_ids[: (i + 1) * P]) not in have
            ]
            if not chains:
                return 0
            self.offload.spill(self.cache, chains, trigger="replicate")
            return self.offload.flush()

    def park_chain(self, token_ids: list[int]) -> int:
        """Tool-time parking: free the HBM pages holding this token
        history's KV (the session's trie-resident state) after copying
        them to the host pool. Called while the session's ReAct loop
        blocks on tool execution — the multi-second window where the
        pages only deny admission to queued prompts. Returns tokens
        parked (0 when offload is off or nothing was evictable)."""
        if self.offload is None:
            return 0
        with self.lock:
            pages = self.alloc.match_prefix(token_ids)
            if not pages:
                return 0
            n = self.alloc.evict_chain(pages)
            if n:
                obs.OFFLOAD_PARKS.inc(trigger="tool")
                obs.flight.record(
                    "park", trigger="tool", pages=n,
                    tokens=n * self.cfg.page_size,
                )
                self._observe_occupancy()
            return n * self.cfg.page_size

    def park_sequence(self, seq_id: int) -> "Sequence | None":
        """Pressure parking: offload a LIVE sequence's written pages to
        the host pool and free ALL its HBM state, returning the tokens it
        generated so far. The caller (scheduler) re-queues the request
        with the salvage folded into its prompt — exactly the
        slice-restart salvage flow — and the re-admission restores the
        pages from the host pool instead of re-prefilling. Returns the
        parked Sequence (host-side state: tokens, logprob_data), or None
        (nothing parked) when the sequence turned out to be finished by
        the time the pipeline settled — the caller reaps it normally."""
        if self.offload is None:
            raise RuntimeError("park_sequence requires the offload tier")
        with self.lock:
            self._async_settle()
            # Settle pipelined decode first: in-flight blocks may still
            # append tokens to this sequence (and their pulls roll page
            # bookings back to written content). Stream-callback raises
            # belong to their own (now-errored) rows — keep draining.
            while self._inflight or self._lane_of:
                try:
                    self._flush_and_invalidate()
                except Exception:  # noqa: BLE001
                    log.exception(
                        "stream callback raised while settling pipelined "
                        "state for parking; row isolated"
                    )
            seq = self.sequences.get(seq_id)
            if seq is None or seq.done:
                return None  # finished mid-drain: reap, don't park
            seq = self.sequences.pop(seq_id)
            written = seq.prompt_ids + seq.tokens[:-1]
            P = self.cfg.page_size
            pages = self.alloc.pages_of(seq_id)
            chains = [
                (pages[i], written[: (i + 1) * P])
                for i in range(min(len(written) // P, len(pages)))
            ]
            if chains:
                self.offload.spill(self.cache, chains, trigger="pressure")
            # Plain free (no trie donation): the content now lives in the
            # host tier; keeping an HBM copy would defeat the parking.
            self.alloc.free(seq_id)
            obs.OFFLOAD_PARKS.inc(trigger="pressure")
            obs.flight.record(
                "park", trigger="pressure", seq_id=seq_id,
                pages=len(chains), tokens=len(chains) * P,
                generated=len(seq.tokens),
            )
            if seq.decode_span is not None:
                obs.attribution.record_goodput(
                    seq.decode_span.duration_s(), "decode_active"
                )
                seq.decode_span.close(
                    tokens=len(seq.tokens), finish_reason="parked"
                )
                seq.decode_span = None
            self._observe_occupancy()
            return seq

    def abort_request(self, seq_id: int) -> None:
        """Abandon a sequence that is still in the prefilling state (e.g.
        scheduler shutdown): free its pages and drop its host state. No-op
        for ids the engine no longer tracks."""
        with self.lock:
            if self._prefilling.pop(seq_id, None) is None:
                return
            self.sequences.pop(seq_id, None)
            self.alloc.free(seq_id)

    def drain(self) -> dict[int, list[int]]:
        """Pull every in-flight decode dispatch and fold the tokens into
        host state. Call before reading final sequence state outside the
        step loop (benchmarks, shutdown); the step loop itself drains
        incrementally."""
        with self.lock:
            out: dict[int, list[int]] = {}
            if self._async.pending:
                # Decode tokens fold into this drain's result; prefill
                # completions stay buffered for async_take_results (the
                # scheduler must still learn about them).
                _merge_pulls(out, self._async.drain_decode())
            while self._inflight:
                _merge_pulls(out, self._pull_oldest())
            return out

    def finish(self, seq_id: int) -> list[int]:
        """Release resources; returns the generated tokens. Full pages are
        donated to the prefix trie keyed by their exact token history (the
        cache holds prompt + generated[:-1]: the last sampled token is never
        written back by a decode step)."""
        with self.lock:
            seq = self.sequences.pop(seq_id)
            self._ffwd_noted.discard(seq_id)
            self.alloc.free(seq_id, tokens=seq.prompt_ids + seq.tokens[:-1])
            obs.flight.record(
                "finish", seq_id=seq_id, tokens=len(seq.tokens),
                finish_reason=seq.finish_reason,
                request_id=obs.flight.request_id_of(seq.trace),
            )
            if seq.decode_span is not None:
                # Aborted/errored sequences can reach finish() with the
                # decode span still open.
                obs.attribution.record_goodput(
                    seq.decode_span.duration_s(), "decode_active"
                )
                seq.decode_span.close(
                    tokens=len(seq.tokens), finish_reason=seq.finish_reason
                )
                seq.decode_span = None
            self._observe_occupancy()
            return seq.tokens

    # -- convenience (tests / bench) ----------------------------------------
    def generate(
        self,
        prompts: list[list[int]],
        sampling: SamplingParams | None = None,
    ) -> list[list[int]]:
        """Synchronous batch generation with continuous decode stepping."""
        ids = [self.add_request(p, sampling) for p in prompts]
        pending = {i for i in ids if not self.sequences[i].done}
        while pending:
            self.step_block(sorted(pending))
            pending = {i for i in pending if not self.sequences[i].done}
        return [self.finish(i) for i in ids]
