"""Deterministic fault injection: the chaos half of failure containment.

Named fault points sit on the real failure seams of the serving stack —
router replica calls, KV transfer payloads, fleet heartbeats, scheduler
ticks, agent tool exec — and fire according to a spec that selects by
**hit count**, never by wall clock or unseeded randomness, so the same
spec against the same workload produces the same flight-event sequence
(chaos tests are reproducible, not flaky).

Spec grammar (``OPSAGENT_FAULTS`` env var, or ``configure()``):

    spec    := clause (";" clause)*
    clause  := point "@" selector
    point   := dotted fault-point name (see the table below)
    selector:= N          fire on the Nth hit of the point (1-based)
             | N..M       fire on hits N through M inclusive
             | N+         fire on every hit from N on
             | every:K    fire on every Kth hit
             | p:P:SEED   fire with probability P from a random.Random(SEED)
                          stream advanced once per hit (deterministic given
                          the per-point hit order)

Example: ``fleet.stream_disconnect@5;client.heartbeat_drop@2..4``.

Wired fault points:

    fleet.connect             HttpReplica._call: connection refused
    fleet.timeout             HttpReplica._call: request timeout
    fleet.stream_disconnect   router stream pump: mid-SSE disconnect
    transfer.corrupt          KV import: one payload byte flipped
    transfer.truncate         KV import: last leaf of a record dropped
    client.heartbeat_drop     fleet membership: heartbeat silently dropped
    sched.step_fault          scheduler tick raises (forced engine restart
                              after the loop's failure threshold)
    sched.out_of_pages        admission raises OutOfPages (page storm)
    tool.exec                 agent tool exec: subprocess failure
    tool.timeout              agent tool exec: subprocess timeout
    pagestore.fetch_timeout   fleet page fault-in: peer fetch times out
                              (admission degrades to local re-prefill)
    pagestore.stale_entry     fleet page fault-in: the peer answers as
                              if the chain were LRU-evicted (directory
                              row evicted, admission re-prefills)

Every firing records a ``fault_injected`` flight event and increments
``opsagent_fault_injections_total{point=...}``, so tests and the
``fleet-chaos`` bench stage can assert exactly what fired.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Any

from .. import obs
from ..utils.logger import get_logger

log = get_logger("faults")

ENV_FAULTS = "OPSAGENT_FAULTS"


class _Rule:
    """One parsed selector for one fault point."""

    def __init__(self, kind: str, a: float = 0, b: float = 0):
        self.kind = kind            # nth|range|from|every|prob
        self.a = a
        self.b = b
        self._rng: random.Random | None = None

    def matches(self, hit: int) -> bool:
        if self.kind == "nth":
            return hit == int(self.a)
        if self.kind == "range":
            return int(self.a) <= hit <= int(self.b)
        if self.kind == "from":
            return hit >= int(self.a)
        if self.kind == "every":
            k = int(self.a)
            return k > 0 and hit % k == 0
        if self.kind == "prob":
            if self._rng is None:
                self._rng = random.Random(int(self.b))
            # One draw per hit keeps the stream aligned with hit order.
            return self._rng.random() < self.a
        return False


def _parse_selector(sel: str) -> _Rule:
    sel = sel.strip()
    if sel.startswith("every:"):
        return _Rule("every", int(sel[len("every:"):]))
    if sel.startswith("p:"):
        _, p, seed = sel.split(":", 2)
        return _Rule("prob", float(p), int(seed))
    if sel.endswith("+"):
        return _Rule("from", int(sel[:-1]))
    if ".." in sel:
        lo, hi = sel.split("..", 1)
        return _Rule("range", int(lo), int(hi))
    return _Rule("nth", int(sel))


def parse_spec(spec: str) -> dict[str, list[_Rule]]:
    """Parse a spec string into {point: [rules]}; bad clauses are logged
    and skipped (a typo in an operator env must not take the server down)."""
    rules: dict[str, list[_Rule]] = {}
    for clause in spec.replace("\n", ";").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            point, sel = clause.split("@", 1)
            rules.setdefault(point.strip(), []).append(_parse_selector(sel))
        except (ValueError, IndexError):
            log.warning("ignoring malformed fault clause %r", clause)
    return rules


class FaultInjector:
    """Process-wide injector. Hit counters are per point; the spec is
    read from ``OPSAGENT_FAULTS`` lazily (bench children set the env
    before the first hit) or pinned explicitly via ``configure()``."""

    def __init__(self, spec: str | None = None):
        self._lock = threading.Lock()
        self._explicit = spec is not None
        self._rules = parse_spec(spec or "")
        self._env_loaded = spec is not None
        self._hits: dict[str, int] = {}
        self._fired: dict[str, int] = {}

    def _load_env_locked(self) -> None:
        if self._env_loaded:
            return
        self._env_loaded = True
        spec = os.environ.get(ENV_FAULTS, "")
        if spec:
            self._rules = parse_spec(spec)
            log.info("fault injection active: %s", spec)

    def configure(self, spec: str) -> None:
        """Pin a spec (tests / bench phases); counters reset."""
        with self._lock:
            self._explicit = True
            self._env_loaded = True
            self._rules = parse_spec(spec)
            self._hits.clear()
            self._fired.clear()

    def reset(self) -> None:
        """Clear counters and unpin — the env spec re-reads on next hit."""
        with self._lock:
            self._explicit = False
            self._env_loaded = False
            self._rules = {}
            self._hits.clear()
            self._fired.clear()

    def active(self) -> bool:
        with self._lock:
            self._load_env_locked()
            return bool(self._rules)

    def fire(self, point: str, **ctx: Any) -> bool:
        """Count one hit of ``point``; True when a fault should be
        injected here. Each firing is recorded (flight event + counter)."""
        with self._lock:
            self._load_env_locked()
            rules = self._rules.get(point)
            if not rules:
                return False
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            if not any(r.matches(hit) for r in rules):
                return False
            self._fired[point] = self._fired.get(point, 0) + 1
        obs.FAULT_INJECTIONS.inc(point=point)
        obs.flight.record("fault_injected", point=point, hit=hit, **ctx)
        log.warning("fault injected: %s (hit %d) %s", point, hit, ctx or "")
        return True

    def maybe_raise(
        self, point: str, exc: type[BaseException] | BaseException,
        msg: str = "", **ctx: Any,
    ) -> None:
        """``fire`` + raise: the standard wiring for a fault point whose
        failure mode is an exception."""
        if self.fire(point, **ctx):
            if isinstance(exc, BaseException):
                raise exc
            raise exc(msg or f"injected fault at {point}")

    def summary(self) -> dict[str, Any]:
        with self._lock:
            self._load_env_locked()
            return {
                "active": bool(self._rules),
                "points": sorted(self._rules),
                "hits": dict(self._hits),
                "fired": dict(self._fired),
            }


_injector = FaultInjector()


def get_injector() -> FaultInjector:
    return _injector


def configure(spec: str) -> None:
    _injector.configure(spec)


def reset() -> None:
    _injector.reset()


def active() -> bool:
    return _injector.active()


def fire(point: str, **ctx: Any) -> bool:
    return _injector.fire(point, **ctx)


def maybe_raise(
    point: str, exc: type[BaseException] | BaseException,
    msg: str = "", **ctx: Any,
) -> None:
    _injector.maybe_raise(point, exc, msg, **ctx)


def summary() -> dict[str, Any]:
    return _injector.summary()
