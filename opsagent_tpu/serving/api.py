"""OpenAI-compatible frontend for the serving engine.

Two access paths, one implementation:

- **In-process provider**: registered under the ``tpu://`` scheme with the
  agent's ChatClient (llm/client.py), so ``--model tpu://tiny-test`` routes
  the ReAct loop straight into the engine with zero HTTP hops and zero
  external API calls (BASELINE.json north_star).
- **HTTP server**: ``opsagent serve-engine`` exposes POST
  /v1/chat/completions (non-streaming and SSE streaming), GET /v1/models and
  /healthz for out-of-process clients speaking the unchanged OpenAI wire
  format.
"""

from __future__ import annotations

import asyncio
import json
import queue
import threading
import time
import uuid
from typing import Any

from .. import obs
from ..llm.client import register_provider
from ..utils.jsonrepair import parse_json
from ..utils.logger import get_logger
from . import faults
from .chat_template import apply_chat_template
from .engine import Engine, EngineConfig
from .sampler import SamplingParams
from .scheduler import Request, RequestError, Scheduler

log = get_logger("serving.api")


class _SpanFinisher:
    """Duck-types ``Trace.finish()`` for a nested fleet-hop leg span:
    the completion paths call ``owned.finish()`` in their finally
    blocks, and a leg span that never closed would extend to "now"
    forever in the assembled timeline."""

    def __init__(self, span: Any):
        self._span = span

    def finish(self) -> None:
        self._span.close()


class ServingStack:
    """Engine + scheduler + chat glue for one hosted model."""

    def __init__(self, engine: Engine, restart_tolerant: bool = True):
        # Slice-restart tolerance: the scheduler rebuilds a fresh engine
        # from the same config if the device runtime fails persistently,
        # re-admitting in-flight work (scheduler._recover). ``engine``
        # is a property so restarts are transparent to every consumer.
        # The resolved model_cfg rides along: an auto-derived (non-preset)
        # architecture must survive the rebuild, or recovery would die in
        # get_config_preset on the checkpoint-dir name.
        factory = (
            lambda cfg=engine.cfg, mc=engine.model_cfg: Engine(
                cfg, model_cfg=mc
            )
        ) if restart_tolerant else None
        self.scheduler = Scheduler(engine, engine_factory=factory)
        self.scheduler.start()
        self.model_name = engine.model_cfg.name

    @property
    def engine(self) -> Engine:
        # Direct assignment (tests wiring a fake engine without a
        # scheduler) takes precedence; otherwise track the scheduler's
        # current engine so restarts are transparent.
        override = getattr(self, "_engine_override", None)
        if override is not None:
            return override
        return self.scheduler.engine

    @engine.setter
    def engine(self, value: Engine) -> None:
        self._engine_override = value

    # -- request translation ------------------------------------------------
    def _translate(
        self, body: dict[str, Any]
    ) -> tuple[SamplingParams, list[int], Any]:
        """Body -> (sampling, prompt_ids, mask_fn); malformed client params
        (e.g. max_tokens="many") become a 400, not a retryable 500."""
        try:
            return (
                self._sampling_from(body),
                self._prompt_ids(body),
                self._constraint_from(body),
            )
        except (ValueError, TypeError, KeyError) as e:
            raise RequestError(f"invalid request: {e}", 400) from e

    def _tool_choice_constraint(self, body: dict[str, Any]):
        """OpenAI ``tool_choice`` -> constrained-decoding mask_fn forcing a
        tool_calls envelope: "required" constrains to a call of ANY listed
        tool, {"type": "function", "function": {"name": N}} to that tool
        specifically (arguments constrained to the tool's parameter schema
        when it fits the FSM compiler's subset, any-JSON otherwise). The
        structural guarantee the reference could never have — its remote
        models free-text their calls (reference pkg/workflows/swarm.go)."""
        tc = body.get("tool_choice")
        tools = body.get("tools") or []
        if tc in (None, "auto", "none"):
            return None
        names = [
            t.get("function", {}).get("name")
            for t in tools
            if isinstance(t, dict) and t.get("function", {}).get("name")
        ]
        if not names:
            raise ValueError("tool_choice requires a non-empty tools list")
        args_schema: Any = {}
        if tc == "required":
            name_schema: dict[str, Any] = {"enum": names}
        elif isinstance(tc, dict) and tc.get("type") == "function":
            want = tc.get("function", {}).get("name")
            if want not in names:
                raise ValueError(
                    f"tool_choice names unknown function {want!r}"
                )
            name_schema = {"enum": [want]}
            for t in tools:
                if (
                    isinstance(t, dict)
                    and t.get("function", {}).get("name") == want
                ):
                    args_schema = t["function"].get("parameters") or {}
        else:
            raise ValueError(f"unsupported tool_choice {tc!r}")
        from .constrained import json_constraint

        def envelope(args: Any) -> dict[str, Any]:
            return {
                "type": "object",
                "properties": {
                    "tool_calls": {
                        "type": "array",
                        "minItems": 1,
                        "items": {
                            "type": "object",
                            "properties": {
                                "function": {
                                    "type": "object",
                                    "properties": {
                                        "name": name_schema,
                                        "arguments": args,
                                    },
                                },
                            },
                        },
                    },
                },
            }

        # depth=8: the envelope itself consumes 4 nesting levels (object ->
        # array -> item -> function), so the default depth would compile
        # "arguments" at depth 0 — primitives only, '{' forbidden.
        try:
            return json_constraint(
                self.engine.tokenizer, envelope(args_schema), depth=8
            )
        except ValueError:
            # Tool parameter schema outside the FSM compiler's subset:
            # still force the envelope + name, arguments as any JSON.
            return json_constraint(
                self.engine.tokenizer, envelope({}), depth=8
            )

    def _constraint_from(self, body: dict[str, Any]):
        """OpenAI ``response_format`` -> constrained-decoding mask_fn.
        ``json_object`` constrains to any JSON value; ``json_schema`` to the
        given schema (on-device FSM masking — the engine-side replacement
        for the reference's JSON-repair ladder, pkg/utils/json.go:16)."""
        rf = body.get("response_format")
        tc_mask = self._tool_choice_constraint(body)
        if rf and tc_mask is not None:
            raise ValueError(
                "response_format and a forcing tool_choice cannot be "
                "combined (one constrained-decoding grammar per request)"
            )
        if not rf:
            return tc_mask
        if not isinstance(rf, dict):
            raise ValueError(f"response_format must be an object, got {rf!r}")
        from .constrained import json_constraint

        kind = rf.get("type")
        if kind == "json_object":
            return json_constraint(self.engine.tokenizer, None)
        if kind == "json_schema":
            spec = rf.get("json_schema") or {}
            if not isinstance(spec, dict):
                raise ValueError("response_format.json_schema must be an object")
            if "schema" in spec:
                schema = spec["schema"]
            elif any(k in spec for k in ("type", "properties", "enum", "items")):
                schema = spec  # schema passed bare, not nested under "schema"
            else:
                raise ValueError(
                    "response_format.json_schema carries no schema "
                    '(expected a "schema" member or an inline JSON schema)'
                )
            if not isinstance(schema, dict):
                raise ValueError("json_schema.schema must be an object")
            return json_constraint(self.engine.tokenizer, schema or None)
        raise ValueError(f"unsupported response_format type {kind!r}")

    def _sampling_from(self, body: dict[str, Any]) -> SamplingParams:
        logprobs = bool(body.get("logprobs", False))
        top_lp = int(body.get("top_logprobs", 0) or 0)
        if top_lp and not logprobs:
            raise ValueError("top_logprobs requires logprobs: true")
        if not 0 <= top_lp <= 20:
            raise ValueError("top_logprobs must be in 0..20")
        lb_raw = body.get("logit_bias") or {}
        if not isinstance(lb_raw, dict):
            raise ValueError("logit_bias must be an object of id -> bias")
        logit_bias = []
        for k, v in lb_raw.items():
            b = float(v)
            if not -100.0 <= b <= 100.0:
                raise ValueError("logit_bias values must be in -100..100")
            logit_bias.append((int(k), b))
        pres = float(body.get("presence_penalty", 0.0) or 0.0)
        freq = float(body.get("frequency_penalty", 0.0) or 0.0)
        if not -2.0 <= pres <= 2.0 or not -2.0 <= freq <= 2.0:
            raise ValueError("penalties must be in -2..2")
        return SamplingParams(
            temperature=float(body.get("temperature", 0.0) or 0.0),
            top_k=int(body.get("top_k", 0) or 0),
            top_p=float(body.get("top_p", 1.0) or 1.0),
            max_tokens=int(
                body.get("max_tokens") or self.engine.cfg.max_new_tokens_default
            ),
            stop=tuple(
                [body["stop"]] if isinstance(body.get("stop"), str)
                else body.get("stop") or []
            ),
            logprobs=logprobs,
            top_logprobs=top_lp,
            logit_bias=tuple(logit_bias),
            presence_penalty=pres,
            frequency_penalty=freq,
        )

    def _prompt_ids(self, body: dict[str, Any]) -> list[int]:
        # tool_choice "none": the model must not see the tools at all.
        tools = (
            None if body.get("tool_choice") == "none" else body.get("tools")
        )
        return apply_chat_template(
            self.engine.tokenizer,
            body.get("messages", []),
            model_family=self.model_name,
            tools=tools,
        )

    def _finalize_text(
        self, tokens: list[int], stop: tuple[str, ...], finish_reason: str = ""
    ) -> tuple[str, str]:
        """(text, finish_reason) with eos/stop-string trimming."""
        eos = self.engine.tokenizer.eos_id
        finish = finish_reason or "length"
        if tokens and tokens[-1] == eos:
            tokens = tokens[:-1]
            finish = "stop"
        text = self.engine.tokenizer.decode(tokens)
        for s in stop:
            idx = text.find(s)
            if idx >= 0:
                text = text[:idx]
                finish = "stop"
        return text, finish

    @staticmethod
    def _parse_tool_calls(text: str) -> list[dict[str, Any]] | None:
        t = text.strip()
        if '"tool_calls"' not in t:
            return None
        try:
            obj = parse_json(t)
        except ValueError:
            return None
        calls = obj.get("tool_calls") if isinstance(obj, dict) else None
        if not isinstance(calls, list) or not calls:
            return None
        out = []
        for i, c in enumerate(calls):
            fn = c.get("function", {}) if isinstance(c, dict) else {}
            args = fn.get("arguments", "")
            if not isinstance(args, str):
                args = json.dumps(args, ensure_ascii=False)
            out.append(
                {
                    "id": c.get("id") or f"call_{i}",
                    "type": "function",
                    "function": {"name": fn.get("name", ""), "arguments": args},
                }
            )
        return out

    # -- chat.completions ---------------------------------------------------
    def _request_trace(
        self, hop: dict[str, Any] | None = None,
    ) -> tuple[Any, "obs.Span | None", str]:
        """Trace context for one chat completion: nest under the caller's
        current span when one is active (the in-process tpu:// path — the
        ReAct loop's ``llm_turn`` span), otherwise root a NEW trace whose
        request ID doubles as the OpenAI completion id, so
        ``GET /api/trace/<completion id>`` finds it. Returns
        (owned_handle_or_None, parent_span, completion_id).

        ``hop`` is the fleet router's hop stamp ({request_id, hop,
        replica}): the incoming journey ID is ADOPTED instead of minting
        a fresh one, so trace spans, flight events, and attribution on
        every participating replica key to one ID. When this process
        already holds a trace under that ID (in-process fleet: a hedge
        probe or mid-stream failover leg landing beside the first leg),
        the new leg nests as a ``fleet_hop`` child of the existing root —
        one span tree per journey, mirroring engine-restart stitching."""
        parent = obs.current_span()
        if parent is not None:
            return None, parent, f"chatcmpl-{uuid.uuid4().hex[:24]}"
        rid = str(hop.get("request_id") or "") if hop else ""
        hop_kind = str(hop.get("hop", "")) if hop else ""
        if rid:
            existing = obs.get_store().get(rid)
            if existing is not None:
                if hop_kind == "failover":
                    existing.anomalous = True
                leg = existing.root.start_child(
                    "fleet_hop",
                    hop=hop_kind,
                    replica=str(hop.get("replica", "")),
                )
                return _SpanFinisher(leg), leg, rid
        t = obs.Trace(rid or obs.new_request_id("chatcmpl"))
        if hop:
            t.root.set(
                hop=hop_kind,
                replica=str(hop.get("replica", "")),
            )
            # A failover leg IS the anomaly: tail-based retention must
            # keep this journey even on a remote replica that never saw
            # the router's local mark.
            if hop_kind == "failover":
                t.anomalous = True
        obs.get_store().add(t)
        return t, t.root, t.request_id

    @staticmethod
    def _stamp_class(parent: "obs.Span | None", body: Any) -> None:
        """Stamp the request's SLO class on its trace. First writer wins:
        the ReAct loop classifies the OUTER request; nested llm-turn
        completions inherit rather than reclassify."""
        t = getattr(parent, "trace", None)
        if t is not None and not getattr(t, "slo_class", ""):
            t.slo_class = obs.slo.classify(body)

    def chat_completion(self, body: dict[str, Any]) -> dict[str, Any]:
        hop = body.pop("fleet_hop", None) if isinstance(body, dict) \
            else None
        owned, parent, cid = self._request_trace(hop)
        self._stamp_class(parent, body)
        try:
            return self._chat_completion_traced(body, parent, cid)
        finally:
            if owned is not None:
                owned.finish()

    def _chat_completion_traced(
        self, body: dict[str, Any], parent: "obs.Span", cid: str
    ) -> dict[str, Any]:
        sampling, prompt_ids, mask_fn = self._translate(body)
        try:
            n = int(body.get("n", 1) or 1)
        except (TypeError, ValueError) as e:
            raise RequestError(f"invalid n: {e}", 400) from e
        if not 1 <= n <= 8:
            raise RequestError("n must be in 1..8", 400)
        t0 = time.time()
        # n choices = n engine requests sharing the prompt: the prefix
        # cache dedups their KV, so extra choices only pay decode. Each
        # request gets its OWN constraint instance — JsonConstraint walks
        # the DFA incrementally per sequence, so sharing one across
        # interleaved rows would cross their grammar states.
        mask_fns = [mask_fn] + [
            self._constraint_from(body) for _ in range(n - 1)
        ]
        spans = [
            parent.start_child("generate", choice=i) if parent is not None
            else None
            for i in range(n)
        ]
        reqs = [
            Request(
                list(prompt_ids), sampling, mask_fn=mask_fns[i],
                trace=spans[i],
            )
            for i in range(n)
        ]
        for r in reqs:
            self.scheduler.submit(r)
        deadline = time.time() + 600
        try:
            for r in reqs:
                if not r.done.wait(max(0.0, deadline - time.time())):
                    raise TimeoutError("generation timed out")
        finally:
            for i, s in enumerate(spans):
                if s is not None:
                    s.close(tokens=len(reqs[i].tokens))
        errs = [r for r in reqs if r.error]
        if errs:
            raise RequestError(errs[0].error, errs[0].error_status)
        with obs.span("detokenize", parent=parent):
            choices = [
                self._build_choice(i, r, sampling) for i, r in enumerate(reqs)
            ]
        total_completion = sum(len(r.tokens) for r in reqs)
        return {
            "id": cid,
            "object": "chat.completion",
            "created": int(t0),
            "model": body.get("model") or self.model_name,
            "choices": choices,
            "usage": {
                "prompt_tokens": len(prompt_ids),
                "completion_tokens": total_completion,
                "total_tokens": len(prompt_ids) + total_completion,
            },
        }

    def _build_choice(
        self, index: int, req: Request, sampling: SamplingParams
    ) -> dict[str, Any]:
        tokens = req.tokens
        text, finish = self._finalize_text(tokens, sampling.stop, req.finish_reason)
        tool_calls = self._parse_tool_calls(text)
        message: dict[str, Any] = {"role": "assistant", "content": text}
        if tool_calls:
            message = {"role": "assistant", "content": None, "tool_calls": tool_calls}
            finish = "tool_calls"
        choice: dict[str, Any] = {
            "index": index, "message": message, "finish_reason": finish,
        }
        if sampling.logprobs:
            tok = self.engine.tokenizer
            lp_toks = (
                tokens[:-1] if tokens and tokens[-1] == tok.eos_id else tokens
            )
            if finish == "stop" and sampling.stop:
                # logprobs.content must align with the (stop-truncated)
                # message content: _finalize_text cuts the text at the
                # START of the stop match, so keep only tokens whose
                # cumulative decode fits before that index. A token the
                # cut splits mid-way is dropped (conservative: logprobs
                # are a subset of content, never beyond it).
                full = tok.decode(lp_toks)
                hits = [full.find(s) for s in sampling.stop]
                hits = [h for h in hits if h >= 0]
                if hits:
                    cut = min(hits)
                    keep = 0
                    for n in range(1, len(lp_toks) + 1):
                        if len(tok.decode(lp_toks[:n])) <= cut:
                            keep = n
                        else:
                            break
                    lp_toks = lp_toks[:keep]
            choice["logprobs"] = {
                "content": [
                    {
                        # token_str, not decode([t]): decode skips special
                        # tokens (eos would render "") and mangles tokens
                        # that are half of a multi-byte character.
                        "token": tok.token_str(t),
                        "logprob": d["logprob"],
                        "top_logprobs": [
                            {"token": tok.token_str(i), "logprob": l}
                            for i, l in d["top"]
                        ],
                    }
                    for t, d in zip(lp_toks, req.logprob_data)
                ]
            }
        return choice

    def chat_completion_stream(self, body: dict[str, Any]):
        """Generator of SSE chunk dicts (sync; drive from a thread)."""
        hop = body.pop("fleet_hop", None) if isinstance(body, dict) \
            else None
        sampling, prompt_ids, mask_fn = self._translate(body)
        if sampling.logprobs:
            # Refuse rather than silently dropping the field (and paying
            # the engine's host-stepped logprob path for nothing).
            raise RequestError(
                "logprobs are not supported with stream: true", 400
            )
        try:
            n = int(body.get("n", 1) or 1)
        except (TypeError, ValueError) as e:
            raise RequestError(f"invalid n: {e}", 400) from e
        if n != 1:
            raise RequestError("n > 1 is not supported with stream", 400)
        token_q: "queue.Queue[int | None]" = queue.Queue()
        owned, parent, cid = self._request_trace(hop)
        self._stamp_class(parent, body)
        gen_span = (
            parent.start_child("generate", stream=True)
            if parent is not None else None
        )
        req = Request(
            prompt_ids, sampling, mask_fn=mask_fn,
            on_token=lambda t: token_q.put(t), trace=gen_span,
        )
        self.scheduler.submit(req)
        created = int(time.time())
        model = body.get("model") or self.model_name
        eos = self.engine.tokenizer.eos_id
        sent: list[int] = []

        def chunk(delta: dict[str, Any], finish: str | None = None):
            return {
                "id": cid,
                "object": "chat.completion.chunk",
                "created": created,
                "model": model,
                "choices": [
                    {"index": 0, "delta": delta, "finish_reason": finish}
                ],
            }

        try:
            yield from self._stream_events(
                req, token_q, chunk, sampling, eos, sent
            )
        finally:
            # Close the trace no matter how the stream ends (client
            # disconnect raises GeneratorExit here): the span tree stays
            # retrievable at /api/trace/{cid} with whatever phases ran.
            if gen_span is not None:
                gen_span.close(tokens=len(sent))
            if owned is not None:
                owned.finish()

    def _stream_events(self, req, token_q, chunk, sampling, eos, sent):
        watchdog = threading.Thread(
            target=lambda: (req.done.wait(600), token_q.put(None)), daemon=True
        )
        watchdog.start()
        # Hold the first SSE chunk until the admission outcome is known:
        # admission failures (prompt too long, engine saturated) must surface
        # as an HTTP error status, not a 200 followed by an in-stream error.
        first_tok = token_q.get()
        if first_tok is None and req.error:
            raise RequestError(req.error, req.error_status)
        yield chunk({"role": "assistant", "content": ""})

        def _tokens():
            t = first_tok
            while t is not None:
                yield t
                t = token_q.get()
        # Incremental detokenization with a SLIDING window (vLLM-style):
        # decode only tokens[prefix_off:] and diff against the same window's
        # previous decode, so per-token cost is O(window), not O(total).
        # Withhold a trailing "�" (incomplete multi-byte char) and hold back
        # max_stop-1 chars so a stop string straddling a chunk boundary is
        # still caught before emission.
        decode = self.engine.tokenizer.decode
        max_stop = max((len(s) for s in sampling.stop), default=0)
        prefix_off = 0   # window start
        read_off = 0     # tokens already diffed within the window
        pending = ""     # decoded but unemitted (stop-string holdback)
        stopped = False
        for tok in _tokens():
            if tok == eos or stopped:
                continue
            sent.append(tok)
            prefix_text = decode(sent[prefix_off:read_off])
            window_text = decode(sent[prefix_off:])
            if window_text.endswith("�"):
                continue  # incomplete multi-byte tail; wait for more tokens
            # Both decodes start at prefix_off, so context-dependent effects
            # at the window start (sentencepiece leading-space stripping)
            # cancel in the diff and the windows telescope correctly. Guard
            # against decoders whose cleanup makes prefix_text not a literal
            # prefix of window_text by cutting at the common prefix instead
            # of blindly at len(prefix_text).
            cut = len(prefix_text)
            if window_text[:cut] != prefix_text:
                cut = 0
                for a, b in zip(prefix_text, window_text):
                    if a != b:
                        break
                    cut += 1
            delta = window_text[cut:]
            prefix_off, read_off = read_off, len(sent)
            if not delta:
                continue
            pending += delta
            for s in sampling.stop:
                idx = pending.find(s)
                if idx >= 0:
                    pending = pending[:idx]
                    stopped = True
                    break
            if stopped:
                emit, pending = pending, ""
            elif max_stop > 1:
                emit, pending = pending[: -(max_stop - 1)], pending[-(max_stop - 1):]
            else:
                emit, pending = pending, ""
            if emit:
                yield chunk({"content": emit})
        if req.error:
            yield {"error": {"message": req.error}}
            return
        if not stopped and pending:
            yield chunk({"content": pending})
        finish = "stop" if stopped else (req.finish_reason or "length")
        yield chunk({}, finish=finish)

    # -- hierarchical KV tier -----------------------------------------------
    def park(self, messages: list[dict[str, Any]], tools: Any = None) -> int:
        """Tool-time parking (hierarchical KV tier): tokenize the session
        history exactly the way admission would and park its KV chain —
        copy the trie-resident pages to the host pool, free the HBM. The
        agent loop calls this when it enters tool execution: for the
        seconds a ``kubectl``/``trivy``/``python`` subprocess runs, the
        session's pages would otherwise only deny admission to queued
        prompts. The next turn's admission restores the chain with a page
        copy instead of re-prefilling it. Returns tokens parked (0 when
        the offload tier is off — the call is always safe)."""
        eng = self.engine
        if getattr(eng, "offload", None) is None:
            return 0
        try:
            ids = apply_chat_template(
                eng.tokenizer, messages or [],
                model_family=self.model_name, tools=tools,
            )
        except Exception:  # noqa: BLE001 - parking is best-effort
            return 0
        return eng.park_chain(ids)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        self.scheduler.stop()


# -- in-process tpu:// provider ---------------------------------------------
_stacks: dict[str, ServingStack] = {}
_stacks_lock = threading.Lock()


def install_stack(name: str, stack: ServingStack) -> None:
    """Register an engine under a tpu:// model name (tests, co-hosting)."""
    with _stacks_lock:
        _stacks[name] = stack


def uninstall_stack(name: str) -> None:
    """Remove a registered stack (the caller closes it)."""
    with _stacks_lock:
        _stacks.pop(name, None)


def installed_stack_max_position(name: str) -> int | None:
    """Context window of an ALREADY-installed stack, or None.

    Unlike get_stack this never constructs an engine: it exists so the
    agent-side token constrictor (llm/tokens.py) can budget against the
    exact window the engine enforces at admission (engine.add_request
    rejects prompts >= max_position) for stacks installed under arbitrary
    names like tpu://real or tpu://tiny-agent, without triggering a
    device-resident engine build on a lookup."""
    with _stacks_lock:
        stack = _stacks.get(name)
        if stack is None:
            # Case-insensitive rescue: tokens.py lowercases model names,
            # but install_stack keeps the caller's case.
            low = name.lower()
            stack = next(
                (s for k, s in _stacks.items() if k.lower() == low), None
            )
    if stack is None:
        return None
    return int(stack.engine.model_cfg.max_position)


def park_session(model: str, messages: list[dict[str, Any]],
                 tools: Any = None) -> int:
    """Tool-exec signal from the agent loop: park the session's KV to the
    host tier while the tool subprocess runs (see ServingStack.park).
    ``model`` may carry the tpu:// scheme. Looks up the ALREADY-installed
    stack only — never constructs an engine — and never raises: parking
    is an optimization, the loop must survive its absence."""
    name = model.split("://", 1)[-1]
    with _stacks_lock:
        stack = _stacks.get(name)
        if stack is None:
            low = name.lower()
            stack = next(
                (s for k, s in _stacks.items() if k.lower() == low), None
            )
    if stack is None:
        return 0
    try:
        return stack.park(messages, tools=tools)
    except Exception:  # noqa: BLE001
        log.exception("tool-time parking failed (ignored)")
        return 0


def get_stack(name: str) -> ServingStack:
    # Engine construction happens under the lock: two racing first requests
    # must not each build a device-resident engine (the loser would leak
    # device memory and a scheduler thread).
    with _stacks_lock:
        if name not in _stacks:
            log.info("creating in-process engine for tpu://%s", name)
            _stacks[name] = ServingStack(Engine(EngineConfig(model=name)))
        return _stacks[name]


def _tpu_provider_factory(target: str):
    from ..llm.client import LLMError

    def provider(body: dict[str, Any]) -> dict[str, Any]:
        stack = get_stack(target or body.get("model", ""))
        try:
            return stack.chat_completion(body)
        except Exception as e:  # noqa: BLE001 - agent loop handles LLMError
            raise LLMError(f"tpu engine error: {e}") from e

    return provider


register_provider("tpu", _tpu_provider_factory)


# -- HTTP server -------------------------------------------------------------
def build_engine_app(stack: ServingStack, membership=None):
    """``membership`` is the replica's fleet membership (serve-engine
    --join-fleet; serving/fleet/client.py): it feeds the /healthz
    ``fleet`` block and the /fleet/drain notification state."""
    from aiohttp import web

    async def models(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": stack.model_name,
                        "object": "model",
                        "owned_by": "opsagent-tpu",
                    }
                ],
            }
        )

    async def healthz(request: web.Request) -> web.Response:
        eng = stack.engine
        sched = getattr(stack, "scheduler", None)
        body = {
            "status": "ok",
            "model": stack.model_name,
            "free_pages": eng.alloc.free_pages,
            "running": len(eng.sequences),
            # Scheduler queue depth: the fleet router's spill-over input.
            "queued": len(getattr(sched, "_waiting", ()))
            + (sched._queue.qsize() if hasattr(sched, "_queue") else 0),
            "prefilling": len(getattr(sched, "_prefilling", ())),
            "prefix_hit_tokens": eng.alloc.hit_tokens,
            "prefix_miss_tokens": eng.alloc.miss_tokens,
            "prefix_evictions": eng.alloc.evictions,
            # RESOLVED execution modes (attn impl after every fallback
            # gate, weight + KV quant): fleet snapshots and sweep readers
            # self-describe instead of inferring backend from env.
            "impl": eng.impl_info(),
        }
        if getattr(eng, "init_stats", None):
            # Cold-start provenance: how long weights + warmup took, and
            # whether this engine came up fresh or from a snapshot.
            body["init"] = dict(eng.init_stats)
        if getattr(eng, "offload", None) is not None:
            body["host_pool"] = eng.offload.stats()
        if getattr(eng.cfg, "async_depth", 1) > 1:
            body["async"] = {
                "depth": eng.cfg.async_depth,
                "inflight": eng.async_pending(),
            }
        if membership is not None:
            body["fleet"] = membership.healthz_block()
        if faults.active():
            # Chaos visibility: which fault points are armed and what has
            # fired — so an operator can tell injected pain from real pain.
            body["faults"] = faults.summary()
        return web.json_response(body)

    async def completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        if not body.get("messages"):
            return web.json_response(
                {"error": {"message": "messages is required"}}, status=400
            )
        # Fleet hop annotation: the router stamps the journey both in
        # the body (primary carrier) and as X-Fleet-* headers; accept
        # the headers so a front proxy that strips unknown body fields
        # still propagates the journey ID to this replica's spans.
        if "fleet_hop" not in body:
            hdr_rid = request.headers.get("X-Fleet-Request-Id")
            if hdr_rid:
                body["fleet_hop"] = {
                    "request_id": hdr_rid,
                    "hop": request.headers.get("X-Fleet-Hop", ""),
                    "replica": request.headers.get(
                        "X-Fleet-Replica", ""
                    ),
                }
        loop = asyncio.get_running_loop()
        if body.get("stream"):
            gen = stack.chat_completion_stream(body)
            # Pull the first chunk BEFORE preparing the stream: request-
            # translation errors (bad sampling params, prompt too long)
            # surface as a proper JSON error status, not a dead connection.
            try:
                first = await loop.run_in_executor(None, lambda: next(gen, None))
            except Exception as e:  # noqa: BLE001
                status = e.status if isinstance(e, RequestError) else 500
                return web.json_response(
                    {"error": {"message": str(e), "type": type(e).__name__}},
                    status=status,
                )
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                }
            )
            await resp.prepare(request)
            chunk = first
            try:
                while chunk is not None:
                    await resp.write(
                        b"data: " + json.dumps(chunk).encode("utf-8") + b"\n\n"
                    )
                    chunk = await loop.run_in_executor(
                        None, lambda: next(gen, None)
                    )
            except Exception as e:  # noqa: BLE001 - headers already sent
                log.exception("stream failed mid-flight")
                err = {"error": {"message": str(e), "type": type(e).__name__}}
                await resp.write(
                    b"data: " + json.dumps(err).encode("utf-8") + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        try:
            out = await loop.run_in_executor(None, stack.chat_completion, body)
        except Exception as e:  # noqa: BLE001 - OpenAI-style error envelope
            status = e.status if isinstance(e, RequestError) else 500
            return web.json_response(
                {"error": {"message": str(e), "type": type(e).__name__}},
                status=status,
            )
        return web.json_response(out)

    async def profile_start(request: web.Request) -> web.Response:
        # On-demand jax.profiler capture around live traffic: POST (body
        # ignored), then hit /v1/profile/stop and open the configured
        # --profile-dir in TensorBoard. The device-side complement to
        # GET /api/perf/stats' host timers (reference only has the
        # latter: pkg/api/router.go:104).
        from ..utils.profiling import profile_dir

        # The trace destination is operator-configured only (--profile-dir
        # / $OPSAGENT_PROFILE_DIR): a network client must not get an
        # arbitrary-filesystem-write primitive out of the serving port.
        logdir = profile_dir()
        if not logdir:
            return web.json_response(
                {"error": {"message": "profiling not enabled: start the "
                                      "server with --profile-dir"}},
                status=403,
            )
        import jax

        try:
            jax.profiler.start_trace(logdir)
        except Exception as e:  # noqa: BLE001 - already tracing / bad dir
            return web.json_response(
                {"error": {"message": str(e)}}, status=409
            )
        return web.json_response({"status": "tracing", "logdir": logdir})

    async def profile_stop(request: web.Request) -> web.Response:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - not tracing / write failure
            return web.json_response(
                {"error": {"message": str(e)}}, status=409
            )
        return web.json_response({"status": "stopped"})

    async def metrics(request: web.Request) -> web.Response:
        # Freshen the engine gauges at scrape time: an idle engine's last
        # step may be minutes old, but page residency (held sessions,
        # prefix-cache content) changes meanwhile.
        eng = stack.engine
        try:
            with eng.lock:
                eng._observe_occupancy()
        except AttributeError:
            pass  # test fakes without the full engine surface
        return web.Response(
            text=obs.metrics_text(),
            content_type="text/plain",
            charset="utf-8",
            headers={"X-Prometheus-Version": "0.0.4"},
        )

    async def trace_get(request: web.Request) -> web.Response:
        t = obs.get_trace(request.match_info["request_id"])
        if t is None:
            return web.json_response(
                {"error": {"message": "unknown request_id"}}, status=404
            )
        return web.json_response(t)

    async def timeline_get(request: web.Request) -> web.Response:
        # The request's assembled lifecycle timeline (trace spans +
        # flight events): non-overlapping phase segments, the goodput
        # split, and the attributable flight events — works mid-flight
        # and across engine restarts (obs/timeline.py).
        tl = obs.timeline.assemble(request.match_info["request_id"])
        if tl is None:
            return web.json_response(
                {"error": {"message": "unknown request_id"}}, status=404
            )
        return web.json_response(tl)

    async def memory_profile(request: web.Request) -> web.Response:
        # GET /api/debug/memory — dump the device memory profile (pprof)
        # into the operator-configured profile dir: live HBM page
        # pressure, visible without waiting for a crash. Same
        # operator-dir-only guard as /api/debug/profile: a network
        # client must not pick the write path.
        import os as _os

        from ..utils.profiling import profile_dir, save_device_memory_profile

        logdir = profile_dir()
        if not logdir:
            return web.json_response(
                {"error": {"message": "profiling not enabled: start the "
                                      "server with --profile-dir"}},
                status=403,
            )
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = _os.path.join(logdir, f"memory-{stamp}.prof")
        loop = asyncio.get_running_loop()
        try:
            _os.makedirs(logdir, exist_ok=True)
            await loop.run_in_executor(
                None, save_device_memory_profile, path
            )
        except Exception as e:  # noqa: BLE001 - surfaced to the caller
            return web.json_response(
                {"error": {"message": str(e)}}, status=500
            )
        return web.json_response({"status": "saved", "path": path})

    async def flight_get(request: web.Request) -> web.Response:
        # The flight recorder's event ring: what the engine/scheduler
        # actually did, newest last. ?n= caps the event count, ?kind=
        # filters (admission/dispatch/compile/anomaly/...).
        try:
            n = int(request.query["n"]) if "n" in request.query else None
        except ValueError:
            return web.json_response(
                {"error": {"message": "n must be an integer"}}, status=400
            )
        rec = obs.flight.get_recorder()
        return web.json_response({
            **rec.stats(),
            "events": rec.snapshot(n=n, kind=request.query.get("kind")),
        })

    async def slo_get(request: web.Request) -> web.Response:
        return web.json_response(obs.slo.evaluate())

    async def history_get(request: web.Request) -> web.Response:
        # GET /api/metrics/history?series=&since=&step= — the telemetry
        # time machine: tiered-downsample rings for every tracked series
        # (obs/history.py). The sampler thread is started by
        # run_engine_server; under a bare test app the endpoint still
        # answers (empty points) rather than 404ing.
        try:
            kwargs = obs.history.parse_query(request.query)
        except ValueError as e:
            return web.json_response(
                {"error": {"message": f"bad query: {e}"}}, status=400
            )
        return web.json_response(obs.history.query(**kwargs))

    async def profile_capture(request: web.Request) -> web.Response:
        # POST /api/debug/profile?seconds=N — capture a jax.profiler
        # device trace around LIVE traffic for N seconds (blocking in a
        # worker thread; requests keep flowing), so a TPU window can
        # attribute the full-stack tax on chip without a bench harness.
        from ..utils.profiling import timed_capture

        try:
            seconds = float(request.query.get("seconds", "5"))
        except ValueError:
            return web.json_response(
                {"error": {"message": "seconds must be a number"}},
                status=400,
            )
        loop = asyncio.get_running_loop()
        try:
            logdir = await loop.run_in_executor(
                None, timed_capture, seconds
            )
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=400
            )
        except RuntimeError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=403
            )
        except Exception as e:  # noqa: BLE001 - already tracing / bad dir
            return web.json_response(
                {"error": {"message": str(e)}}, status=409
            )
        return web.json_response({
            "status": "captured", "seconds": seconds, "logdir": logdir,
        })

    # -- fleet data plane (serving/fleet): prefix digests for affinity
    # routing, chain park/export/import for replica-to-replica session
    # migration, and the drain notification. The wire format is the host
    # pool's token-chain keying (offload/pool.py), so imported pages
    # restore through the exact local offload-hit path.
    async def fleet_digests(request: web.Request) -> web.Response:
        eng = stack.engine
        loop = asyncio.get_running_loop()
        digests = await loop.run_in_executor(None, eng.prefix_digests)
        return web.json_response({
            "model": stack.model_name,
            "page_size": int(eng.cfg.page_size),
            "digests": digests,
        })

    async def fleet_park(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            tokens = [int(t) for t in body.get("tokens") or []]
        except (json.JSONDecodeError, TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "tokens must be an int list"}},
                status=400,
            )
        eng = stack.engine
        loop = asyncio.get_running_loop()

        def _park() -> int:
            n = eng.park_chain(tokens)
            eng.offload_flush()
            return n

        parked = await loop.run_in_executor(None, _park)
        return web.json_response({"parked_tokens": parked})

    async def fleet_kv_export(request: web.Request) -> web.Response:
        # Two body forms share the wire format:
        #   {"tokens": [...], "park": true}        single chain (migration)
        #   {"chains": [{"tokens": [...], "start_page": N}, ...],
        #    "park": false}                        batched (page fault-in)
        # park=True frees the chain's HBM pages after copying (the sender
        # is handing the session off); park=False replicates trie pages
        # into the host pool non-destructively — a peer fault-in must not
        # cost this replica its own cache.
        try:
            body = await request.json()
            chains = body.get("chains")
            if chains is None:
                chains = [{
                    "tokens": body.get("tokens") or [],
                    "start_page": 0,
                }]
            reqs = [
                (
                    [int(t) for t in c.get("tokens") or []],
                    max(0, int(c.get("start_page", 0))),
                )
                for c in chains
            ]
        except (json.JSONDecodeError, TypeError, ValueError,
                AttributeError):
            return web.json_response(
                {"error": {"message": "tokens must be an int list"}},
                status=400,
            )
        batched = body.get("chains") is not None
        park = bool(body.get("park", True))
        eng = stack.engine
        if getattr(eng, "offload", None) is None:
            empty = {"pages": [], "offload": False}
            if batched:
                empty = {
                    "results": [{"pages": []} for _ in reqs],
                    "offload": False,
                }
            return web.json_response(empty)
        from .fleet.transfer import pack_entries

        loop = asyncio.get_running_loop()

        def _export():
            out = []
            for tokens, start_page in reqs:
                if park:
                    eng.park_chain(tokens)
                    eng.offload_flush()
                else:
                    eng.replicate_chain(tokens)
                out.append(pack_entries(
                    eng.offload.pool.match(tokens, start_page=start_page)
                ))
            return out

        results = await loop.run_in_executor(None, _export)
        if batched:
            return web.json_response({
                "results": [{"pages": p} for p in results],
                "page_size": int(eng.cfg.page_size),
            })
        return web.json_response({
            "pages": results[0], "page_size": int(eng.cfg.page_size),
        })

    async def fleet_kv_import(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            records = body.get("pages") or []
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON body"}}, status=400
            )
        eng = stack.engine
        if getattr(eng, "offload", None) is None:
            return web.json_response({"imported": 0, "offload": False})
        from .fleet.transfer import unpack_entries

        loop = asyncio.get_running_loop()

        def _import() -> int:
            n = 0
            for toks, tree in unpack_entries(records, eng.cache):
                if eng.offload.pool.put(toks, tree):
                    n += 1
            return n

        imported = await loop.run_in_executor(None, _import)
        return web.json_response({"imported": imported})

    async def fleet_drain(request: web.Request) -> web.Response:
        # Router-initiated graceful drain notification: flips the
        # /healthz fleet block to draining. Admission gating is the
        # router's job (it stops routing here); in-flight work finishes.
        if membership is not None:
            membership.draining = True
        return web.json_response({
            "status": "draining",
            "running": len(stack.engine.sequences),
        })

    async def fleet_promote(request: web.Request) -> web.Response:
        # Autoscaler-initiated role change (standby -> decode): keeps the
        # replica's self-reported role in sync with the router registry
        # so a later full re-register doesn't demote it back to standby.
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        role = str(body.get("role", "decode"))
        if membership is not None:
            membership.promote(role)
        return web.json_response({
            "status": "ok",
            "role": membership.role if membership is not None else role,
        })

    app = web.Application(client_max_size=256 * 1024 * 1024)
    app.router.add_post("/v1/chat/completions", completions)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/api/trace/{request_id}", trace_get)
    app.router.add_get("/api/timeline/{request_id}", timeline_get)
    app.router.add_get("/api/debug/flight", flight_get)
    app.router.add_get("/api/debug/memory", memory_profile)
    app.router.add_get("/api/slo", slo_get)
    app.router.add_get("/api/metrics/history", history_get)
    app.router.add_post("/api/debug/profile", profile_capture)
    app.router.add_post("/v1/profile/start", profile_start)
    app.router.add_post("/v1/profile/stop", profile_stop)
    app.router.add_get("/fleet/digests", fleet_digests)
    app.router.add_post("/fleet/park", fleet_park)
    app.router.add_post("/fleet/kv/export", fleet_kv_export)
    app.router.add_post("/fleet/kv/import", fleet_kv_import)
    app.router.add_post("/fleet/drain", fleet_drain)
    app.router.add_post("/fleet/promote", fleet_promote)
    return app


def run_engine_server(
    host: str = "0.0.0.0",
    port: int = 8000,
    model_name: str = "tiny-test",
    checkpoint: str = "",
    tokenizer: str = "",
    tp: int = 0,
    sp: int = 1,
    ep: int = 1,
    max_batch_size: int = 8,
    quantize: str = "",
    kv_quantize: str = "",
    speculative_k: int = 0,
    offload: bool = False,
    async_depth: int = 2,
    join_fleet: str = "",
    advertise: str = "",
    replica_id: str = "",
    replica_role: str = "decode",
    restore_snapshot: str = "",
    compile_cache_dir: str = "",
) -> None:
    import os

    from aiohttp import web

    from ..models.config import resolve_model

    if compile_cache_dir:
        os.environ["OPSAGENT_COMPILE_CACHE_DIR"] = compile_cache_dir

    if restore_snapshot:
        # Cold-start fast path: the snapshot IS the engine config —
        # model/engine flags on the command line are ignored (the
        # fingerprint check would refuse anything else anyway).
        if checkpoint or model_name != "tiny-test":
            log.warning(
                "--restore-snapshot overrides --model/--checkpoint: "
                "engine comes up exactly as snapshotted from %s",
                restore_snapshot,
            )
        engine = Engine.from_snapshot(restore_snapshot, warmup=True)
        model_name = engine.cfg.model
    else:
        model_name, model_cfg = resolve_model(model_name, checkpoint)
        if model_cfg is not None:
            log.info(
                "config.json -> %s: %dL d=%d heads=%d/%d vocab=%d",
                model_name, model_cfg.num_layers, model_cfg.hidden_size,
                model_cfg.num_heads, model_cfg.num_kv_heads,
                model_cfg.vocab_size,
            )

        cfg = EngineConfig(
            model=model_name,
            checkpoint=checkpoint,
            tokenizer=tokenizer,
            tp=tp,
            sp=sp,
            ep=ep,
            max_batch_size=max_batch_size,
            quantize=quantize,
            kv_quantize=kv_quantize,
            speculative_k=speculative_k,
            offload=offload,
            async_depth=async_depth,
            # Production server: compile everything before accepting requests
            # so no client ever pays XLA compile inside its TTFT.
            warmup=True,
        )
        engine = Engine(cfg, model_cfg=model_cfg)
    stack = ServingStack(engine)
    install_stack(model_name, stack)
    membership = None
    if join_fleet:
        from .fleet.client import FleetMembership

        membership = FleetMembership(
            stack,
            router_url=join_fleet,
            advertise_url=advertise or f"http://{host}:{port}",
            replica_id=replica_id,
            role=replica_role,
        )
        if getattr(engine, "offload", None) is not None:
            # Fleet-global KV: admission misses consult the router's
            # page directory and fault chains in peer-to-peer.
            from .fleet.pagestore import http_client

            engine.pagestore = http_client(
                join_fleet, membership.replica_id, engine
            )
    app = build_engine_app(stack, membership=membership)
    # Continuous SLO evaluation (GET /api/slo serves the same watchdog):
    # keeps the throughput rate window warm and logs breach transitions
    # into the flight ring even when nobody scrapes.
    obs.slo.get_watchdog().start()
    # Telemetry time machine: 1 Hz sampler behind /api/metrics/history
    # (tiered downsampling keeps it memory-bounded forever).
    obs.history.get_history().start()

    async def _announce(_) -> None:
        log.info("serving engine listening on %s:%d (model=%s)", host, port, model_name)
        if membership is not None:
            # Join AFTER the socket is bound: the router may probe the
            # advertised URL the moment the registration lands.
            membership.start()

    app.on_startup.append(_announce)
    web.run_app(app, host=host, port=port, print=None)
