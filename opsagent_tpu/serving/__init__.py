"""TPU serving engine: the in-tree replacement for the reference's remote
LLM providers (SURVEY.md section 7)."""
