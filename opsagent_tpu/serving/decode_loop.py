"""Device-resident multi-step decode: N (decode + sample) steps per host
dispatch, as one XLA program.

Why this exists: the engine's original hot loop pulled the sampled token to
the host after EVERY decode step. A device->host transfer costs a full
round trip (~10-70 ms on tunneled/pod setups — far more than the decode
step's own compute), so per-token pulls cap throughput at ~1/RTT regardless
of model size. Scanning ``n_steps`` decode+sample iterations inside one
``jax.jit`` amortizes the dispatch AND the single [B, n_steps] token pull
over the whole block, leaving the device busy back-to-back.

Per-row early exit happens ON DEVICE: a row goes inactive when it samples
EOS or exhausts its per-dispatch token budget. Inactive rows stop writing KV
(their page state stays exactly "prompt + accepted[:-1]") and emit pad
tokens, which the host-side bookkeeping discards. Stop-string checks remain
host-side — the host walks each row's block output token by token and
truncates the page allocation back to what it accepted.

Replaces the per-token HTTPS round trip of the reference agent loop
(reference pkg/assistants/simple.go:343,515) with its tpu-native dual: the
round trip is now per-BLOCK, not per-token.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..models import llama
from ..models.config import ModelConfig
from .sampler import NEG_INF, sample


def _record_attr(kind: str, attr, attr_kw: dict | None) -> None:
    """Forward one dispatch's composition to the goodput ledger's cost
    model (obs/attribution.py) — the per-dispatch wall-time/byte hook.
    Host float math only; never raises into the dispatch path."""
    if attr is None:
        return
    try:
        attr.dispatch(kind, **(attr_kw or {}))
    except Exception:  # noqa: BLE001 - attribution must not kill serving
        pass


def record_dispatch(
    kind: str, rows: int, steps: int, attr=None, attr_kw: dict | None = None
) -> None:
    """Host-side dispatch telemetry for the decode programs in this
    module. The loop bodies themselves are jitted — their Python runs only
    at trace time, so instrumentation inside them would count compiles,
    not dispatches. The engine calls this once per enqueued program:
    ``kind`` is "block" (decode_block_carry), "spec"
    (speculative_block_carry), or "single" (the fused one-step path);
    ``rows`` is how many lanes got a budget and ``steps`` the largest
    per-lane budget in the dispatch. ``attr``/``attr_kw`` carry the
    dispatch's roofline composition to the attribution ledger."""
    from .. import obs

    obs.DECODE_DISPATCHES.inc(kind=kind)
    if rows > 0 and steps > 0:
        obs.get_registry().histogram(
            "opsagent_decode_dispatch_rows",
            "Budgeted lanes per decode dispatch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),
        ).observe(rows)
    _record_attr(kind, attr, attr_kw)


def record_mixed_dispatch(
    decode_rows: int, prefill_tokens: int, budget: int,
    attr=None, attr_kw: dict | None = None,
) -> None:
    """Composition telemetry for one MIXED prefill+decode dispatch
    (engine.step_mixed): how many decode lanes rode the dispatch, how many
    prefill chunk tokens piggybacked on its weight stream, and what
    fraction of the per-dispatch token budget (max_step_tokens) the two
    together used. These are the series the sessions-mixed bench stage
    uses to attribute the one-weight-stream-per-tick win."""
    from .. import obs

    obs.DECODE_DISPATCHES.inc(kind="mixed")
    obs.MIXED_DECODE_LANES.observe(max(0, decode_rows))
    obs.MIXED_PREFILL_TOKENS.observe(max(0, prefill_tokens))
    if budget > 0:
        obs.MIXED_BUDGET_UTILIZATION.observe(
            min(1.0, (decode_rows + prefill_tokens) / budget)
        )
    _record_attr("mixed", attr, attr_kw)


def record_async_dispatch(
    decode_rows: int, prefill_tokens: int, budget: int, depth: int,
    attr=None, attr_kw: dict | None = None,
) -> None:
    """Telemetry for one ASYNC mixed dispatch (engine step_mixed_async /
    serving.async_runtime): the same composition series as the sync mixed
    tick — the async tick is the same batch shape, just pipelined — plus
    the in-flight-depth gauge the overlap proof reads. ``depth`` is the
    pipeline occupancy INCLUDING this dispatch. No ``measured_s`` ever
    rides here: the async dispatch is enqueue-only by design, so its wall
    time is not a step-time measurement."""
    from .. import obs

    obs.DECODE_DISPATCHES.inc(kind="mixed_async")
    obs.MIXED_DECODE_LANES.observe(max(0, decode_rows))
    obs.MIXED_PREFILL_TOKENS.observe(max(0, prefill_tokens))
    if budget > 0:
        obs.MIXED_BUDGET_UTILIZATION.observe(
            min(1.0, (decode_rows + prefill_tokens) / budget)
        )
    obs.ASYNC_INFLIGHT_DEPTH.set(depth)
    _record_attr("mixed_async", attr, attr_kw)


def record_async_commit(overlapped: bool, depth_after: int) -> None:
    """One committed async tick: ``overlapped`` is True when the commit's
    host work (pull, detokenize, stop-scan, streaming) ran while a newer
    dispatch was still executing on device — the condition the whole
    async runtime exists to create."""
    from .. import obs

    obs.ASYNC_COMMITS.inc()
    if overlapped:
        obs.ASYNC_OVERLAPPED_COMMITS.inc()
    obs.ASYNC_INFLIGHT_DEPTH.set(depth_after)


def record_ffwd_append(
    seq_id: int, run_len: int, attr=None, request_id: str | None = None,
) -> None:
    """One forced-token run spliced by the grammar fast-forward path:
    ``run_len`` tokens emitted straight from the constrained FSM's
    singleton masks, each of which would otherwise have cost its own
    forward pass. Counts the run, the tokens, and the skipped dispatches,
    drops a ``ffwd`` flight event, and charges the skipped dispatches to
    the attribution ledger at zero weight-stream cost (the consuming
    dispatch's q_tokens already carry the run's real KV/attention work)
    so ``opsagent_attr_dispatches_total`` and the goodput ledger stay
    honest about how many dispatches the grammar replaced."""
    from .. import obs

    obs.FFWD_RUNS.inc()
    obs.FFWD_TOKENS.inc(run_len)
    obs.FFWD_SKIPPED_DISPATCHES.inc(run_len)
    obs.flight.record(
        "ffwd", seq_id=seq_id, run_len=run_len, request_id=request_id,
    )
    if attr is not None:
        for _ in range(run_len):
            _record_attr("ffwd", attr, dict(weight_streams=0.0))


def mixed_step_carry(
    params: Any,
    cfg: ModelConfig,
    tokens: jax.Array,      # [B, S] int32 host-built ragged rows (prefill
                            # chunks; decode rows' col 0 is a placeholder
                            # when use_carry)
    use_carry: jax.Array,   # [B] bool: row's input token is carry_tok
                            # (decode lane continuing from the previous
                            # dispatch — its token never visited the host)
    carry_tok: jax.Array,   # [B] int32 previous dispatch's sampled tokens
    starts: jax.Array,      # [B] int32 write offsets
    q_lens: jax.Array,      # [B] int32 (0 = inert row)
    emits: jax.Array,       # [B] bool: row's sampled token is real output
                            # (decode lanes + prompt-finishing chunks);
                            # non-emitting rows keep their carry/FSM state
    cache: Any,             # paged KV pytree (donated by the jit wrapper)
    page_table: jax.Array,  # [B, MaxP]
    key: jax.Array,
    temps: jax.Array,       # [B] float32
    top_k: jax.Array,       # [B] int32
    top_p: jax.Array,       # [B] float32
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    mesh=None,
    weight_stream: str = "xla",  # llama.weight_stream_scope backend
    # Device-side constrained decoding, same table layout as
    # decode_block_carry: row 0 of fsm_mask/fsm_dest is the FREE sentinel,
    # DFA state s lives at row s+1. carry_fsm rides the dispatch chain;
    # ov_fsm is the host-walked state for newly seated rows.
    fsm_mask: jax.Array | None = None,
    fsm_dest: jax.Array | None = None,
    carry_fsm: jax.Array | None = None,   # [B] int32
    ov_fsm: jax.Array | None = None,      # [B] int32
) -> tuple[jax.Array, Any, jax.Array]:
    """``llama.mixed_step`` with the sampled-token feedback DEVICE-RESIDENT:
    each decode lane's input token is spliced from ``carry_tok`` — the
    previous dispatch's output — so tick t+1 can be enqueued before tick
    t's tokens are pulled to host (the one-step-lookahead mixed pipeline,
    serving/async_runtime.py). Prefill chunk rows keep taking their tokens
    from the host arrays (prompt ids are host state by definition).

    Returns ``(toks [B], cache, fsm [B])`` where ``toks`` is BOTH the
    pull target for the commit phase and the next dispatch's carry: for
    emitting rows it is the sampled token, for everything else the spliced
    input (a don't-care the host never reads). The FSM state advances only
    on emitting rows, so a chunk whose sampled token is discarded cannot
    corrupt a constrained row's grammar walk."""
    first = jnp.where(use_carry, carry_tok, tokens[:, 0]).astype(jnp.int32)
    tokens = tokens.at[:, 0].set(first)
    logits, cache = llama.mixed_step(
        params, cfg, tokens, starts, q_lens, cache, page_table,
        dtype=dtype, attn_impl=attn_impl, mesh=mesh,
        weight_stream=weight_stream,
    )
    with_fsm = fsm_mask is not None
    if with_fsm:
        fstate = jnp.where(use_carry, carry_fsm, ov_fsm).astype(jnp.int32)
        logits = jnp.where(fsm_mask[fstate], logits, NEG_INF)
    tok = sample(logits, key, temps, top_k, top_p, None).astype(jnp.int32)
    out = jnp.where(emits, tok, first)
    if with_fsm:
        fsm_out = jnp.where(emits, fsm_dest[fstate, tok], fstate)
    else:
        fsm_out = jnp.zeros_like(out)
    return out, cache, fsm_out


def decode_block(
    params: Any,
    cfg: ModelConfig,
    tokens: jax.Array,      # [B] int32: last sampled (not yet written) token
    write_at: jax.Array,    # [B] int32: tokens already written to cache
    active: jax.Array,      # [B] bool
    budgets: jax.Array,     # [B] int32: max tokens this row may emit now
    cache: Any,             # paged KV pytree (donated by the jit wrapper)
    page_table: jax.Array,  # [B, MaxP] — pages for the whole block are
                            # pre-allocated by the caller
    key: jax.Array,         # PRNG key (threaded through, returned updated)
    temps: jax.Array,       # [B] float32
    top_k: jax.Array,       # [B] int32
    top_p: jax.Array,       # [B] float32
    eos_id: jax.Array,      # [] int32
    pad_id: jax.Array,      # [] int32
    n_steps: int,
    greedy: bool = False,
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    mesh=None,               # Mesh for the shard_mapped pallas-under-tp path
    weight_stream: str = "xla",  # llama.weight_stream_scope backend
) -> tuple[jax.Array, Any, jax.Array]:
    """One self-contained block: ``decode_block_carry`` with every lane
    host-initialized (override all) and the carry discarded. Returns
    (tokens_out [B, n_steps] int32 — pad past a row's finish —, cache, key).

    ``greedy=True`` (trace-time) replaces the sampler with a bare argmax —
    the agent-loop default (temperature 0, reference pkg/llms/openai.go:73)
    — because even a top-k candidate scan over a 128k vocab inside the
    decode loop costs several times the decode step itself on TPU.
    """
    toks, cache, (_, _, _, _, key) = decode_block_carry(
        params, cfg,
        carry_tok=tokens, carry_at=write_at,
        carry_eos=jnp.zeros_like(active), key=key,
        override=jnp.ones_like(active), ov_tok=tokens, ov_at=write_at,
        alive=active, budgets=budgets, cache=cache, page_table=page_table,
        temps=temps, top_k=top_k, top_p=top_p,
        eos_id=eos_id, pad_id=pad_id, n_steps=n_steps, greedy=greedy,
        dtype=dtype, attn_impl=attn_impl, mesh=mesh,
        weight_stream=weight_stream,
    )
    return toks, cache, key


def decode_block_carry(
    params: Any,
    cfg: ModelConfig,
    # device-resident carry from the previous dispatch (or zeros):
    carry_tok: jax.Array,   # [B] int32 last sampled (not yet written) token
    carry_at: jax.Array,    # [B] int32 tokens already written to cache
    carry_eos: jax.Array,   # [B] bool  row sampled EOS at some point
    key: jax.Array,         # PRNG key (threaded through)
    # host-supplied per-dispatch inputs:
    override: jax.Array,    # [B] bool  lane newly (re)assigned: take ov_*
    ov_tok: jax.Array,      # [B] int32
    ov_at: jax.Array,       # [B] int32
    alive: jax.Array,       # [B] bool  host wants this lane running
    budgets: jax.Array,     # [B] int32 max tokens this dispatch may emit
    cache: Any,             # paged KV pytree (donated)
    page_table: jax.Array,  # [B, MaxP] pages pre-booked for the whole block
    temps: jax.Array,       # [B] float32
    top_k: jax.Array,       # [B] int32
    top_p: jax.Array,       # [B] float32
    eos_id: jax.Array,      # [] int32
    pad_id: jax.Array,      # [] int32
    n_steps: int,
    greedy: bool = False,
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    mesh=None,               # Mesh for the shard_mapped pallas-under-tp path
    weight_stream: str = "xla",  # llama.weight_stream_scope backend
    # Device-side constrained decoding (SURVEY §7's hard part: the FSM
    # steps on device, no host sync per token). fsm_mask/fsm_dest are the
    # shared [S+1, V] tables — ROW 0 is the FREE sentinel (everything
    # allowed, dest 0) so zero-initialized states mean "unconstrained";
    # DFA state s lives at row s+1. carry_fsm/ov_fsm are per-row states.
    fsm_mask: jax.Array | None = None,
    fsm_dest: jax.Array | None = None,
    carry_fsm: jax.Array | None = None,   # [B] int32
    ov_fsm: jax.Array | None = None,      # [B] int32
) -> tuple[jax.Array, Any, tuple]:
    """``decode_block`` with the loop state living ON DEVICE across
    dispatches, so the host can enqueue block k+1 before pulling block k's
    tokens (the pipelined engine path).

    The host round trip is the throughput ceiling on tunneled/pod setups
    (~70 ms here vs ~6 ms/step device compute); chaining dispatches through
    the returned carry keeps the device busy while the previous block's
    [B, n_steps] token pull and host bookkeeping overlap with compute.
    The ``override`` lane lets the host splice in newly admitted sequences
    (fresh token/write-offset) and ``alive`` lets it kill rows (stop
    strings, cancellations) with one-dispatch lag; everything else — EOS
    detection, per-dispatch budgets, KV writes — is decided on device.

    Returns (tokens [B, n_steps], cache, new carry (tok, at, eos, key)).
    """
    tok = jnp.where(override, ov_tok, carry_tok).astype(jnp.int32)
    at = jnp.where(override, ov_at, carry_at).astype(jnp.int32)
    eos = jnp.where(override, False, carry_eos)
    act0 = alive & ~eos & (budgets > 0)
    with_fsm = fsm_mask is not None
    if with_fsm:
        fstate = jnp.where(override, ov_fsm, carry_fsm).astype(jnp.int32)
    else:
        fstate = jnp.zeros_like(tok)

    def body(carry, step_idx):
        tok, at, eos, act, fstate, cache, key = carry
        logits, cache = llama.decode_step(
            params, cfg, tok, at, cache, page_table, act,
            dtype=dtype, attn_impl=attn_impl, mesh=mesh,
            weight_stream=weight_stream,
        )
        if with_fsm:
            # Grammar mask from the per-row DFA state: one [B, V] gather,
            # no host round trip. NEG_INF (not -inf): masked logits feed
            # a softmax in the sampled path.
            logits = jnp.where(fsm_mask[fstate], logits, -1e30)
        if greedy:
            nxt = jnp.argmax(logits, axis=-1)
        else:
            key, sub = jax.random.split(key)
            nxt = sample(logits, sub, temps, top_k, top_p, None)
        nxt = jnp.where(act, nxt, tok).astype(jnp.int32)
        emitted = jnp.where(act, nxt, pad_id).astype(jnp.int32)
        if with_fsm:
            fstate = jnp.where(act, fsm_dest[fstate, nxt], fstate)
        at = at + act.astype(jnp.int32)
        eos = eos | (act & (nxt == eos_id))
        act = act & ~eos & (step_idx + 1 < budgets)
        return (nxt, at, eos, act, fstate, cache, key), emitted

    (tok, at, eos, _, fstate, cache, key), toks = jax.lax.scan(
        body,
        (tok, at, eos, act0, fstate, cache, key),
        jnp.arange(n_steps),
    )
    return toks.T, cache, (tok, at, eos, fstate, key)


# -- speculative decoding (prompt-lookup / n-gram drafting) ------------------
def ngram_draft(
    hist: jax.Array,    # [B, H] token history (prompt + accepted generation)
    at: jax.Array,      # [B] written-token counts (hist[:, :at] is real)
    tok: jax.Array,     # [B] next input token (not yet in hist)
    k: int,             # draft length
    ngram: int,         # match-gram length (includes tok as its last item)
) -> jax.Array:
    """Prompt-lookup drafting, fully on device: find the LAST earlier
    occurrence of the trailing ``ngram`` (history tail + tok) and propose
    the k tokens that followed it. Agent ReAct loops re-emit the same JSON
    scaffolding every iteration, so lookups hit constantly. Rows with no
    match draft pad-like junk that simply fails verification (costing
    nothing extra — the verify forward runs regardless). Returns
    [B, k] draft tokens."""
    B, H = hist.shape
    g = ngram
    gpos = at[:, None] - (g - 1) + jnp.arange(g)[None, :]          # [B, g]
    gram = jnp.take_along_axis(hist, jnp.clip(gpos, 0, H - 1), axis=1)
    gram = gram.at[:, -1].set(tok)
    W = H - g + 1
    eq = jnp.ones((B, W), bool)
    for i in range(g):
        eq &= hist[:, i : W + i] == gram[:, i : i + 1]
    jpos = jnp.arange(W)[None, :]
    # The candidate window must end strictly before the current tail gram
    # (else it matches itself), and its draft must be written history.
    ok = eq & (jpos + g + k - 1 <= at[:, None] - 1)
    j = jnp.max(jnp.where(ok, jpos, -1), axis=1)                   # [B]
    dpos = j[:, None] + g + jnp.arange(k)[None, :]
    draft = jnp.take_along_axis(hist, jnp.clip(dpos, 0, H - 1), axis=1)
    return jnp.where(j[:, None] >= 0, draft, -1)


def speculative_block_carry(
    params: Any,
    cfg: ModelConfig,
    carry_tok: jax.Array,   # [B] int32 last sampled (not yet written) token
    carry_at: jax.Array,    # [B] int32 tokens already written to cache
    carry_eos: jax.Array,   # [B] bool
    carry_hist: jax.Array,  # [B, H] int32 device-resident token history
    override: jax.Array,    # [B] bool  lane newly (re)assigned
    ov_tok: jax.Array,      # [B] int32
    ov_at: jax.Array,       # [B] int32
    ov_hist: jax.Array,     # [B, H] int32 host-supplied history for overrides
    alive: jax.Array,       # [B] bool
    budgets: jax.Array,     # [B] int32 max tokens this dispatch may emit
    cache: Any,             # paged KV pytree (donated)
    page_table: jax.Array,  # [B, MaxP]
    eos_id: jax.Array,
    pad_id: jax.Array,
    n_steps: int,           # scan iterations (each emits 1..k+1 tokens)
    k: int,                 # draft tokens per iteration
    ngram: int = 2,
    dtype: jnp.dtype = jnp.bfloat16,
) -> tuple[jax.Array, jax.Array, Any, tuple]:
    """GREEDY decode with prompt-lookup speculation, device-resident like
    ``decode_block_carry``: each scan step drafts k tokens from the row's
    own history, verifies them in ONE multi-position forward
    (llama.verify_step), and emits the accepted prefix + the model's own
    next token — 1 to k+1 tokens per weight-streaming pass. Emission stops
    at EOS and at the per-dispatch budget; KV written for rejected draft
    positions is overwritten later (write offset advances by accepted
    count only), and writes past the booked pages land on -1 table slots,
    which drop.

    Returns (tokens [B, n_steps, k+1] — pad past each step's count —,
    counts [B, n_steps], cache, new carry (tok, at, eos, hist)).
    """
    B = carry_tok.shape[0]
    tok = jnp.where(override, ov_tok, carry_tok).astype(jnp.int32)
    at = jnp.where(override, ov_at, carry_at).astype(jnp.int32)
    eos = jnp.where(override, False, carry_eos)
    hist = jnp.where(override[:, None], ov_hist, carry_hist).astype(jnp.int32)
    iota = jnp.arange(k + 1)[None, :]

    def body(carry, _):
        tok, at, eos, act, emitted, cache, hist = carry
        rem = budgets - emitted
        draft = ngram_draft(hist, at, tok, k, ngram)
        inputs = jnp.concatenate([tok[:, None], draft], axis=1)    # [B, k+1]
        valid = jnp.where(act, jnp.minimum(k + 1, rem), 0)
        logits, cache = llama.verify_step(
            params, cfg, inputs, at, valid, cache, page_table, dtype=dtype
        )
        a = jnp.argmax(logits, axis=-1).astype(jnp.int32)          # [B, k+1]
        match = (draft == a[:, :k]).astype(jnp.int32)
        prefix_ok = jnp.cumprod(match, axis=1)                     # [B, k]
        can = jnp.concatenate(
            [jnp.ones((B, 1), jnp.int32), prefix_ok], axis=1
        )                                                          # [B, k+1]
        no_eos_before = jnp.cumprod(
            jnp.concatenate(
                [jnp.ones((B, 1), jnp.int32),
                 (a[:, :k] != eos_id).astype(jnp.int32)],
                axis=1,
            ),
            axis=1,
        )
        emit = (
            (can * no_eos_before) > 0
        ) & (iota < rem[:, None]) & act[:, None]
        n_emit = jnp.sum(emit, axis=1).astype(jnp.int32)           # [B]
        out_toks = jnp.where(emit, a, pad_id).astype(jnp.int32)
        eos_new = eos | jnp.any(emit & (a == eos_id), axis=1)
        # History: the ACCEPTED inputs land at positions at..at+n_emit-1.
        wpos = at[:, None] + iota
        H = hist.shape[1]
        hpos = jnp.where(
            (iota < n_emit[:, None]) & (wpos < H), wpos, H
        )
        hist = jax.vmap(
            lambda h, p, v: h.at[p].set(v, mode="drop")
        )(hist, hpos, inputs)
        last = jnp.take_along_axis(
            a, jnp.clip(n_emit - 1, 0, k)[:, None], axis=1
        )[:, 0]
        tok = jnp.where(n_emit > 0, last, tok)
        at = at + n_emit
        emitted = emitted + n_emit
        act = act & ~eos_new & (emitted < budgets)
        return (tok, at, eos_new, act, emitted, cache, hist), (
            out_toks, n_emit
        )

    act0 = alive & ~eos & (budgets > 0)
    (tok, at, eos, _, _, cache, hist), (toks, counts) = jax.lax.scan(
        body,
        (tok, at, eos, act0, jnp.zeros((B,), jnp.int32), cache, hist),
        None,
        length=n_steps,
    )
    # scan stacks leading: toks [n_steps, B, k+1] -> [B, n_steps, k+1].
    return (
        jnp.transpose(toks, (1, 0, 2)),
        counts.T,
        cache,
        (tok, at, eos, hist),
    )
