"""Device-resident multi-step decode: N (decode + sample) steps per host
dispatch, as one XLA program.

Why this exists: the engine's original hot loop pulled the sampled token to
the host after EVERY decode step. A device->host transfer costs a full
round trip (~10-70 ms on tunneled/pod setups — far more than the decode
step's own compute), so per-token pulls cap throughput at ~1/RTT regardless
of model size. Scanning ``n_steps`` decode+sample iterations inside one
``jax.jit`` amortizes the dispatch AND the single [B, n_steps] token pull
over the whole block, leaving the device busy back-to-back.

Per-row early exit happens ON DEVICE: a row goes inactive when it samples
EOS or exhausts its per-dispatch token budget. Inactive rows stop writing KV
(their page state stays exactly "prompt + accepted[:-1]") and emit pad
tokens, which the host-side bookkeeping discards. Stop-string checks remain
host-side — the host walks each row's block output token by token and
truncates the page allocation back to what it accepted.

Replaces the per-token HTTPS round trip of the reference agent loop
(reference pkg/assistants/simple.go:343,515) with its tpu-native dual: the
round trip is now per-BLOCK, not per-token.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..models import llama
from ..models.config import ModelConfig
from .sampler import sample


def decode_block(
    params: Any,
    cfg: ModelConfig,
    tokens: jax.Array,      # [B] int32: last sampled (not yet written) token
    write_at: jax.Array,    # [B] int32: tokens already written to cache
    active: jax.Array,      # [B] bool
    budgets: jax.Array,     # [B] int32: max tokens this row may emit now
    cache: Any,             # paged KV pytree (donated by the jit wrapper)
    page_table: jax.Array,  # [B, MaxP] — pages for the whole block are
                            # pre-allocated by the caller
    key: jax.Array,         # PRNG key (threaded through, returned updated)
    temps: jax.Array,       # [B] float32
    top_k: jax.Array,       # [B] int32
    top_p: jax.Array,       # [B] float32
    eos_id: jax.Array,      # [] int32
    pad_id: jax.Array,      # [] int32
    n_steps: int,
    greedy: bool = False,
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    mesh=None,               # Mesh for the shard_mapped pallas-under-tp path
) -> tuple[jax.Array, Any, jax.Array]:
    """One self-contained block: ``decode_block_carry`` with every lane
    host-initialized (override all) and the carry discarded. Returns
    (tokens_out [B, n_steps] int32 — pad past a row's finish —, cache, key).

    ``greedy=True`` (trace-time) replaces the sampler with a bare argmax —
    the agent-loop default (temperature 0, reference pkg/llms/openai.go:73)
    — because even a top-k candidate scan over a 128k vocab inside the
    decode loop costs several times the decode step itself on TPU.
    """
    toks, cache, (_, _, _, key) = decode_block_carry(
        params, cfg,
        carry_tok=tokens, carry_at=write_at,
        carry_eos=jnp.zeros_like(active), key=key,
        override=jnp.ones_like(active), ov_tok=tokens, ov_at=write_at,
        alive=active, budgets=budgets, cache=cache, page_table=page_table,
        temps=temps, top_k=top_k, top_p=top_p,
        eos_id=eos_id, pad_id=pad_id, n_steps=n_steps, greedy=greedy,
        dtype=dtype, attn_impl=attn_impl, mesh=mesh,
    )
    return toks, cache, key


def decode_block_carry(
    params: Any,
    cfg: ModelConfig,
    # device-resident carry from the previous dispatch (or zeros):
    carry_tok: jax.Array,   # [B] int32 last sampled (not yet written) token
    carry_at: jax.Array,    # [B] int32 tokens already written to cache
    carry_eos: jax.Array,   # [B] bool  row sampled EOS at some point
    key: jax.Array,         # PRNG key (threaded through)
    # host-supplied per-dispatch inputs:
    override: jax.Array,    # [B] bool  lane newly (re)assigned: take ov_*
    ov_tok: jax.Array,      # [B] int32
    ov_at: jax.Array,       # [B] int32
    alive: jax.Array,       # [B] bool  host wants this lane running
    budgets: jax.Array,     # [B] int32 max tokens this dispatch may emit
    cache: Any,             # paged KV pytree (donated)
    page_table: jax.Array,  # [B, MaxP] pages pre-booked for the whole block
    temps: jax.Array,       # [B] float32
    top_k: jax.Array,       # [B] int32
    top_p: jax.Array,       # [B] float32
    eos_id: jax.Array,      # [] int32
    pad_id: jax.Array,      # [] int32
    n_steps: int,
    greedy: bool = False,
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    mesh=None,               # Mesh for the shard_mapped pallas-under-tp path
) -> tuple[jax.Array, Any, tuple[jax.Array, jax.Array, jax.Array, jax.Array]]:
    """``decode_block`` with the loop state living ON DEVICE across
    dispatches, so the host can enqueue block k+1 before pulling block k's
    tokens (the pipelined engine path).

    The host round trip is the throughput ceiling on tunneled/pod setups
    (~70 ms here vs ~6 ms/step device compute); chaining dispatches through
    the returned carry keeps the device busy while the previous block's
    [B, n_steps] token pull and host bookkeeping overlap with compute.
    The ``override`` lane lets the host splice in newly admitted sequences
    (fresh token/write-offset) and ``alive`` lets it kill rows (stop
    strings, cancellations) with one-dispatch lag; everything else — EOS
    detection, per-dispatch budgets, KV writes — is decided on device.

    Returns (tokens [B, n_steps], cache, new carry (tok, at, eos, key)).
    """
    tok = jnp.where(override, ov_tok, carry_tok).astype(jnp.int32)
    at = jnp.where(override, ov_at, carry_at).astype(jnp.int32)
    eos = jnp.where(override, False, carry_eos)
    act0 = alive & ~eos & (budgets > 0)

    def body(carry, step_idx):
        tok, at, eos, act, cache, key = carry
        logits, cache = llama.decode_step(
            params, cfg, tok, at, cache, page_table, act,
            dtype=dtype, attn_impl=attn_impl, mesh=mesh,
        )
        if greedy:
            nxt = jnp.argmax(logits, axis=-1)
        else:
            key, sub = jax.random.split(key)
            nxt = sample(logits, sub, temps, top_k, top_p, None)
        nxt = jnp.where(act, nxt, tok).astype(jnp.int32)
        emitted = jnp.where(act, nxt, pad_id).astype(jnp.int32)
        at = at + act.astype(jnp.int32)
        eos = eos | (act & (nxt == eos_id))
        act = act & ~eos & (step_idx + 1 < budgets)
        return (nxt, at, eos, act, cache, key), emitted

    (tok, at, eos, _, cache, key), toks = jax.lax.scan(
        body,
        (tok, at, eos, act0, cache, key),
        jnp.arange(n_steps),
    )
    return toks.T, cache, (tok, at, eos, key)
