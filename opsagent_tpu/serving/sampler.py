"""Token sampling: greedy, temperature, top-k, top-p — one fused jittable
function over the decode batch, with an optional constrained-decoding mask.

The near-greedy default mirrors the reference client's
``Temperature: math.SmallestNonzeroFloat32`` (reference pkg/llms/openai.go:73):
temperature 0 means argmax.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0      # 1.0 = disabled
    max_tokens: int = 2048
    stop: tuple[str, ...] = ()


def sample(
    logits: jax.Array,             # [B, V] float32
    key: jax.Array,
    temperature: jax.Array,        # [B]
    top_k: jax.Array,              # [B] int32 (0 = off)
    top_p: jax.Array,              # [B] float32 (1.0 = off)
    allowed_mask: jax.Array | None = None,  # [B, V] bool; False = forbidden
) -> jax.Array:
    """Sample one token per row. Rows with temperature<=0 take the argmax."""
    B, V = logits.shape
    if allowed_mask is not None:
        logits = jnp.where(allowed_mask, logits, NEG_INF)

    greedy = jnp.argmax(logits, axis=-1)

    # -- top-k: mask everything below the k-th largest logit.
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]          # [B, V]
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, V) - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=1)  # [B, 1]
    logits_k = jnp.where(logits >= kth, logits, NEG_INF)

    # -- top-p over the surviving set.
    t = jnp.maximum(temperature, 1e-6)[:, None]
    probs_sorted = jax.nn.softmax(jnp.sort(logits_k / t, axis=-1)[:, ::-1], axis=-1)
    cumsum = jnp.cumsum(probs_sorted, axis=-1)
    # Number of tokens needed to reach top_p mass (always keep >= 1).
    keep_sorted = cumsum - probs_sorted < top_p[:, None]
    cutoff_val = jnp.sort(logits_k, axis=-1)[:, ::-1]
    cutoff = jnp.max(jnp.where(keep_sorted, -cutoff_val, NEG_INF), axis=-1)
    logits_p = jnp.where(logits_k >= -cutoff[:, None], logits_k, NEG_INF)

    sampled = jax.random.categorical(key, logits_p / t, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)
