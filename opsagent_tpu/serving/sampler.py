"""Token sampling: greedy, temperature, top-k, top-p — one fused jittable
function over the decode batch, with an optional constrained-decoding mask.

The near-greedy default mirrors the reference client's
``Temperature: math.SmallestNonzeroFloat32`` (reference pkg/llms/openai.go:73):
temperature 0 means argmax.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0      # 1.0 = disabled
    max_tokens: int = 2048
    stop: tuple[str, ...] = ()
    # OpenAI logprobs: False = off; True returns each sampled token's
    # logprob, with top_logprobs (0..20) alternatives per position.
    logprobs: bool = False
    top_logprobs: int = 0
    # OpenAI logit_bias ({token_id: -100..100}, stored as pairs for
    # hashability) and repetition penalties (-2..2): together they form
    # one additive per-token bias applied to logits before sampling.
    logit_bias: tuple[tuple[int, float], ...] = ()
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # Tokens generated before an engine restart (set by the scheduler's
    # recovery path): penalty counting includes them, so sampling behavior
    # does not silently change because a slice restarted mid-request.
    penalty_history: tuple[int, ...] = ()


# Candidate-set size for top-k / top-p sampling. Full-vocab SORTS are the
# dominant cost of a fused decode+sample step on TPU (a [B, 128k] sort
# dwarfs the decode matmuls at small batch), so truncation-based sampling
# works on the top-MAX_CANDIDATES logits from one cheap ``lax.top_k``.
# Plain temperature sampling does NOT go through the candidate set — it is
# computed exactly over the full vocab with the Gumbel-argmax trick (argmax
# of logits/t + Gumbel noise ~ categorical(softmax(logits/t))), which needs
# no sort at all. Only requests that themselves ask for truncation
# (top_k > 0, clamped to 64, or top_p < 1) use the candidate list.
MAX_CANDIDATES = 64


def sample(
    logits: jax.Array,             # [B, V] float32
    key: jax.Array,
    temperature: jax.Array,        # [B]
    top_k: jax.Array,              # [B] int32 (0 = off)
    top_p: jax.Array,              # [B] float32 (1.0 = off)
    allowed_mask: jax.Array | None = None,  # [B, V] bool; False = forbidden
) -> jax.Array:
    """Sample one token per row, sort-free. Per row:

    - temperature <= 0: argmax (the agent-loop default).
    - temperature > 0, no top-k/top-p: EXACT full-vocab categorical via
      Gumbel-argmax.
    - top_k > 0 and/or top_p < 1: truncated sampling over the descending
      top-``MAX_CANDIDATES`` candidate list (top_k clamped to it; top-p
      mass computed within it), mapped back to vocab ids."""
    B, V = logits.shape
    if allowed_mask is not None:
        logits = jnp.where(allowed_mask, logits, NEG_INF)

    t = jnp.maximum(temperature, 1e-6)[:, None]
    # Independent keys: the full-vocab Gumbel draw and categorical's
    # internal draw must not share Threefry counter space.
    k_noisy, k_trunc = jax.random.split(key)

    # -- exact paths: greedy and Gumbel-argmax temperature sampling.
    gumbel = jax.random.gumbel(k_noisy, (B, V), dtype=logits.dtype)
    noisy = jnp.argmax(logits / t + gumbel, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)

    # -- truncated path over the candidate list.
    C = min(MAX_CANDIDATES, V)
    vals, idx = jax.lax.top_k(logits, C)           # [B, C] descending
    kk = jnp.where(top_k > 0, jnp.minimum(top_k, C), C)      # [B]
    pos = jnp.arange(C)[None, :]
    scaled = jnp.where(pos < kk[:, None], vals, NEG_INF) / t
    # top-p: keep the smallest prefix reaching top_p mass (always >= 1).
    probs = jax.nn.softmax(scaled, axis=-1)
    cumsum = jnp.cumsum(probs, axis=-1)
    keep = cumsum - probs < top_p[:, None]
    scaled_p = jnp.where(keep, scaled, NEG_INF)
    choice = jax.random.categorical(k_trunc, scaled_p, axis=-1)  # [B] in [0, C)
    truncated = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0]

    wants_truncation = (top_k > 0) | (top_p < 1.0)
    sampled = jnp.where(wants_truncation, truncated, noisy)
    return jnp.where(temperature <= 0.0, greedy, sampled)
