"""One-step-lookahead async mixed serving ticks.

PERF.md's r04 trace work showed the raw device loop does ~4775 tok/s/chip
while the full serving stack delivers a tenth of that — because every
mixed scheduler tick is synchronous: dispatch ``step_mixed``, block on
``np.asarray(toks)``, then do ALL host work (detokenize, stop-string
scan, streaming callbacks, trie bookkeeping, admission planning) while
the device sits idle. This module makes the mixed prefill+decode path a
two-deep asynchronous pipeline:

- **Plan phase (runs ahead)**: build tick t+1's batch and enqueue its
  dispatch BEFORE tick t's tokens are pulled. The decode lanes' input
  tokens never visit the host — they ride a device-resident carry (the
  previous dispatch's sampled-token output, exactly the trick
  ``decode_loop.decode_block_carry`` plays for pure block decode).
- **Commit phase (lags one step)**: pull tick t's tokens and run the
  host post-processing while tick t+1 executes on device.

Consequences the rest of the engine absorbs:

- Stop-string and EOS detection lag one tick: a finished row's single
  overshoot token is discarded at commit and its page booking rolled
  back (``opsagent_async_overshoot_tokens_total`` counts them).
  ``max_tokens`` finishes exactly — the planner never books past the
  budget — so only data-dependent finishes pay the overshoot.
- A prompt whose final chunk is in flight keeps decoding through
  *lookahead lanes*: its first sampled token exists only in the device
  carry, so the runtime seats it as a carry-fed decode row before the
  scheduler even learns the admission completed.
- Constrained rows ride the async lane only when their FSM has dense
  device tables (``constrained.device_table_fsm``): the grammar mask
  comes from on-device state (``decode_loop.mixed_step_carry``).
  Everything else — hosted masks, logprobs, logit bias — falls back to
  the existing sync lanes (the scheduler routes those ticks away before
  they get here).

The runtime operates on the Engine's state under the Engine's lock; the
Engine owns one instance and exposes it as ``step_mixed_async`` /
``async_drain`` (engine.py).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats
from ..utils.profiling import annotate
from .constrained import device_table_fsm
from .kvcache import OutOfPages

log = get_logger("async_runtime")


@dataclass
class _Tick:
    """One dispatched-but-uncommitted mixed tick."""

    toks_d: Any                       # device [B] sampled tokens (= carry)
    decode: list = field(default_factory=list)   # [(seq_id, lane)]
    chunks: list = field(default_factory=list)   # [(seq_id, lane, done, c)]
    # Grammar fast-forward pre-accepts: seq_id -> forced tokens this
    # dispatch appended BEFORE its sampled token (committed in order
    # ahead of the pull; see _dispatch's ffwd planning).
    ffwd: dict = field(default_factory=dict)
    t_disp: float = 0.0
    tick_id: int = 0


class AsyncMixedRuntime:
    """Plan/commit machinery for the async mixed pipeline. Every method
    assumes the caller holds the engine lock (the Engine wrappers do)."""

    def __init__(self, engine):
        self.eng = engine
        self._pending: deque[_Tick] = deque()
        # Previous dispatch's seating: carry continuity requires a decode
        # row to have emitted in the immediately preceding dispatch in the
        # same lane — anything else re-seats from host state.
        self._prev_lane: dict[int, int] = {}
        self._prev_emitted: set[int] = set()
        # Tokens sampled but not yet committed, per sequence: the budget
        # guard (max_tokens is never overshot) and the carry-break check.
        self._inflight_toks: dict[int, int] = {}
        # Prompts whose FINAL chunk is dispatched but uncommitted: they
        # ride subsequent ticks as carry-fed lookahead decode lanes.
        self._finishing: set[int] = set()
        # Committed results awaiting pickup (internal settles — parking,
        # warmup, sync-lane fallbacks — commit into this buffer so a
        # finished admission can never be lost between scheduler ticks).
        self._results: tuple[dict, dict] = ({}, {})
        self._tick_id = 0

    # -- public surface (via Engine wrappers) -------------------------------
    @property
    def pending(self) -> int:
        return len(self._pending)

    def take_results(self) -> tuple[dict[int, list[int]], dict[int, Any]]:
        d, p = self._results
        self._results = ({}, {})
        return d, p

    def step(
        self, decode_ids: list[int], prefill_chunks: dict[int, int]
    ) -> tuple[dict[int, list[int]], dict[int, Any]]:
        """Dispatch one mixed tick (plan phase) and commit every tick past
        the configured lookahead depth. Returns the committed results so
        far — which, at depth > 1, describe EARLIER ticks than the one
        just dispatched."""
        depth = max(1, getattr(self.eng.cfg, "async_depth", 1))
        dispatched = False
        if decode_ids or prefill_chunks or self._finishing:
            dispatched = self._dispatch(decode_ids, prefill_chunks)
        while len(self._pending) >= depth:
            self._commit_oldest()
        if not dispatched and self._pending:
            # Nothing was dispatched (every row finished, budget-covered,
            # or filtered): a commit still guarantees progress — the
            # caller must never spin on a pipeline only it can drain.
            self._commit_oldest()
        return self.take_results()

    def drain_decode(self) -> dict[int, list[int]]:
        """Flush, then hand back ONLY the decode tokens; buffered prefill
        completions stay for ``take_results`` (the scheduler still needs
        them). Engine.drain's pickup form."""
        self.flush()
        d, p = self._results
        self._results = ({}, p)
        return d

    def flush(self) -> None:
        """Commit everything in flight and drop the carry seating: the
        next dispatch re-seats every row from (now current) host state.
        Called before any sync-lane engine path touches shared state."""
        while self._pending:
            self._commit_oldest()
        self._prev_lane = {}
        self._prev_emitted = set()
        self._inflight_toks.clear()
        self._finishing.clear()

    # -- plan phase ----------------------------------------------------------
    def _dispatch(
        self, decode_ids: list[int], prefill_chunks: dict[int, int]
    ) -> bool:
        eng = self.eng
        cfg = eng.cfg
        B = cfg.max_batch_size
        MaxP = cfg.max_pages_per_seq
        decode = [
            eng.sequences[s] for s in decode_ids
            if s in eng.sequences and not eng.sequences[s].done
        ]
        # Lookahead lanes: prompts whose final chunk is in flight decode
        # through the carry without waiting for the scheduler to learn
        # the admission completed. Continuity required — their only token
        # lives in the device carry.
        seen = {s.seq_id for s in decode}
        for sid in sorted(self._finishing):
            if sid in seen:
                continue
            s = eng.sequences.get(sid)
            if (
                s is not None and not s.done
                and sid in self._prev_lane and sid in self._prev_emitted
            ):
                decode.append(s)
        # Carry-continuity check: a decode row with uncommitted tokens
        # MUST have emitted in the previous dispatch (its next input token
        # exists only in the device carry). A skipped tick means the
        # caller reordered rows under us — settle so host state is
        # current, then everything re-seats fresh.
        for s in decode:
            if self._inflight_toks.get(s.seq_id, 0) and (
                s.seq_id not in self._prev_lane
                or s.seq_id not in self._prev_emitted
            ):
                obs.ASYNC_FALLBACKS.inc(reason="carry_break")
                self.flush()
                break
        # Budget guard: never dispatch a token max_tokens cannot accept —
        # "length" finishes exactly, with no overshoot to discard.
        decode = [
            s for s in decode
            if not s.done
            and len(s.tokens) + self._inflight_toks.get(s.seq_id, 0)
            < s.params.max_tokens
        ]
        # Book the token each decode lane is about to write. On a dry
        # pool, committing the pipeline first can roll finished rows'
        # bookings back — retry once before truncating (step()'s flow).
        grown: list = []
        for s in decode:
            try:
                eng.alloc.extend(s.seq_id, 1)
                grown.append(s)
                continue
            except OutOfPages:
                pass
            self.flush()
            if s.done:
                continue
            try:
                eng.alloc.extend(s.seq_id, 1)
                grown.append(s)
            except OutOfPages:
                s.done = True
                s.finish_reason = "length"
                obs.PREEMPTIONS.inc()
                obs.flight.record("preemption", seq_id=s.seq_id)
                log.warning(
                    "seq %d truncated: KV page budget exhausted", s.seq_id
                )
        decode = [s for s in grown if not s.done]
        # Grammar fast-forward plans: a constrained decode row whose
        # current FSM state forces a run of singleton-mask tokens appends
        # the whole run in THIS dispatch (the q_len>1 path chunk rows
        # already ride) and samples only the token after it — every
        # forced token skips a forward pass. Host state must pin the
        # row's FSM state, so rows with more than one uncommitted token
        # wait for commits to catch up (after a fast-forward tick the
        # row rides the normal carry for a tick, then re-engages). A
        # CONTINUING row's one in-flight token was sampled at the forced
        # state, so it IS run[0] by determinism: feed it from host
        # knowledge instead of the carry and append the rest.
        # plan: seq_id -> (anchor token, pre-accept tokens, device ov state)
        ffwd_plan: dict[int, tuple[int, list[int], int]] = {}
        if getattr(cfg, "grammar_ffwd", False):
            for s in decode:
                sid = s.seq_id
                inflight = self._inflight_toks.get(sid, 0)
                if inflight > 1:
                    continue
                fsm = device_table_fsm(s.mask_fn)
                if fsm is None:
                    continue
                st0 = s.mask_fn.dfa_state(s.tokens)
                run = fsm.forced_run(st0) if st0 >= 0 else []
                if run and run[-1] == fsm.eos_id:
                    # A masked sample at the eos-only state yields eos at
                    # any temperature — the trailing eos needs no append.
                    run = run[:-1]
                if inflight == 1:
                    if (
                        sid not in self._prev_lane
                        or sid not in self._prev_emitted
                        or len(run) < 2
                    ):
                        continue
                    anchor, pre = run[0], run[1:]
                else:
                    if not run:
                        continue
                    anchor = (
                        s.tokens[-1] if s.tokens else eng.tokenizer.bos_id
                    )
                    pre = run
                # Never overshoot max_tokens (pre + one sampled token on
                # top of what is already in flight) or the largest bucket.
                cap = min(
                    s.params.max_tokens - len(s.tokens) - inflight - 1,
                    cfg.mixed_buckets[-1] - 1,
                )
                pre = pre[: max(0, cap)]
                if not pre:
                    continue
                # The masked sample applies the state AFTER everything
                # this dispatch consumes beyond st0: the anchor too for
                # a continuing row (its in-flight token was sampled AT
                # st0), just the pre tokens for a settled one (st0
                # already includes the anchor = last host token).
                st = st0
                for t in ([anchor] + pre) if inflight == 1 else pre:
                    st = fsm.advance(st, t)
                # Book the extra tokens; a dry pool drops the plan (the
                # row keeps its normal one-token path).
                try:
                    eng.alloc.extend(sid, len(pre))
                except OutOfPages:
                    eng.alloc.truncate(sid, eng.alloc.length(sid))
                    continue
                ffwd_plan[sid] = (int(anchor), pre, st + 1)
        chunk_info: list[tuple[int, Any, int, int]] = []
        smax = 1
        for _sid, (_a, _pre, _st) in ffwd_plan.items():
            smax = max(smax, 1 + len(_pre))
        for sid, want in prefill_chunks.items():
            seq = eng.sequences.get(sid)
            if seq is None or sid not in eng._prefilling:
                continue
            done = eng._prefilling[sid]
            c = min(want, cfg.mixed_buckets[-1], seq.prompt_len - done)
            if c <= 0:
                continue
            chunk_info.append((sid, seq, done, c))
            smax = max(smax, c)
        if not decode and not chunk_info:
            return False
        if len(decode) + len(chunk_info) > B:
            raise ValueError(
                f"async mixed batch of {len(decode)} decode + "
                f"{len(chunk_info)} prefill rows exceeds max_batch_size={B}"
            )
        S = eng._mixed_bucket(smax)

        # Lane assignment: continuing decode rows keep their lane (the
        # carry is indexed by lane); everyone else takes a free one.
        taken: set[int] = set()
        lane_of: dict[int, int] = {}
        continuing: set[int] = set()
        for s in decode:
            ln = self._prev_lane.get(s.seq_id)
            if (
                ln is not None and s.seq_id in self._prev_emitted
                and ln not in taken
            ):
                lane_of[s.seq_id] = ln
                taken.add(ln)
                continuing.add(s.seq_id)
        for sid, *_ in chunk_info:
            ln = self._prev_lane.get(sid)
            if ln is not None and ln not in taken:
                lane_of[sid] = ln
                taken.add(ln)
        free = [i for i in range(B) if i not in taken]
        for s in decode:
            if s.seq_id not in lane_of:
                lane_of[s.seq_id] = free.pop(0)
        for sid, *_ in chunk_info:
            if sid not in lane_of:
                lane_of[sid] = free.pop(0)

        tokens = np.full((B, S), eng.tokenizer.pad_id, np.int32)
        use_carry = np.zeros((B,), bool)
        starts = np.zeros((B,), np.int32)
        qlens = np.zeros((B,), np.int32)
        emits = np.zeros((B,), bool)
        ov_fsm = np.zeros((B,), np.int32)
        tables = np.full((B, MaxP), -1, np.int32)
        temps = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        fsm_obj = None

        def _seat_fsm(fsm):
            nonlocal fsm_obj
            if fsm_obj is None:
                fsm_obj = fsm
            elif fsm_obj is not fsm:
                # The scheduler routes mixed-schema ticks to the sync
                # lane; reaching here means a caller bypassed that gate.
                raise ValueError(
                    "distinct FSM schemas in one async mixed dispatch"
                )

        def _walk(fsm, toks: list[int]) -> int:
            st = fsm.dfa.start
            for t in toks:
                if t != fsm.eos_id:
                    st = fsm.advance(st, t)
            return st + 1  # device-table row 0 is the FREE sentinel

        dec_rows: list[tuple[Any, int]] = []
        for s in decode:
            lane = lane_of[s.seq_id]
            dec_rows.append((s, lane))
            plan = ffwd_plan.get(s.seq_id)
            q = 1 if plan is None else 1 + len(plan[1])
            qlens[lane] = q
            emits[lane] = True
            # The bookings above made alloc.length = written + inflight
            # + q; the row writes its q inputs from the slots before it.
            starts[lane] = eng.alloc.length(s.seq_id) - q
            tables[lane] = eng.alloc.page_table_row(s.seq_id)
            temps[lane] = s.params.temperature
            top_k[lane] = s.params.top_k
            top_p[lane] = s.params.top_p
            fsm = device_table_fsm(s.mask_fn)
            if fsm is not None:
                _seat_fsm(fsm)
            if plan is not None:
                # Fast-forward row: every input token is host-known (the
                # anchor by forced determinism), so the carry is unused.
                anchor, pre, ov_dev = plan
                tokens[lane, :q] = [anchor] + pre
                ov_fsm[lane] = ov_dev
            elif s.seq_id in continuing:
                use_carry[lane] = True
            else:
                tokens[lane, 0] = (
                    s.tokens[-1] if s.tokens else eng.tokenizer.bos_id
                )
                if fsm is not None:
                    ov_fsm[lane] = _walk(fsm, s.tokens)
        chk_rows: list[tuple[int, int, int, int, bool]] = []
        for sid, seq, done, c in chunk_info:
            lane = lane_of[sid]
            finishing = done + c >= seq.prompt_len
            chk_rows.append((sid, lane, done, c, finishing))
            tokens[lane, :c] = seq.prompt_ids[done:done + c]
            starts[lane] = done
            qlens[lane] = c
            tables[lane] = eng.alloc.page_table_row(sid)
            temps[lane] = seq.params.temperature
            top_k[lane] = seq.params.top_k
            top_p[lane] = seq.params.top_p
            emits[lane] = finishing
            if finishing:
                fsm = device_table_fsm(seq.mask_fn)
                if fsm is not None:
                    _seat_fsm(fsm)
                    ov_fsm[lane] = _walk(fsm, seq.tokens)

        perf = get_perf_stats()
        now = time.perf_counter()
        if eng._mixed_gap_stamp is not None:
            gap = now - eng._mixed_gap_stamp
            obs.STEP_HOST_GAP_SECONDS.observe(gap, mode="async")
            perf.record_metric("engine.step_host_gap", gap * 1e3, "ms")
        t_disp = time.perf_counter()
        try:
            with annotate("engine.mixed_step_async"), eng.mesh_ctx():
                eng._sample_key, sub = jax.random.split(eng._sample_key)
                carry = eng._async_carry
                if carry is None:
                    carry = jnp.zeros((B,), jnp.int32)
                fsmc = eng._async_fsm_carry
                if fsmc is None:
                    fsmc = jnp.zeros((B,), jnp.int32)
                if fsm_obj is not None:
                    fm, fd = eng._fsm_device_tables(fsm_obj)
                else:
                    fm = fd = None
                toks_d, eng.cache, fsm_d = eng._mixed_carry_jit(
                    eng.params,
                    jnp.asarray(tokens),
                    jnp.asarray(use_carry),
                    carry,
                    jnp.asarray(starts),
                    jnp.asarray(qlens),
                    jnp.asarray(emits),
                    eng.cache,
                    jnp.asarray(tables),
                    sub,
                    jnp.asarray(temps),
                    jnp.asarray(top_k),
                    jnp.asarray(top_p),
                    fsm_mask=fm,
                    fsm_dest=fd,
                    carry_fsm=fsmc,
                    ov_fsm=jnp.asarray(ov_fsm),
                )
            eng._async_carry = toks_d
            eng._async_fsm_carry = fsm_d
        except Exception:
            # Salvage what earlier (healthy) dispatches produced, then
            # roll back THIS tick: the +1 bookings are for tokens the
            # failed dispatch never wrote, and its chunk admissions
            # follow step_mixed's drop-and-reraise contract.
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - device may be gone
                log.exception("async pipeline salvage flush failed")
            for s, _lane in dec_rows:
                if not s.done and s.seq_id in eng.sequences:
                    plan = ffwd_plan.get(s.seq_id)
                    booked = 1 if plan is None else 1 + len(plan[1])
                    eng.alloc.truncate(
                        s.seq_id, eng.alloc.length(s.seq_id) - booked
                    )
            for sid, *_ in chk_rows:
                eng._drop_admission(sid)
            self._prev_lane = {}
            self._prev_emitted = set()
            raise
        eng._mixed_gap_stamp = time.perf_counter()
        perf.record_metric(
            "engine.mixed_dispatch", (eng._mixed_gap_stamp - t_disp) * 1e3,
            "ms",
        )
        n_prefill = int(sum(c for _s, _l, _d, c, _f in chk_rows))
        if n_prefill:
            perf.record_metric("engine.prefill_tokens", n_prefill, "tok")
            obs.PREFILL_TOKENS.inc(n_prefill)
        from .decode_loop import record_async_dispatch

        from ..obs.attribution import prefill_attn_positions

        # Decode-lane composition sums use each row's true q (1 for a
        # plain lane, 1 + run length for a fast-forward append); with no
        # ffwd rows they reduce to the previous one-token formulas.
        dec_q = int(sum(int(qlens[lane]) for _s, lane in dec_rows))
        dec_ctx = int(sum(
            int(starts[lane]) + int(qlens[lane]) for _s, lane in dec_rows
        ))
        record_async_dispatch(
            decode_rows=len(dec_rows),
            prefill_tokens=n_prefill,
            budget=cfg.max_step_tokens,
            depth=len(self._pending) + 1,
            attr=getattr(eng, "attr", None),
            attr_kw=dict(
                q_tokens=dec_q + n_prefill,
                kv_read_tokens=dec_ctx + int(sum(
                    d + c for _sid, _l, d, c, _f in chk_rows
                )),
                kv_write_tokens=dec_q + n_prefill,
                attn_q_ctx=int(sum(
                    prefill_attn_positions(
                        int(starts[lane]), int(qlens[lane])
                    )
                    for _s, lane in dec_rows
                )) + int(sum(
                    prefill_attn_positions(d, c)
                    for _sid, _l, d, c, _f in chk_rows
                )),
            ),
        )
        n_forced = int(sum(len(p[1]) for p in ffwd_plan.values()))
        if ffwd_plan:
            from .decode_loop import record_ffwd_append

            for s, _lane in dec_rows:
                plan = ffwd_plan.get(s.seq_id)
                if plan is not None:
                    record_ffwd_append(
                        s.seq_id, len(plan[1]),
                        attr=getattr(eng, "attr", None),
                        request_id=obs.flight.request_id_of(s.trace),
                    )
        self._tick_id += 1
        obs.flight.record(
            "dispatch", op="mixed",
            decode_seq_ids=[s.seq_id for s, _ in dec_rows],
            prefill_seq_ids=[sid for sid, *_ in chk_rows],
            bucket=int(S), prefill_tokens=n_prefill,
            forced_tokens=n_forced,
            budget=cfg.max_step_tokens,
            tick=self._tick_id, pipeline_pos=len(self._pending),
        )
        # Book-keeping AFTER the dispatch succeeded: planned prefill
        # progress advances (the write is enqueued — deterministic), the
        # finishing set gains this tick's completing prompts, and every
        # emitting row carries one more uncommitted token.
        for sid, _lane, done, c, finishing in chk_rows:
            eng._prefilling[sid] = done + c
            if finishing:
                self._finishing.add(sid)
                self._inflight_toks[sid] = (
                    self._inflight_toks.get(sid, 0) + 1
                )
        self._prev_lane = {}
        self._prev_emitted = set()
        for s, lane in dec_rows:
            self._prev_lane[s.seq_id] = lane
            self._prev_emitted.add(s.seq_id)
            plan = ffwd_plan.get(s.seq_id)
            self._inflight_toks[s.seq_id] = (
                self._inflight_toks.get(s.seq_id, 0) + 1
                + (0 if plan is None else len(plan[1]))
            )
        for sid, lane, _done, _c, finishing in chk_rows:
            self._prev_lane[sid] = lane
            if finishing:
                self._prev_emitted.add(sid)
        self._pending.append(_Tick(
            toks_d=toks_d,
            decode=[(s.seq_id, lane) for s, lane in dec_rows],
            chunks=chk_rows,
            ffwd={sid: plan[1] for sid, plan in ffwd_plan.items()},
            t_disp=t_disp,
            tick_id=self._tick_id,
        ))
        return True

    # -- commit phase --------------------------------------------------------
    def _dec_inflight(self, sid: int) -> None:
        left = self._inflight_toks.get(sid, 0) - 1
        if left > 0:
            self._inflight_toks[sid] = left
        else:
            self._inflight_toks.pop(sid, None)

    def _commit_oldest(self) -> None:
        eng = self.eng
        tick = self._pending.popleft()
        perf = get_perf_stats()
        overlapped = bool(self._pending)
        t0 = time.perf_counter()
        sampled = np.asarray(tick.toks_d)
        perf.record_metric(
            "engine.async_pull", (time.perf_counter() - t0) * 1e3, "ms"
        )
        decode_out, prefill_out = self._results
        produced = 0
        for sid, lane in tick.decode:
            pre = tick.ffwd.get(sid, [])
            n_toks = 1 + len(pre)
            for _ in range(n_toks):
                self._dec_inflight(sid)
            s = eng.sequences.get(sid)
            if s is None or s.done:
                # Stop/EOS detection lagged a tick: this row finished at
                # an earlier commit (or was dropped) while this dispatch
                # was in flight. Its tokens are discarded; the page
                # booking was already rolled back by the done-path
                # truncate.
                obs.ASYNC_OVERSHOOT_TOKENS.inc(n_toks)
                continue
            dspan = s.decode_span
            accepted = 0
            # Fast-forward pre-accepts land first (they precede the
            # sampled token in the append), so the stop-string/EOS scan
            # runs over the run in order and a mid-run stop discards the
            # tail as overshoot.
            for tok in list(pre) + [int(sampled[lane])]:
                if s.done:
                    obs.ASYNC_OVERSHOOT_TOKENS.inc()
                    continue
                try:
                    eng._accept_token(s, tok)
                except Exception:  # noqa: BLE001 - raising stream callback
                    # Row-local isolation without propagation, exactly
                    # like step_mixed: the reap path surfaces "error";
                    # raising here would lose the same tick's other rows.
                    s.done = True
                    s.finish_reason = s.finish_reason or "error"
                decode_out.setdefault(sid, []).append(tok)
                accepted += 1
            produced += accepted
            if dspan is not None:
                dspan.child(
                    "ffwd_step" if pre else "mixed_step",
                    tick.t_disp, time.perf_counter(), tokens=accepted,
                )
            if s.done:
                # Roll bookings (including any still-in-flight lookahead
                # tokens') back to written content; later stale writes
                # land harmlessly before any new owner's (dispatch order).
                eng.alloc.truncate(sid, eng._host_written(s))
        for sid, lane, done, c, finishing in tick.chunks:
            seq = eng.sequences.get(sid)
            if seq is None:
                # Dropped by a failure path while this tick was in flight.
                if finishing:
                    self._finishing.discard(sid)
                    self._dec_inflight(sid)
                continue
            if not finishing:
                prefill_out[sid] = False
                continue
            # Finishing chunk: the prompt's first sampled token.
            self._finishing.discard(sid)
            self._dec_inflight(sid)
            eng._prefilling.pop(sid, None)
            token = int(sampled[lane])
            seq.ttft_s = time.perf_counter() - seq.started_s
            perf.record_metric("engine.ttft", seq.ttft_s * 1e3, "ms")
            eng._first_token_obs(seq)
            try:
                eng._accept_token(seq, token)
            except Exception as e:  # noqa: BLE001 - stream callback
                eng._drop_admission(sid)
                prefill_out[sid] = e
                continue
            prefill_out[sid] = True
            if seq.done:
                eng.alloc.truncate(sid, eng._host_written(seq))
        if produced:
            perf.record_metric("engine.decode_tokens", produced, "tok")
        from .decode_loop import record_async_commit

        record_async_commit(overlapped, len(self._pending))
        eng._observe_occupancy()
