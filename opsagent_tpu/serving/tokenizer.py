"""Tokenizers for the serving engine.

Two implementations behind one interface:

- ``ByteTokenizer``: hermetic UTF-8 byte-level tokenizer (vocab 256 + special
  tokens). Used by tests and random-weight benchmarks — no downloaded
  artifacts, fully deterministic.
- ``HFTokenizer``: wraps a local HuggingFace tokenizer directory for real
  checkpoints (Llama-3 / Qwen2.5 / DeepSeek vocabularies).
"""

from __future__ import annotations

from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...
    def token_str(self, token_id: int) -> str: ...
    def token_bytes(self, token_id: int) -> bytes: ...


class ByteTokenizer:
    """UTF-8 bytes + specials. ids 0..255 = bytes; 256=PAD, 257=BOS, 258=EOS,
    259..262 = chat-structure markers."""

    PAD, BOS, EOS = 256, 257, 258
    SYS, USER, ASSISTANT, END = 259, 260, 261, 262

    def __init__(self, vocab_size: int = 512):
        assert vocab_size >= 263
        self.vocab_size = vocab_size
        self.pad_id = self.PAD
        self.bos_id = self.BOS
        self.eos_id = self.EOS

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def token_str(self, token_id: int) -> str:
        if 0 <= token_id < 256:
            return chr(token_id) if token_id < 128 else ""
        return ""

    def token_bytes(self, token_id: int) -> bytes:
        """Raw bytes this token contributes to the output stream (empty for
        special tokens — they never satisfy a constrained-decoding FSM)."""
        if 0 <= token_id < 256:
            return bytes([token_id])
        return b""


class HFTokenizer:
    """Local HuggingFace tokenizer wrapper (no network access)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.bos_id = self._tok.bos_token_id or 0
        self.eos_id = self._tok.eos_token_id or 0
        self.pad_id = (
            self._tok.pad_token_id
            if self._tok.pad_token_id is not None
            else self.eos_id
        )
        self._special_ids: set[int] | None = None  # filled on first use

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def token_str(self, token_id: int) -> str:
        return self._tok.convert_ids_to_tokens(token_id) or ""

    def token_bytes(self, token_id: int) -> bytes:
        """Raw bytes of one token, undoing byte-level-BPE's printable-char
        remapping (the GPT-2 byte encoder used by Llama-3/Qwen/DeepSeek
        vocabularies). Special tokens map to b""."""
        if self._special_ids is None:
            self._special_ids = set(self._tok.all_special_ids)
        if token_id in self._special_ids:
            return b""
        tok = self._tok.convert_ids_to_tokens(token_id)
        if tok is None:
            return b""
        dec = _byte_decoder()
        if all(c in dec for c in tok):
            return bytes(dec[c] for c in tok)
        # SentencePiece-style vocab: '<0xNN>' byte-fallback tokens ARE the
        # byte they name; '▁' marks a leading space.
        if len(tok) == 6 and tok.startswith("<0x") and tok.endswith(">"):
            try:
                return bytes([int(tok[3:5], 16)])
            except ValueError:
                pass
        return tok.replace("▁", " ").encode("utf-8")

    @property
    def hf(self):  # escape hatch for chat templates
        return self._tok


_BYTE_DECODER: dict[str, int] | None = None


def _byte_decoder() -> dict[str, int]:
    """char -> byte map inverting the GPT-2 byte-to-unicode encoder."""
    global _BYTE_DECODER
    if _BYTE_DECODER is None:
        bs = (
            list(range(ord("!"), ord("~") + 1))
            + list(range(0xA1, 0xAD))
            + list(range(0xAE, 0x100))
        )
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _BYTE_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTE_DECODER


def load_tokenizer(path: str = "", vocab_size: int = 512) -> Tokenizer:
    if path:
        return HFTokenizer(path)
    return ByteTokenizer(vocab_size=vocab_size)
