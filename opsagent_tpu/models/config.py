"""Model family configurations.

The serving engine hosts open-weights instruct models in the llama
architecture family (Llama-3, Qwen2.5 — GQA + RoPE + SwiGLU + RMSNorm) and,
via ``moe``, DeepSeek-style mixture-of-experts variants. Presets carry the
published architecture hyperparameters; weights are loaded from safetensors
checkpoints or randomly initialized (tests/benchmarks).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    num_experts_per_token: int = 2
    num_shared_experts: int = 0
    expert_intermediate_size: int = 0  # 0 = use model intermediate_size
    # Router combine-weight semantics, matching the HF config fields of the
    # same names. DeepSeek-MoE-16B / V2-Lite / Qwen2-MoE checkpoints ship
    # norm_topk_prob=false (combine with raw softmax probabilities);
    # renormalizing for them scales expert outputs by 1/sum(top-k probs)
    # (~1.5-3x at k=6 of 64) and corrupts generation. DeepSeek-V3 ships
    # norm_topk_prob=true with routed_scaling_factor=2.5.
    norm_topk_prob: bool = False
    routed_scaling_factor: float = 1.0
    # Router scoring (HF scoring_func): "softmax" (DeepSeek-MoE/V2) or
    # "sigmoid" (V3). Sigmoid scoring pairs with the noaux_tc topk_method:
    # a per-expert e_score_correction_bias (loaded from the checkpoint)
    # is added for SELECTION only; combine weights use the uncorrected
    # sigmoid scores.
    scoring_func: str = "softmax"
    # Group-limited top-k (HF n_group/topk_group): experts partition into
    # n_group groups; only the topk_group best groups are eligible.
    # Group ranking follows scoring_func: sigmoid (V3 noaux_tc) ranks by
    # top-2 sum, softmax (V2 group_limited_greedy) by group max. 1/1
    # disables.
    n_group: int = 1
    topk_group: int = 1
    # Grouped-dispatch policy. Below the token threshold (decode steps,
    # tiny batches) the all-experts scan runs instead: with T*k >= E every
    # expert's weights stream from HBM once either way, so the scan is
    # bandwidth-optimal and has no drop risk. Above it (prefill/training)
    # tokens are dispatched into per-expert capacity buckets of
    # ceil(T*k/E * capacity_factor) slots — expert FLOPs scale with top-k,
    # not num_experts. 0 disables grouped dispatch entirely.
    grouped_dispatch_min_tokens: int = 512
    capacity_factor: float = 2.0


@dataclass(frozen=True)
class MLAConfig:
    """Multi-head Latent Attention (DeepSeek-V2/V3): low-rank q and kv
    projections with a decoupled per-head-SHARED RoPE part. Two serving
    layouts: ``latent_cache=False`` materializes per-head k/v onto the
    standard paged cache (v zero-padded to the qk head dim so every
    attention path is shared); ``latent_cache=True`` stores the compressed
    per-token latent and decodes in the weight-absorbed MQA form — the
    memory/bandwidth win that motivates MLA."""

    q_lora_rank: int = 0           # 0 = full-rank q projection (V2-Lite)
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # Serve with the COMPRESSED latent cache: pages hold one
    # (kv_lora_rank + qk_rope_head_dim)-dim latent per token instead of
    # per-head k/v — the point of MLA (V3: 576 vs 49152 floats/token,
    # ~85x less KV memory/bandwidth). Decode absorbs the kv
    # up-projection into the query (per-head latent queries, MQA-style
    # attention over the shared latent); prefill attends materialized
    # and writes latents. False = uncompressed per-head cache.
    latent_cache: bool = False

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def latent_dim(self) -> int:
        return self.kv_lora_rank + self.qk_rope_head_dim


@dataclass(frozen=True)
class RopeScalingConfig:
    """Long-context rope frequency scaling (ops/rope.py implements the
    math). ``rope_type``: "llama3" (Llama-3.1's wavelength-banded
    interpolation) or "yarn" (DeepSeek-V2/V3; NTK-by-parts with mscale)."""

    rope_type: str
    factor: float
    original_max_position: int
    # llama3
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    # yarn
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    mscale: float = 1.0
    mscale_all_dim: float = 0.0


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int = 0  # 0 = hidden_size // num_heads
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    attn_bias: bool = False          # Qwen2-style q/k/v biases
    # Qwen3-style per-head RMSNorm on q and k (over head_dim, learned
    # [head_dim] weights, applied before RoPE).
    qk_norm: bool = False
    tie_embeddings: bool = False
    max_position: int = 131072
    moe: Optional[MoEConfig] = None
    moe_layer_start: int = 0         # dense layers before the first MoE layer
    # DeepSeek-V2/V3 Multi-head Latent Attention. Constraints (validated
    # by models.llama.init_params): head_dim == mla.qk_head_dim and
    # num_kv_heads == num_heads (MLA has no GQA; the latent IS the
    # compression).
    mla: Optional[MLAConfig] = None
    # Long-context rope scaling; when set, max_position may cover the
    # scaled window (factor x original_max_position).
    rope_scaling: Optional[RopeScalingConfig] = None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def rope_dim_(self) -> int:
        """Dims RoPE rotates: the decoupled rope part under MLA, the whole
        head otherwise."""
        return self.mla.qk_rope_head_dim if self.mla else self.head_dim_

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim_

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim_

    def num_params(self) -> int:
        """Approximate parameter count (dense layers)."""
        d, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        per_layer = (
            d * self.q_size + 2 * d * self.kv_size + self.q_size * d  # attn
            + 3 * d * f                                               # mlp
            + 2 * d                                                   # norms
        )
        embed = v * d * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + embed + d


PRESETS: dict[str, ModelConfig] = {}


def _register(cfg: ModelConfig) -> ModelConfig:
    PRESETS[cfg.name] = cfg
    return cfg


# -- test/bench models ------------------------------------------------------
TINY_TEST = _register(
    ModelConfig(
        name="tiny-test",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        rope_theta=10000.0,
        # Generous window: the byte tokenizer spends ~3k tokens on the
        # agent system prompt, and admission now ENFORCES max_position.
        max_position=16384,
    )
)

# ~1B-class model for single-chip benchmarking (fits v5e 16GB in bf16 with
# room for KV pages).
BENCH_1B = _register(
    ModelConfig(
        name="bench-1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500000.0,
    )
)

# ~3B-class single-chip bench model.
BENCH_3B = _register(
    ModelConfig(
        name="bench-3b",
        vocab_size=128256,
        hidden_size=3072,
        intermediate_size=8192,
        num_layers=28,
        num_heads=24,
        num_kv_heads=8,
        rope_theta=500000.0,
    )
)

# 8B-class bench model: exactly the Llama-3-8B architecture (the BASELINE
# north-star class). In bf16 its 16 GB of weights do NOT fit one 16 GB v5e
# chip — bench.py serves it with weight-only int8 (models.quant), 8 GB.
BENCH_8B = _register(
    ModelConfig(
        name="bench-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500000.0,
        max_position=8192,
    )
)

# -- production model families (published architecture hyperparameters) -----
LLAMA3_8B = _register(
    ModelConfig(
        name="llama-3-8b-instruct",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500000.0,
        max_position=8192,
    )
)

LLAMA31_70B = _register(
    ModelConfig(
        name="llama-3.1-70b-instruct",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        rope_theta=500000.0,
        # 128k window via Llama-3.1's wavelength-banded rope scaling
        # (HF rope_scaling rope_type=llama3, factor 8 over the 8k
        # original window).
        rope_scaling=RopeScalingConfig(
            rope_type="llama3",
            factor=8.0,
            original_max_position=8192,
            low_freq_factor=1.0,
            high_freq_factor=4.0,
        ),
    )
)

QWEN25_7B = _register(
    ModelConfig(
        name="qwen2.5-7b-instruct",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        rope_theta=1000000.0,
        attn_bias=True,
        rms_norm_eps=1e-6,
        # Native window per the HF config (YaRN x4 to 128k is an opt-in
        # config edit upstream; add rope_scaling here to enable it).
        max_position=32768,
    )
)

QWEN25_72B = _register(
    ModelConfig(
        name="qwen2.5-72b-instruct",
        vocab_size=152064,
        hidden_size=8192,
        intermediate_size=29568,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        rope_theta=1000000.0,
        attn_bias=True,
        rms_norm_eps=1e-6,
        # Native window per the HF config (YaRN x4 to 128k is an opt-in
        # config edit upstream; add rope_scaling here to enable it).
        max_position=32768,
    )
)

# DeepSeek-MoE-16B (GQA + MoE; the pre-MLA DeepSeek generation).
DEEPSEEK_MOE_16B = _register(
    ModelConfig(
        name="deepseek-moe-16b",
        vocab_size=102400,
        hidden_size=2048,
        intermediate_size=10944,
        num_layers=28,
        num_heads=16,
        num_kv_heads=16,
        rope_theta=10000.0,
        moe=MoEConfig(
            num_experts=64,
            num_experts_per_token=6,
            num_shared_experts=2,
            expert_intermediate_size=1408,
        ),
        moe_layer_start=1,
    )
)

# DeepSeek-V2-Lite (16B-class MLA + MoE; HF deepseek_v2 arch): full-rank
# q (q_lora_rank null in the HF config), compressed kv. Reference HF
# config fields mirrored 1:1.
DEEPSEEK_V2_LITE = _register(
    ModelConfig(
        name="deepseek-v2-lite",
        vocab_size=102400,
        hidden_size=2048,
        intermediate_size=10944,
        num_layers=27,
        num_heads=16,
        num_kv_heads=16,
        head_dim=192,               # qk_nope (128) + qk_rope (64)
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        # 160k window via YaRN (factor 40 over the 4k native window),
        # per the HF config's rope_scaling block.
        max_position=163840,
        rope_scaling=RopeScalingConfig(
            rope_type="yarn",
            factor=40.0,
            original_max_position=4096,
            beta_fast=32.0,
            beta_slow=1.0,
            mscale=0.707,
            mscale_all_dim=0.707,
        ),
        moe=MoEConfig(
            num_experts=64,
            num_experts_per_token=6,
            num_shared_experts=2,
            expert_intermediate_size=1408,
        ),
        moe_layer_start=1,
        mla=MLAConfig(
            q_lora_rank=0,
            kv_lora_rank=512,
            qk_nope_head_dim=128,
            qk_rope_head_dim=64,
            v_head_dim=128,
            latent_cache=True,
        ),
    )
)

# DeepSeek-V3 (671B total / 37B active; BASELINE config 3): MLA with
# low-rank q, 256 routed experts top-8 + 1 shared, SIGMOID router scoring
# with the noaux_tc selection bias and group-limited top-k (n_group 8,
# topk_group 4), norm_topk_prob and routed scaling 2.5 per the HF config.
DEEPSEEK_V3 = _register(
    ModelConfig(
        name="deepseek-v3",
        vocab_size=129280,
        hidden_size=7168,
        intermediate_size=18432,
        num_layers=61,
        num_heads=128,
        num_kv_heads=128,
        head_dim=192,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        # 160k via YaRN (factor 40, mscale 1.0 both) per the HF config.
        max_position=163840,
        rope_scaling=RopeScalingConfig(
            rope_type="yarn",
            factor=40.0,
            original_max_position=4096,
            beta_fast=32.0,
            beta_slow=1.0,
            mscale=1.0,
            mscale_all_dim=1.0,
        ),
        moe=MoEConfig(
            num_experts=256,
            num_experts_per_token=8,
            num_shared_experts=1,
            expert_intermediate_size=2048,
            norm_topk_prob=True,
            routed_scaling_factor=2.5,
            scoring_func="sigmoid",
            n_group=8,
            topk_group=4,
        ),
        moe_layer_start=3,
        mla=MLAConfig(
            q_lora_rank=1536,
            kv_lora_rank=512,
            qk_nope_head_dim=128,
            qk_rope_head_dim=64,
            v_head_dim=128,
            latent_cache=True,
        ),
    )
)

TINY_MLA = _register(
    ModelConfig(
        name="tiny-mla",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=4,
        head_dim=24,                # 16 nope + 8 rope
        rope_theta=10000.0,
        max_position=2048,
        mla=MLAConfig(
            q_lora_rank=32,
            kv_lora_rank=32,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=16,
        ),
    )
)

TINY_MOE = _register(
    ModelConfig(
        name="tiny-moe",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        rope_theta=10000.0,
        max_position=2048,
        moe=MoEConfig(
            num_experts=4,
            num_experts_per_token=2,
            num_shared_experts=1,
            expert_intermediate_size=64,
        ),
        moe_layer_start=1,
    )
)


def get_config_preset(name: str) -> ModelConfig:
    if name in PRESETS:
        return PRESETS[name]
    raise KeyError(f"unknown model preset '{name}' (have: {sorted(PRESETS)})")


def config_from_hf(path: str, name: str = "") -> ModelConfig:
    """Derive a ModelConfig from an HF checkpoint dir's ``config.json``
    (model_type ``llama`` / ``mistral`` (non-sliding-window releases) /
    ``qwen2`` / ``deepseek`` / ``deepseek_v2`` / ``deepseek_v3`` — the
    dense, MoE, and MLA families this engine serves), so ANY such HF
    checkpoint directory is servable without a hand-written preset.
    The reference needs no model configs at all —
    its "model" is a remote API (reference pkg/llms/openai.go:69); here
    the checkpoint's own metadata is the source of truth. ``path`` may
    be the dir or the json file."""
    import json
    import os

    cfg_path = (
        os.path.join(path, "config.json") if os.path.isdir(path) else path
    )
    with open(cfg_path, encoding="utf-8") as f:
        hf = json.load(f)
    mt = hf.get("model_type", "llama")
    if mt not in ("llama", "mistral", "qwen2", "qwen3", "qwen3_moe",
                  "deepseek", "deepseek_v2", "deepseek_v3"):
        raise ValueError(
            f"config_from_hf supports model_type llama/mistral/qwen2/"
            f"qwen3/qwen3_moe/deepseek/deepseek_v2/deepseek_v3, got {mt!r}"
        )
    # Sliding-window attention is not implemented; a config that would
    # ACTIVELY use it must be rejected loudly, never silently served
    # with full attention. Mistral (llama-shaped otherwise: same weight
    # names, GQA, silu, RMSNorm): active when sliding_window is non-null
    # and below the position window — only v0.1-class checkpoints;
    # v0.2+/Nemo/Small ship sliding_window: null. Qwen2 carries the same
    # fields but gates them with use_sliding_window (shipped Qwen2.5
    # releases set it false).
    sw = hf.get("sliding_window")
    sw_active = sw is not None and int(sw) < int(
        hf.get("max_position_embeddings", 8192)
    )
    if mt in ("qwen2", "qwen3", "qwen3_moe"):
        sw_active = sw_active and bool(hf.get("use_sliding_window", False))
    if sw_active and not mt.startswith("deepseek"):
        raise ValueError(
            f"checkpoint uses ACTIVE sliding-window attention "
            f"(sliding_window={sw}); this engine serves full paged "
            f"attention only — use a release with the window disabled "
            f"(sliding_window: null)"
        )
    moe = None
    mla = None
    moe_layer_start = 0
    if mt == "qwen3_moe":
        # Qwen3-MoE uses the deepseek WEIGHT naming (mlp.gate router,
        # mlp.experts.N.*_proj) with its own CONFIG key names and
        # softmax-then-topk routing, no shared experts. Interleaved
        # dense layers (decoder_sparse_step != 1 or mlp_only_layers)
        # are not the contiguous dense-prefix layout this engine's
        # stacked tree supports — reject rather than mis-route.
        if hf.get("mlp_only_layers"):
            raise ValueError(
                "qwen3_moe mlp_only_layers interleaving is not supported"
            )
        if int(hf.get("decoder_sparse_step", 1) or 1) != 1:
            raise ValueError(
                "only decoder_sparse_step=1 (every layer MoE) is supported"
            )
        moe = MoEConfig(
            num_experts=int(hf["num_experts"]),
            num_experts_per_token=int(hf["num_experts_per_tok"]),
            num_shared_experts=0,
            expert_intermediate_size=int(hf["moe_intermediate_size"]),
            norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
            routed_scaling_factor=1.0,
            scoring_func="softmax",
            n_group=1,
            topk_group=1,
        )
    if mt.startswith("deepseek"):
        if int(hf.get("moe_layer_freq", 1)) != 1:
            raise ValueError(
                "only moe_layer_freq=1 (contiguous MoE stack after the "
                "dense prefix) is supported"
            )
        if hf.get("n_routed_experts"):
            scoring = hf.get("scoring_func", "softmax")
            if scoring not in ("softmax", "sigmoid"):
                # Reject rather than silently routing with softmax
                # semantics (models.llama falls back to softmax for
                # unknown scoring functions).
                raise ValueError(
                    f"unsupported router scoring_func {scoring!r}"
                )
            moe = MoEConfig(
                num_experts=int(hf["n_routed_experts"]),
                num_experts_per_token=int(hf["num_experts_per_tok"]),
                num_shared_experts=int(hf.get("n_shared_experts", 0) or 0),
                expert_intermediate_size=int(
                    hf.get("moe_intermediate_size", 0) or 0
                ),
                norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
                routed_scaling_factor=float(
                    hf.get("routed_scaling_factor", 1.0)
                ),
                scoring_func=scoring,
                n_group=int(hf.get("n_group", 1) or 1),
                topk_group=int(hf.get("topk_group", 1) or 1),
            )
            moe_layer_start = int(hf.get("first_k_dense_replace", 0))
        if mt in ("deepseek_v2", "deepseek_v3"):
            mla = MLAConfig(
                q_lora_rank=int(hf.get("q_lora_rank") or 0),
                kv_lora_rank=int(hf["kv_lora_rank"]),
                qk_nope_head_dim=int(hf["qk_nope_head_dim"]),
                qk_rope_head_dim=int(hf["qk_rope_head_dim"]),
                v_head_dim=int(hf["v_head_dim"]),
                # Serve V2/V3 with the compressed latent pages — the
                # whole point of MLA (engine latent-cache path).
                latent_cache=True,
            )
    rs = None
    hf_rs = hf.get("rope_scaling") or None
    if hf_rs:
        rt = hf_rs.get("rope_type") or hf_rs.get("type")
        if rt == "llama3":
            rs = RopeScalingConfig(
                rope_type="llama3",
                factor=float(hf_rs["factor"]),
                original_max_position=int(
                    hf_rs["original_max_position_embeddings"]
                ),
                low_freq_factor=float(hf_rs.get("low_freq_factor", 1.0)),
                high_freq_factor=float(hf_rs.get("high_freq_factor", 4.0)),
            )
        elif rt == "yarn":
            rs = RopeScalingConfig(
                rope_type="yarn",
                factor=float(hf_rs["factor"]),
                original_max_position=int(
                    hf_rs["original_max_position_embeddings"]
                ),
                beta_fast=float(hf_rs.get("beta_fast", 32.0)),
                beta_slow=float(hf_rs.get("beta_slow", 1.0)),
                mscale=float(hf_rs.get("mscale", 1.0)),
                mscale_all_dim=float(hf_rs.get("mscale_all_dim", 0.0)),
            )
        else:
            raise ValueError(f"unsupported rope_scaling type {rt!r}")
    heads = int(hf["num_attention_heads"])
    return ModelConfig(
        name=name or os.path.basename(os.path.normpath(
            path if os.path.isdir(path) else os.path.dirname(cfg_path)
        )) or mt,
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        num_heads=heads,
        # MLA has no GQA: the latent is the compression.
        num_kv_heads=heads if mla else int(
            hf.get("num_key_value_heads", heads)
        ),
        head_dim=mla.qk_head_dim if mla else int(hf.get("head_dim") or 0),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        # Qwen2 checkpoints carry q/k/v biases without an explicit flag;
        # Qwen3 dropped the biases for per-head q/k RMSNorm instead.
        attn_bias=(mt == "qwen2") or bool(hf.get("attention_bias", False)),
        qk_norm=mt in ("qwen3", "qwen3_moe"),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        max_position=int(hf.get("max_position_embeddings", 8192)),
        moe=moe,
        moe_layer_start=moe_layer_start,
        mla=mla,
        rope_scaling=rs,
    )


def resolve_model(
    model_name: str, checkpoint: str = ""
) -> tuple[str, Optional[ModelConfig]]:
    """Resolve a --model-name flag to ``(name, model_cfg_or_None)``.

    A preset name passes through with ``None`` (the engine resolves the
    preset itself); ``auto`` derives the architecture from the checkpoint
    dir's ``config.json`` via ``config_from_hf``. In auto mode the
    checkpoint's own metadata is AUTHORITATIVE — even when the dir's
    basename collides with a preset name (a renamed snapshot or a
    fine-tune with different dims must serve with ITS config, not the
    preset's). One shared policy for serve-engine and
    scripts/run_real_checkpoint.py."""
    if model_name != "auto":
        return model_name, None
    if not checkpoint:
        raise ValueError("--model-name auto requires --checkpoint")
    cfg = config_from_hf(checkpoint)
    return cfg.name, cfg


def hf_config_dict(cfg: ModelConfig) -> dict:
    """``config.json`` contents for a ModelConfig — the inverse of
    ``config_from_hf`` (checkpoint export). Dense configs emit
    llama/qwen2; qk_norm configs emit qwen3 (or qwen3_moe when paired
    with a plain softmax MoE); other MoE and/or MLA configs emit the
    deepseek family (deepseek_v2/v3 when MLA is present, deepseek
    otherwise)."""
    qwen3_moe = (
        cfg.qk_norm and cfg.moe is not None and cfg.mla is None
        and cfg.moe.scoring_func == "softmax"
        and not cfg.moe.num_shared_experts
        and cfg.moe.routed_scaling_factor == 1.0
        and cfg.moe.n_group <= 1 and cfg.moe_layer_start == 0
    )
    if cfg.qk_norm and (cfg.moe or cfg.mla) and not qwen3_moe:
        # QK-norm is only expressible in the qwen3/qwen3_moe families; a
        # silent deepseek export would drop qk_norm and desync the
        # reloaded tree from the saved qn/kn weights.
        raise ValueError(
            "hf_config_dict cannot express qk_norm together with this "
            "moe/mla configuration"
        )
    if qwen3_moe:
        mt = "qwen3_moe"
    elif cfg.mla:
        mt = ("deepseek_v3" if cfg.moe and cfg.moe.scoring_func == "sigmoid"
              else "deepseek_v2")
    elif cfg.moe:
        mt = "deepseek"
    elif cfg.qk_norm:
        mt = "qwen3"
    else:
        mt = "qwen2" if cfg.attn_bias else "llama"
    archs = {
        "llama": "LlamaForCausalLM",
        "qwen2": "Qwen2ForCausalLM",
        "qwen3": "Qwen3ForCausalLM",
        "qwen3_moe": "Qwen3MoeForCausalLM",
        "deepseek": "DeepseekForCausalLM",
        "deepseek_v2": "DeepseekV2ForCausalLM",
        "deepseek_v3": "DeepseekV3ForCausalLM",
    }
    hf: dict = {
        "model_type": mt,
        "architectures": [archs[mt]],
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "max_position_embeddings": cfg.max_position,
        # Explicit so non-qwen2 model_types (the deepseek family) cannot
        # silently drop q/k/v biases on a roundtrip.
        "attention_bias": cfg.attn_bias,
    }
    if cfg.head_dim:
        hf["head_dim"] = cfg.head_dim
    if cfg.rope_scaling:
        rs = cfg.rope_scaling
        if rs.rope_type == "llama3":
            hf["rope_scaling"] = {
                "rope_type": "llama3",
                "factor": rs.factor,
                "original_max_position_embeddings": rs.original_max_position,
                "low_freq_factor": rs.low_freq_factor,
                "high_freq_factor": rs.high_freq_factor,
            }
        else:
            hf["rope_scaling"] = {
                "rope_type": rs.rope_type,
                "factor": rs.factor,
                "original_max_position_embeddings": rs.original_max_position,
                "beta_fast": rs.beta_fast,
                "beta_slow": rs.beta_slow,
                "mscale": rs.mscale,
                "mscale_all_dim": rs.mscale_all_dim,
            }
    if cfg.moe and qwen3_moe:
        m = cfg.moe
        hf.update({
            "num_experts": m.num_experts,
            "num_experts_per_tok": m.num_experts_per_token,
            "moe_intermediate_size": m.expert_intermediate_size,
            "norm_topk_prob": m.norm_topk_prob,
            "decoder_sparse_step": 1,
            "mlp_only_layers": [],
        })
    elif cfg.moe:
        m = cfg.moe
        hf.update({
            "n_routed_experts": m.num_experts,
            "num_experts_per_tok": m.num_experts_per_token,
            "n_shared_experts": m.num_shared_experts,
            "moe_intermediate_size": m.expert_intermediate_size,
            "first_k_dense_replace": cfg.moe_layer_start,
            "moe_layer_freq": 1,
            "norm_topk_prob": m.norm_topk_prob,
            "routed_scaling_factor": m.routed_scaling_factor,
            "scoring_func": m.scoring_func,
            "n_group": m.n_group,
            "topk_group": m.topk_group,
        })
    if cfg.mla:
        a = cfg.mla
        hf.update({
            "q_lora_rank": a.q_lora_rank or None,
            "kv_lora_rank": a.kv_lora_rank,
            "qk_nope_head_dim": a.qk_nope_head_dim,
            "qk_rope_head_dim": a.qk_rope_head_dim,
            "v_head_dim": a.v_head_dim,
        })
    return hf


def scaled_for_test(cfg: ModelConfig, vocab_size: int = 512) -> ModelConfig:
    """Shrink a preset's vocab for fast CPU tests, keeping its shape ratios."""
    return replace(cfg, vocab_size=vocab_size)
