"""Llama-family decoder (Llama-3, Qwen2.5): GQA + RoPE + SwiGLU + RMSNorm.

Functional JAX, designed for XLA/TPU:

- Parameters are a pytree of **stacked** per-layer arrays (leading dim = num
  layers) walked with ``lax.scan`` — one traced layer body instead of L
  inlined copies, which keeps 80-layer compile times sane.
- Tensor parallelism is pure sharding metadata: ``param_specs`` returns a
  matching pytree of PartitionSpecs (Megatron-style column/row splits over
  the "tp" mesh axis); XLA inserts the all-reduces at wo/wd boundaries.
- Two entry points over the same weights: ``prefill`` (causal attention over
  the fresh sequence, writes KV pages) and ``decode_step`` (one token per
  sequence, paged attention) — the two XLA programs the serving engine jits.

This whole module replaces the reference's outbound HTTPS call to a remote
LLM (reference pkg/llms/openai.go:69-103); there is no counterpart Go code.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    causal_prefill_attention,
    paged_decode_attention_auto,
    paged_prefix_attention,
    write_kv_pages,
)
from ..ops.rope import apply_rope, rope_table
from .config import ModelConfig

Params = dict[str, Any]


# -- init / specs -----------------------------------------------------------
def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random init (scaled normal). Real checkpoints come via models.loader."""
    d, f, v, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
    q, kv = cfg.q_size, cfg.kv_size
    ks = iter(jax.random.split(key, 12))

    def norm01(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    layers: Params = {
        "attn_norm": jnp.ones((L, d), dtype),
        "wq": norm01(next(ks), (L, d, q), d),
        "wk": norm01(next(ks), (L, d, kv), d),
        "wv": norm01(next(ks), (L, d, kv), d),
        "wo": norm01(next(ks), (L, q, d), q),
        "mlp_norm": jnp.ones((L, d), dtype),
        "wg": norm01(next(ks), (L, d, f), d),
        "wu": norm01(next(ks), (L, d, f), d),
        "wd": norm01(next(ks), (L, f, d), f),
    }
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, q), dtype)
        layers["bk"] = jnp.zeros((L, kv), dtype)
        layers["bv"] = jnp.zeros((L, kv), dtype)
    params: Params = {
        "embed": norm01(next(ks), (v, d), d),
        "layers": layers,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm01(next(ks), (d, v), d)
    return params


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpecs matching ``init_params``' tree (axes: ("dp","sp","tp")).

    Column-parallel: wq/wk/wv/wg/wu (output dim over tp). Row-parallel:
    wo/wd (input dim over tp, XLA all-reduces the partial sums). Embedding
    sharded over vocab; lm_head over vocab columns.
    """
    layers = {
        "attn_norm": P(None, None),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "mlp_norm": P(None, None),
        "wg": P(None, None, "tp"),
        "wu": P(None, None, "tp"),
        "wd": P(None, "tp", None),
    }
    if cfg.attn_bias:
        layers["bq"] = P(None, "tp")
        layers["bk"] = P(None, "tp")
        layers["bv"] = P(None, "tp")
    specs: Params = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def make_cache(
    cfg: ModelConfig,
    num_pages: int,
    page_size: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> Params:
    """Paged KV cache pytree: pages stacked over layers."""
    L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
    shape = (L, num_pages, page_size, K, D)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_specs(cfg: ModelConfig) -> Params:
    """KV pages are sharded over the kv-head axis (tp), like wk/wv."""
    return {
        "k": P(None, None, None, "tp", None),
        "v": P(None, None, None, "tp", None),
    }


# -- building blocks --------------------------------------------------------
def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def _qkv(
    x: jax.Array, lp: Params, cfg: ModelConfig
) -> tuple[jax.Array, jax.Array, jax.Array]:
    B, S, _ = x.shape
    K, D = cfg.num_kv_heads, cfg.head_dim_
    q = x @ lp["wq"]
    k = x @ lp["wk"]
    v = x @ lp["wv"]
    if cfg.attn_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    return (
        q.reshape(B, S, cfg.num_heads, D),
        k.reshape(B, S, K, D),
        v.reshape(B, S, K, D),
    )


def _mlp(x: jax.Array, lp: Params) -> jax.Array:
    return (jax.nn.silu(x @ lp["wg"]) * (x @ lp["wu"])) @ lp["wd"]


# -- forward passes ---------------------------------------------------------
def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, S] int32, right-padded
    lengths: jax.Array,      # [B] valid lengths
    cache: Params,           # paged cache pytree
    page_table: jax.Array,   # [B, MaxP]
    dtype: jnp.dtype = jnp.bfloat16,
) -> tuple[jax.Array, Params]:
    """Full-sequence forward; writes KV into pages; returns (logits of the
    last valid position [B, V], updated cache)."""
    B, S = tokens.shape
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    cos, sin = rope_table(positions, cfg.head_dim_, cfg.rope_theta)
    x = params["embed"][tokens].astype(dtype)
    start = jnp.zeros((B,), jnp.int32)

    def body(x, scanned):
        lp, k_pages, v_pages = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_pages, v_pages = write_kv_pages(
            k_pages, v_pages, k, v, page_table, start, valid_len=lengths
        )
        attn = causal_prefill_attention(q, k, v, lengths=lengths)
        x = x + attn.reshape(B, S, -1) @ lp["wo"]
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, lp)
        return x, (k_pages, v_pages)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.clip(lengths - 1, 0, S - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [B, D]
    logits = _lm_head(params, cfg, x_last)
    return logits, {"k": k_new, "v": v_new}


def prefill_with_prefix(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, S] int32 TAIL tokens, right-padded
    start: jax.Array,        # [B] cached-prefix lengths
    lengths: jax.Array,      # [B] valid tail lengths
    cache: Params,
    page_table: jax.Array,   # [B, MaxP] (prefix pages + fresh tail pages)
    dtype: jnp.dtype = jnp.bfloat16,
) -> tuple[jax.Array, Params]:
    """Prefix-cache admission: forward only the tail, attending over the
    sequence's cached prefix pages + the tail KV written this call. Returns
    (last-tail-position logits [B, V], updated cache). With start=0 this is
    semantically ``prefill`` (kept separate so the no-prefix program avoids
    the page gather)."""
    B, S = tokens.shape
    positions = start[:, None] + jnp.arange(S)[None, :]
    cos, sin = rope_table(positions, cfg.head_dim_, cfg.rope_theta)
    x = params["embed"][tokens].astype(dtype)

    def body(x, scanned):
        lp, k_pages, v_pages = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_pages, v_pages = write_kv_pages(
            k_pages, v_pages, k, v, page_table, start, valid_len=lengths
        )
        attn = paged_prefix_attention(
            q, k_pages, v_pages, page_table, start, lengths
        )
        x = x + attn.reshape(B, S, -1) @ lp["wo"]
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, lp)
        return x, (k_pages, v_pages)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.clip(lengths - 1, 0, S - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    logits = _lm_head(params, cfg, x_last)
    return logits, {"k": k_new, "v": v_new}


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B] int32 (the latest sampled token per seq)
    lengths: jax.Array,      # [B] tokens already in cache (write offset)
    cache: Params,
    page_table: jax.Array,   # [B, MaxP]
    active: jax.Array,       # [B] bool; inactive slots skip the page write
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",  # "xla" | "pallas" (ops.paged_attention_backend)
) -> tuple[jax.Array, Params]:
    """One decode step for a batch of sequences; returns ([B, V] logits,
    updated cache)."""
    B = tokens.shape[0]
    positions = lengths[:, None]                       # [B, 1]
    cos, sin = rope_table(positions, cfg.head_dim_, cfg.rope_theta)
    x = params["embed"][tokens[:, None]].astype(dtype)  # [B, 1, D]
    valid = active.astype(jnp.int32)                   # [B] 1 new token if active

    def body(x, scanned):
        lp, k_pages, v_pages = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_pages, v_pages = write_kv_pages(
            k_pages, v_pages, k, v, page_table, lengths, valid_len=valid
        )
        attn = paged_decode_attention_auto(
            q[:, 0], k_pages, v_pages, page_table, lengths + valid,
            impl=attn_impl,
        )
        x = x + attn.reshape(B, 1, -1) @ lp["wo"]
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, lp)
        return x, (k_pages, v_pages)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _lm_head(params, cfg, x[:, 0])
    return logits, {"k": k_new, "v": v_new}


def _lm_head(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.tie_embeddings:
        return (x @ params["embed"].T.astype(x.dtype)).astype(jnp.float32)
    return (x @ params["lm_head"]).astype(jnp.float32)


def forward_full(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,
    dtype: jnp.dtype = jnp.bfloat16,
    remat: bool = False,
) -> jax.Array:
    """All-positions logits [B, S, V] with vanilla causal attention and no
    cache — the ground-truth oracle for prefill/decode equivalence tests and
    the loss path for the training step. ``remat=True`` checkpoints the
    scanned layer body (recompute activations in backward: HBM for FLOPs)."""
    B, S = tokens.shape
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    cos, sin = rope_table(positions, cfg.head_dim_, cfg.rope_theta)
    x = params["embed"][tokens].astype(dtype)

    def body(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = causal_prefill_attention(q, k, v)
        x = x + attn.reshape(B, S, -1) @ lp["wo"]
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, lp)
        return x, None

    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return _lm_head(params, cfg, x)
