"""Llama-family decoder (Llama-3, Qwen2.5, DeepSeek-MoE): GQA + RoPE +
SwiGLU + RMSNorm, with optional mixture-of-experts MLP layers.

Functional JAX, designed for XLA/TPU:

- Parameters are a pytree of **stacked** per-layer arrays (leading dim = num
  layers) walked with ``lax.scan`` — one traced layer body instead of L
  inlined copies, which keeps 80-layer compile times sane. MoE models run
  TWO stacks back to back: the dense head (``moe_layer_start`` layers) and
  the MoE tail, each its own scan over uniform params.
- Tensor parallelism is pure sharding metadata: ``param_specs`` returns a
  matching pytree of PartitionSpecs (Megatron-style column/row splits over
  the "tp" mesh axis); XLA inserts the all-reduces at wo/wd boundaries.
  Expert weights shard their inner (intermediate) dim over tp the same way,
  so one mesh serves dense and MoE checkpoints alike.
- Entry points over the same weights: ``prefill`` (causal attention over the
  fresh sequence, writes KV pages), ``prefill_with_prefix`` (tail admission
  against cached prefix pages), ``decode_step`` (one token per sequence,
  paged attention), ``forward_full`` (all-positions oracle / training loss).

This whole module replaces the reference's outbound HTTPS call to a remote
LLM (reference pkg/llms/openai.go:69-103); there is no counterpart Go code.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    paged_ragged_attention_auto,
    causal_prefill_attention,
    paged_decode_attention_auto,
    paged_prefix_attention,
    write_kv_pages,
    write_pages,
)
from ..ops.rope import apply_rope, rope_table
from .config import ModelConfig

Params = dict[str, Any]


def _layer_split(cfg: ModelConfig) -> tuple[int, int]:
    """(dense layer count, MoE layer count)."""
    if cfg.moe is None:
        return cfg.num_layers, 0
    return cfg.moe_layer_start, cfg.num_layers - cfg.moe_layer_start


# -- init / specs -----------------------------------------------------------
def _norm01(k, shape, fan_in, dtype):
    return (
        jax.random.normal(k, shape, jnp.float32) * (fan_in ** -0.5)
    ).astype(dtype)


def _mla_attn_block(cfg: ModelConfig, L: int, ks, dtype, big) -> Params:
    """Multi-head Latent Attention projections (DeepSeek-V2/V3; HF
    modeling_deepseek naming in comments). Validated invariants: head_dim
    == qk_head_dim, no GQA. ``wo`` rows are laid out per head over the
    PADDED head dim (v is zero-padded from v_head_dim to qk_head_dim so
    the cache/attention paths stay shared); the pad rows multiply zeros,
    so their values are irrelevant — the loader zeroes them."""
    m = cfg.mla
    d = cfg.hidden_size
    H = cfg.num_heads
    dn, dr, dv = m.qk_nope_head_dim, m.qk_rope_head_dim, m.v_head_dim
    dq = dn + dr
    if cfg.head_dim_ != dq:
        raise ValueError(
            f"mla: head_dim={cfg.head_dim_} must equal qk_head_dim={dq}"
        )
    if cfg.num_kv_heads != H:
        raise ValueError("mla: num_kv_heads must equal num_heads (no GQA)")
    block: Params = {"attn_norm": jnp.ones((L, d), dtype)}
    if m.q_lora_rank:
        block["wdq"] = big(next(ks), (L, d, m.q_lora_rank), d)     # q_a_proj
        block["q_norm"] = jnp.ones((L, m.q_lora_rank), dtype)      # q_a_layernorm
        block["wuq"] = big(next(ks), (L, m.q_lora_rank, H * dq), m.q_lora_rank)  # q_b_proj
    else:
        block["wq"] = big(next(ks), (L, d, H * dq), d)             # q_proj
    rkv = m.kv_lora_rank
    block["wdkv"] = big(next(ks), (L, d, rkv), d)   # kv_a_proj_with_mqa[:rkv]
    block["wkr"] = big(next(ks), (L, d, dr), d)     # kv_a_proj_with_mqa[rkv:]
    block["kv_norm"] = jnp.ones((L, rkv), dtype)    # kv_a_layernorm
    block["wukv"] = big(next(ks), (L, rkv, H * (dn + dv)), rkv)    # kv_b_proj
    block["wo"] = big(next(ks), (L, H * dq, d), H * dv)            # o_proj, padded rows
    block["mlp_norm"] = jnp.ones((L, d), dtype)
    return block


def _build_tree(cfg: ModelConfig, ks, dtype, big, dense) -> Params:
    """THE param-tree structure, shared by every initializer so it cannot
    drift from ``param_specs``. ``big(key, shape, fan_in)`` makes the large
    matmul weights (the quantizable set); ``dense(key, shape, fan_in, dt)``
    makes the full-precision normal leaves (embed, router). Key-draw order
    is part of the contract: golden fixtures pin ``init_params`` values."""
    d, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    q, kv = cfg.q_size, cfg.kv_size
    Ld, Lm = _layer_split(cfg)

    def attn_block(L: int) -> Params:
        if cfg.mla is not None:
            return _mla_attn_block(cfg, L, ks, dtype, big)
        block: Params = {
            "attn_norm": jnp.ones((L, d), dtype),
            "wq": big(next(ks), (L, d, q), d),
            "wk": big(next(ks), (L, d, kv), d),
            "wv": big(next(ks), (L, d, kv), d),
            "wo": big(next(ks), (L, q, d), q),
            "mlp_norm": jnp.ones((L, d), dtype),
        }
        if cfg.attn_bias:
            block["bq"] = jnp.zeros((L, q), dtype)
            block["bk"] = jnp.zeros((L, kv), dtype)
            block["bv"] = jnp.zeros((L, kv), dtype)
        if cfg.qk_norm:
            # Qwen3 per-head q/k RMSNorm weights (over head_dim).
            block["qn"] = jnp.ones((L, cfg.head_dim_), dtype)
            block["kn"] = jnp.ones((L, cfg.head_dim_), dtype)
        return block

    layers = attn_block(Ld)
    layers["wg"] = big(next(ks), (Ld, d, f), d)
    layers["wu"] = big(next(ks), (Ld, d, f), d)
    layers["wd"] = big(next(ks), (Ld, f, d), f)
    params: Params = {
        "embed": dense(next(ks), (v, d), d, dtype),
        "layers": layers,
        "final_norm": jnp.ones((d,), dtype),
    }
    if Lm:
        m = cfg.moe
        fe = m.expert_intermediate_size or f
        E = m.num_experts
        moe_layers = attn_block(Lm)
        # Router stays f32: tiny, and top-k is precision-sensitive.
        moe_layers["router"] = dense(next(ks), (Lm, d, E), d, jnp.float32)
        if m.scoring_func == "sigmoid":
            # noaux_tc selection bias (zero-init; loaded from real
            # checkpoints' e_score_correction_bias).
            moe_layers["router_bias"] = jnp.zeros((Lm, E), jnp.float32)
        moe_layers["eg"] = big(next(ks), (Lm, E, d, fe), d)
        moe_layers["eu"] = big(next(ks), (Lm, E, d, fe), d)
        moe_layers["ed"] = big(next(ks), (Lm, E, fe, d), fe)
        if m.num_shared_experts:
            fs = fe * m.num_shared_experts
            moe_layers["sg"] = big(next(ks), (Lm, d, fs), d)
            moe_layers["su"] = big(next(ks), (Lm, d, fs), d)
            moe_layers["sd"] = big(next(ks), (Lm, fs, d), fs)
        params["moe_layers"] = moe_layers
    if not cfg.tie_embeddings:
        params["lm_head"] = big(next(ks), (d, v), d)
    return params


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random init (scaled normal). Real checkpoints come via models.loader."""
    ks = iter(jax.random.split(key, 32))

    def norm01(k, shape, fan_in):
        return _norm01(k, shape, fan_in, dtype)

    return _build_tree(
        cfg, ks, dtype,
        big=norm01,
        dense=lambda k, shape, fan_in, dt: _norm01(k, shape, fan_in, dt),
    )


def init_params_random_quantized(
    cfg: ModelConfig, seed: int, dtype: jnp.dtype = jnp.bfloat16,
    mode: str = "int8",
) -> Params:
    """Random weights built DIRECTLY in the quantized serving form
    (models.quant.QuantizedLinear, or QuantizedLinear4 with
    ``mode="int4"``), ON DEVICE, without ever materializing a
    full-precision tree and without any bulk host->device transfer.

    Why both constraints matter at 8B scale:
    - ``init_params`` + ``quantize_params`` needs a full-precision tree
      (16 GB bf16 + f32 intermediates) that does not fit a 16 GB v5e chip,
      and on the host backend the threefry RNG takes tens of minutes.
    - Host-side numpy generation is fast, but then 8+ GB of weights must
      cross the host->device link; on tunneled/remote-device setups that
      transfer is the bottleneck (or worse). Generating on device moves
      only PRNG keys.

    Benchmarks and smoke runs only need *plausible* weights: q is uniform
    int8 with a constant per-tensor scale chosen so the dequantized std
    matches ``init_params``' fan-in scaling (std(U[-127,127]) = 127/sqrt3).
    The whole tree is built by ONE jitted program; stacked weights are
    filled with ``lax.map`` over per-layer keys so peak transient memory
    is one layer slice, not a full-tensor wide intermediate.
    """
    from .quant import QuantizedLinear, QuantizedLinear4, pack_int4

    int4 = mode == "int4"

    def qrand(key, shape: tuple[int, ...], fan_in: int):
        lead, mat = shape[:-2], shape[-2:]

        def gen(k):
            bits = jax.random.bits(k, mat, jnp.uint8)
            if int4:
                # bits%15 in 0..14 minus 7 -> uniform int4 in [-7, 7],
                # packed two-per-int8-byte (QuantizedLinear4's storage).
                return pack_int4(bits.astype(jnp.int16) % 15 - 7)
            # bits%255 in 0..254 minus 127 -> uniform int8 in [-127, 127]
            # (the symmetric range quantize_weight produces; avoids the
            # int8-overflow trap of randint(maxval=128)).
            return (bits.astype(jnp.int16) % 255 - 127).astype(jnp.int8)

        stored = (mat[0] // 2, mat[1]) if int4 else mat
        if lead:
            n = 1
            for x in lead:
                n *= x
            q = jax.lax.map(gen, jax.random.split(key, n))
            q = q.reshape(*lead, *stored)
        else:
            q = gen(key)
        if int4:
            # ONE whole-axis scale group (random weights need no locality):
            # std(U[-7,7]) = 7/sqrt3, matched to init_params' fan-in std.
            s = float(fan_in**-0.5) * (3.0**0.5) / 7.0
            scale = jnp.full(lead + (1, 1, mat[-1]), s, jnp.float32)
            return QuantizedLinear4(q, scale)
        s = float(fan_in**-0.5) * (3.0**0.5) / 127.0
        scale = jnp.full(lead + (1, mat[-1]), s, jnp.float32)
        return QuantizedLinear(q, scale)

    def build(key) -> Params:
        ks = iter(jax.random.split(key, 32))
        return _build_tree(
            cfg, ks, dtype,
            big=qrand,
            # normal() in the target dtype directly: no f32 wide transient.
            dense=lambda k, shape, fan_in, dt: (
                jax.random.normal(k, shape, dt) * (fan_in**-0.5)
            ),
        )

    return jax.jit(build)(jax.random.PRNGKey(seed))


def _attn_block_specs(cfg: ModelConfig) -> Params:
    if cfg.mla is not None:
        # Megatron MLA: the per-head output dims of wuq/wukv are
        # column-parallel (heads shard over tp), wo is row-parallel; the
        # low-rank down-projections and the shared rope key are small and
        # replicated.
        block = {
            "attn_norm": P(None, None),
            "wdkv": P(None, None, None),
            "wkr": P(None, None, None),
            "kv_norm": P(None, None),
            "wukv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
        }
        if cfg.mla.q_lora_rank:
            block["wdq"] = P(None, None, None)
            block["q_norm"] = P(None, None)
            block["wuq"] = P(None, None, "tp")
        else:
            block["wq"] = P(None, None, "tp")
        return block
    block = {
        "attn_norm": P(None, None),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "mlp_norm": P(None, None),
    }
    if cfg.attn_bias:
        block["bq"] = P(None, "tp")
        block["bk"] = P(None, "tp")
        block["bv"] = P(None, "tp")
    if cfg.qk_norm:
        # Per-head-dim vectors: the head axis shards over tp, head_dim
        # does not — replicated.
        block["qn"] = P(None, None)
        block["kn"] = P(None, None)
    return block


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpecs matching ``init_params``' tree (axes: ("dp","sp","tp")).

    Column-parallel: wq/wk/wv/wg/wu (output dim over tp). Row-parallel:
    wo/wd (input dim over tp, XLA all-reduces the partial sums). Expert
    weights shard their EXPERT axis over ep (expert parallelism: each ep
    shard holds E/ep experts, and the grouped dispatch's per-expert
    buckets shard with them — XLA emits the token all-to-all from the
    shardings) and their intermediate dim over tp (column for eg/eu, row
    for ed) — every expert runs tensor-parallel, composing ep x tp.
    Embedding sharded over vocab; lm_head over vocab columns.
    """
    layers = _attn_block_specs(cfg)
    layers.update(
        {
            "wg": P(None, None, "tp"),
            "wu": P(None, None, "tp"),
            "wd": P(None, "tp", None),
        }
    )
    specs: Params = {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
    }
    Ld, Lm = _layer_split(cfg)
    if Lm:
        moe_layers = _attn_block_specs(cfg)
        if cfg.moe.scoring_func == "sigmoid":
            moe_layers["router_bias"] = P(None, None)
        moe_layers.update(
            {
                "router": P(None, None, None),
                "eg": P(None, "ep", None, "tp"),
                "eu": P(None, "ep", None, "tp"),
                "ed": P(None, "ep", "tp", None),
            }
        )
        if cfg.moe.num_shared_experts:
            moe_layers["sg"] = P(None, None, "tp")
            moe_layers["su"] = P(None, None, "tp")
            moe_layers["sd"] = P(None, "tp", None)
        specs["moe_layers"] = moe_layers
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def _latent_cache(cfg: ModelConfig) -> bool:
    return cfg.mla is not None and cfg.mla.latent_cache


def make_cache(
    cfg: ModelConfig,
    num_pages: int,
    page_size: int,
    dtype: jnp.dtype = jnp.bfloat16,
    kv_quantize: str = "",
) -> Params:
    """Paged KV cache pytree: pages stacked over layers. MLA latent mode
    stores ONE (kv_lora_rank + rope)-dim latent per token in ``k`` — the
    compression that motivates MLA — with a 1-dim placeholder ``v`` (the
    pytree shape is shared with the standard layout so the engine's
    donation/restart plumbing is layout-agnostic).

    ``kv_quantize="int8"`` stores pages as ``ops.attention.QuantizedPages``
    (int8 values + per-token-per-head f32 scales): halves decode KV reads,
    the dominant non-weight HBM term at serving shapes (PERF.md). Not
    supported for the MLA latent layout (latents feed weight-absorbed
    matmuls, not raw attention; the engine rejects the combination)."""
    L = cfg.num_layers
    if _latent_cache(cfg):
        if kv_quantize:
            raise ValueError("kv_quantize is not supported with MLA latent cache")
        shape_k = (L, num_pages, page_size, 1, cfg.mla.latent_dim)
        shape_v = (L, num_pages, page_size, 1, 1)
        return {
            "k": jnp.zeros(shape_k, dtype), "v": jnp.zeros(shape_v, dtype)
        }
    K, D = cfg.num_kv_heads, cfg.head_dim_
    shape = (L, num_pages, page_size, K, D)
    if kv_quantize:
        if kv_quantize != "int8":
            raise ValueError(f"unsupported kv_quantize {kv_quantize!r}")
        from ..ops.attention import QuantizedPages

        return {
            "k": QuantizedPages(
                jnp.zeros(shape, jnp.int8),
                jnp.ones(shape[:-1], jnp.float32),
            ),
            "v": QuantizedPages(
                jnp.zeros(shape, jnp.int8),
                jnp.ones(shape[:-1], jnp.float32),
            ),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_specs(cfg: ModelConfig, kv_quantize: str = "") -> Params:
    """KV pages are sharded over the kv-head axis (tp), like wk/wv. The
    MLA latent cache has ONE shared 'head' — replicated over tp (it is
    per-token global state; queries/outputs still shard over heads).
    Quantized pages: the scale plane drops the head-dim axis but keeps
    the kv-head axis, so it shards with its values."""
    if _latent_cache(cfg):
        return {
            "k": P(None, None, None, None, None),
            "v": P(None, None, None, None, None),
        }
    if kv_quantize:
        from ..ops.attention import QuantizedPages

        spec = QuantizedPages(
            P(None, None, None, "tp", None), P(None, None, None, "tp")
        )
        return {"k": spec, "v": spec}
    return {
        "k": P(None, None, None, "tp", None),
        "v": P(None, None, None, "tp", None),
    }


# -- building blocks --------------------------------------------------------
def _ep_constrain(x: jax.Array, spec: P) -> jax.Array:
    """with_sharding_constraint iff the ambient mesh has a real ep axis —
    model code stays mesh-agnostic (tests call forward_full with no mesh
    context at all) while ep>1 runs get the expert-sharded layout pinned
    rather than left to GSPMD propagation (which is free to all-gather
    the expert weights instead, defeating the memory scale-out).

    The ``with mesh:`` context every caller uses (trainer/engine) is
    ``pxla.thread_resources`` under the hood — read at TRACE time; the
    accessor is deprecated but there is no public replacement readable
    inside jit (``get_mesh`` forbids it, ``get_abstract_mesh`` is only
    populated by ``use_mesh``, which this codebase does not adopt)."""
    import warnings

    mesh = None
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from jax.interpreters import pxla

            m = pxla.thread_resources.env.physical_mesh
        if not m.empty:
            mesh = m
    except Exception:  # pragma: no cover - accessor removed upstream
        mesh = None
    if mesh is None:
        am = None
        try:
            am = jax.sharding.get_abstract_mesh()
        except AttributeError:
            # jax 0.4.x keeps the accessor private; same thread-local.
            try:
                from jax._src.mesh import get_abstract_mesh

                am = get_abstract_mesh()
            except Exception:  # pragma: no cover - accessor moved again
                am = None
        if am is not None and getattr(am, "axis_names", ()):
            mesh = am
    if (
        mesh is not None
        and "ep" in mesh.axis_names
        and dict(mesh.shape).get("ep", 1) > 1
    ):
        if isinstance(mesh, jax.sharding.Mesh):
            return jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, spec)
            )
        return jax.lax.with_sharding_constraint(x, spec)
    return x


# Trace-time weight-stream backend scope: "xla" (dequantize fused into
# the matmul operand read) or "pallas-dma" (ops.quant_matmul_pallas —
# weight tiles double-buffered HBM->VMEM under the dot). Thread-local
# like the jit trace itself; set by mixed_step/decode_step from the
# engine's RESOLVED EngineConfig.weight_stream so every _mm under the
# layer scan dispatches without threading a parameter through each
# helper (same trace-time-read pattern as ops.attention.pallas_interpret).
_WS_TLS = threading.local()


@contextlib.contextmanager
def weight_stream_scope(impl: str):
    """Activate a weight-stream backend for the ops traced inside."""
    prev = getattr(_WS_TLS, "impl", "xla")
    _WS_TLS.impl = impl or "xla"
    try:
        yield
    finally:
        _WS_TLS.impl = prev


def _weight_stream_impl() -> str:
    return getattr(_WS_TLS, "impl", "xla")


def _mm(x: jax.Array, w: Any) -> jax.Array:
    """Matmul against a plain array or a weight-only quantized leaf
    (models.quant, any width): the dequantize multiplies fuse into the
    matmul operand read under XLA, so quantized weights stream from HBM
    in their narrow storage type. Under an active
    ``weight_stream_scope("pallas-dma")``, 2D quantized leaves (the
    per-layer scan slices plus lm_head) route through the Pallas
    double-buffered weight-streaming kernel instead; stacked/MoE leaves
    and plain arrays keep the XLA path."""
    from .quant import QuantizedBase

    if isinstance(w, QuantizedBase):
        if _weight_stream_impl() == "pallas-dma":
            from ..ops import quant_matmul_pallas as qmp

            if qmp.supports(w):
                from ..ops.attention import pallas_interpret

                lead = x.shape[:-1]
                y = qmp.quant_matmul_pallas(
                    x.reshape(-1, x.shape[-1]), w,
                    interpret=pallas_interpret(),
                )
                return y.reshape(*lead, y.shape[-1])
        return x @ w.dequantize().astype(x.dtype)
    return x @ w


def _ein(sub: str, x: jax.Array, w: Any) -> jax.Array:
    """einsum twin of ``_mm`` for the batched expert matmuls."""
    from .quant import QuantizedBase

    if isinstance(w, QuantizedBase):
        return jnp.einsum(sub, x, w.dequantize().astype(x.dtype))
    return jnp.einsum(sub, x, w)


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def _qkv(
    x: jax.Array, lp: Params, cfg: ModelConfig
) -> tuple[jax.Array, jax.Array, jax.Array]:
    B, S, _ = x.shape
    K, D = cfg.num_kv_heads, cfg.head_dim_
    q = _mm(x, lp["wq"])
    k = _mm(x, lp["wk"])
    v = _mm(x, lp["wv"])
    if cfg.attn_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, S, cfg.num_heads, D)
    k = k.reshape(B, S, K, D)
    if cfg.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim, BEFORE RoPE (the caller
        # applies rope to this function's outputs).
        q = rms_norm(q, lp["qn"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["kn"], cfg.rms_norm_eps)
    return (q, k, v.reshape(B, S, K, D))


def _qkv_mla(
    x: jax.Array, lp: Params, cfg: ModelConfig, cos, sin,
    with_latent: bool = False,
):
    """MLA q/k/v with decoupled RoPE (DeepSeek-V2/V3):

    - q: (optionally low-rank) projection to H x (nope + rope) dims; RoPE
      rotates only the rope part.
    - kv: one low-rank latent c_kv plus a per-head-SHARED roped key part
      computed straight from x; up-projection expands the normed latent
      to per-head k_nope and v.
    - v is zero-padded to the qk head dim so the shared paged cache and
      attention paths need no second head-dim; wo's matching rows are
      padding (they multiply zeros).
    """
    m = cfg.mla
    B, S, _ = x.shape
    H = cfg.num_heads
    dn, dr, dv = m.qk_nope_head_dim, m.qk_rope_head_dim, m.v_head_dim
    dq = dn + dr
    q_nope, q_rope = _mla_q(x, lp, cfg, cos, sin)
    q = jnp.concatenate([q_nope, q_rope], axis=-1) * _yarn_q_scale(cfg)
    ckv, k_rope = _mla_kv_latent(x, lp, cfg, cos, sin)
    kv = _mm(ckv, lp["wukv"]).reshape(B, S, H, dn + dv)
    k = jnp.concatenate(
        [kv[..., :dn], jnp.broadcast_to(k_rope, (B, S, H, dr))], axis=-1
    )
    v = jnp.concatenate(
        [kv[..., dn:], jnp.zeros((B, S, H, dq - dv), kv.dtype)], axis=-1
    )
    if with_latent:
        latent = jnp.concatenate(
            [ckv[:, :, None, :], k_rope], axis=-1
        ).astype(x.dtype)
        return q, k, v, latent
    return q, k, v


def _mla_q(x, lp, cfg: ModelConfig, cos, sin):
    """(q_nope [B,S,H,dn], roped q_rope [B,S,H,dr])."""
    m = cfg.mla
    B, S, _ = x.shape
    H = cfg.num_heads
    dn, dr = m.qk_nope_head_dim, m.qk_rope_head_dim
    if m.q_lora_rank:
        cq = rms_norm(_mm(x, lp["wdq"]), lp["q_norm"], cfg.rms_norm_eps)
        q = _mm(cq, lp["wuq"])
    else:
        q = _mm(x, lp["wq"])
    q = q.reshape(B, S, H, dn + dr)
    return q[..., :dn], apply_rope(q[..., dn:], cos, sin)


def _mla_kv_latent(x, lp, cfg: ModelConfig, cos, sin):
    """(normed kv latent [B,S,rkv], roped shared key [B,S,1,dr])."""
    m = cfg.mla
    B, S, _ = x.shape
    ckv = rms_norm(_mm(x, lp["wdkv"]), lp["kv_norm"], cfg.rms_norm_eps)
    k_rope = apply_rope(
        _mm(x, lp["wkr"]).reshape(B, S, 1, m.qk_rope_head_dim), cos, sin
    )
    return ckv, k_rope


def _dense_weight(w: Any) -> jax.Array:
    """Materialize a weight that code must reshape/slice (the MLA absorbed
    path reshapes wukv per head): dequantizes quantized leaves of any
    width — XLA fuses the dequantize into the consuming einsum's operand
    read."""
    from .quant import QuantizedBase

    if isinstance(w, QuantizedBase):
        return w.dequantize()
    return w


def _mla_latent_parts(x, lp, cfg: ModelConfig, cos, sin):
    """Weight-absorbed form for the LATENT cache: per-head latent queries
    and the per-token latent to write.

    Per head h: score_h(t) ∝ q_nope·(W_uk c_t) + q_rope·kr_t
              = (W_uk^T q_nope)·c_t + q_rope·kr_t — one MQA-style dot of
    q_lat[h] = [W_uk^T q_nope[h], q_rope[h]] against latent_t = [c_t, kr_t].
    The shared attention ops scale by latent_dim^-0.5, so q_lat is
    pre-scaled by sqrt(latent_dim/qk_head_dim) (plus the YaRN correction)
    to restore the true qk_head_dim^-0.5 softmax scale.

    Returns (q_lat [B,S,H,DL], latent [B,S,1,DL])."""
    m = cfg.mla
    H = cfg.num_heads
    dn = m.qk_nope_head_dim
    dv = m.v_head_dim
    rkv = m.kv_lora_rank
    DL, dq = m.latent_dim, m.qk_head_dim
    q_nope, q_rope = _mla_q(x, lp, cfg, cos, sin)
    w_uk = _dense_weight(lp["wukv"]).reshape(rkv, H, dn + dv)[:, :, :dn]
    q_abs = jnp.einsum("bshd,rhd->bshr", q_nope, w_uk)      # [B,S,H,rkv]
    scale = (DL ** 0.5) / (dq ** 0.5) * _yarn_q_scale(cfg)
    q_lat = jnp.concatenate([q_abs, q_rope], axis=-1) * scale
    ckv, k_rope = _mla_kv_latent(x, lp, cfg, cos, sin)
    latent = jnp.concatenate([ckv[:, :, None, :], k_rope], axis=-1)
    return q_lat.astype(x.dtype), latent.astype(x.dtype)


def _mla_latent_out(ctx, lp, cfg: ModelConfig):
    """Attention output over latent VALUES -> padded per-head layout.

    ctx [B,S,H,DL]: only the first rkv dims are meaningful (the attention
    averaged the latents; the rope dims are discarded). o_h = W_uv^T ctx_c
    recovers each head's v_head_dim output, zero-padded to qk_head_dim so
    the stack's shared ``wo`` matmul applies unchanged."""
    m = cfg.mla
    B, S, H, _ = ctx.shape
    dn, dv = m.qk_nope_head_dim, m.v_head_dim
    rkv = m.kv_lora_rank
    dq = m.qk_head_dim
    w_uv = _dense_weight(lp["wukv"]).reshape(rkv, H, dn + dv)[:, :, dn:]
    o = jnp.einsum("bshr,rhv->bshv", ctx[..., :rkv], w_uv)  # [B,S,H,dv]
    o = jnp.concatenate(
        [o, jnp.zeros((B, S, H, dq - dv), o.dtype)], axis=-1
    )
    return o.reshape(B, S, H * dq).astype(ctx.dtype)


def _yarn_q_scale(cfg: ModelConfig) -> float:
    """YaRN softmax-scale correction (HF: softmax_scale *= mscale^2 with
    mscale = yarn_get_mscale(factor, mscale_all_dim)); folded into q so
    the shared attention paths' 1/sqrt(head_dim) stays untouched. 1.0
    when no yarn mscale_all_dim applies."""
    rs = cfg.rope_scaling
    if rs is None or rs.rope_type != "yarn" or not rs.mscale_all_dim:
        return 1.0
    from ..ops.rope import yarn_get_mscale

    ms = yarn_get_mscale(rs.factor, rs.mscale_all_dim)
    return ms * ms


def _qkv_rope(
    x: jax.Array, lp: Params, cfg: ModelConfig, cos, sin
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """q/k/v with RoPE applied, dispatched on the attention family. The
    rope tables must be built with ``cfg.rope_dim_`` (the decoupled rope
    part under MLA, the full head otherwise)."""
    if cfg.mla is not None:
        return _qkv_mla(x, lp, cfg, cos, sin)
    q, k, v = _qkv(x, lp, cfg)
    return (
        apply_rope(q, cos, sin) * _yarn_q_scale(cfg),
        apply_rope(k, cos, sin),
        v,
    )


def _mlp(x: jax.Array, lp: Params) -> jax.Array:
    return _mm(jax.nn.silu(_mm(x, lp["wg"])) * _mm(x, lp["wu"]), lp["wd"])


def _moe_mlp(
    h: jax.Array, lp: Params, cfg: ModelConfig
) -> tuple[jax.Array, jax.Array]:
    """DeepSeek-style MoE MLP: softmax router, top-k combine weights
    (renormalized/scaled per the checkpoint's norm_topk_prob /
    routed_scaling_factor), always-on shared experts, plus routed experts.
    Returns (output, load-balance aux loss).

    Two dispatch strategies, picked by token count (MoEConfig):

    - **all-experts scan** (decode / tiny batches): every expert computes
      every token, masked by the combine weight. E× the active FLOPs, but
      with T·k >= E each expert's weights stream from HBM once either way,
      so decode — which is bandwidth-bound, not FLOPs-bound — loses
      nothing, and there is no capacity/drop risk.
    - **grouped capacity dispatch** (prefill / training): tokens scatter
      into per-expert buckets of C = ceil(T·k/E · capacity_factor) slots,
      experts run as ONE batched einsum over [E, C, d], results gather
      back weighted. Expert FLOPs scale with top-k·capacity_factor, not
      num_experts (VERDICT round-1 weak #5). Assignments overflowing an
      expert's bucket fall back to the shared-experts-only path for that
      slot (standard Switch-style capacity semantics; capacity_factor
      sizes the safety margin).

    Aux = Switch-Transformer balance loss E·Σ_e f_e·P_e (f_e = fraction of
    token-slots routed to expert e, P_e = mean router probability): minimized
    at uniform routing, it counteracts the router's winner-take-all dynamic
    during fine-tuning (weighted into the loss by TrainConfig.moe_aux_weight;
    serving paths discard it)."""
    m = cfg.moe
    E, k = m.num_experts, m.num_experts_per_token
    T = h.shape[0] * h.shape[1]
    router_logits = (h.astype(jnp.float32) @ lp["router"])          # [B,S,E]
    # Router scoring per the checkpoint's HF config: softmax (DeepSeek-
    # MoE/V2) or sigmoid with the noaux_tc selection bias (V3). The bias
    # steers SELECTION only; combine weights come from the raw scores.
    if m.scoring_func == "sigmoid":
        probs = jax.nn.sigmoid(router_logits)
    else:
        probs = jax.nn.softmax(router_logits, axis=-1)
    select = probs
    if "router_bias" in lp:
        select = select + lp["router_bias"]
    if m.n_group > 1:
        # Group-limited top-k: experts outside the best topk_group groups
        # are ineligible. Group ranking follows the checkpoint's method:
        # V3's noaux_tc (sigmoid) ranks groups by the sum of their top-2
        # selection scores; V2's group_limited_greedy (softmax) ranks by
        # the group's single best score.
        Bd, Sd = select.shape[:2]
        g = select.reshape(Bd, Sd, m.n_group, E // m.n_group)
        if m.scoring_func == "sigmoid":
            group_score = jnp.sum(jax.lax.top_k(g, 2)[0], axis=-1)  # [B,S,G]
        else:
            group_score = jnp.max(g, axis=-1)                       # [B,S,G]
        _, keep_idx = jax.lax.top_k(group_score, m.topk_group)
        keep = jnp.sum(
            jax.nn.one_hot(keep_idx, m.n_group, dtype=select.dtype), axis=-2
        )                                                           # [B,S,G]
        select = jnp.where(
            keep[..., None] > 0, g, -jnp.inf
        ).reshape(Bd, Sd, E)
    _, idx = jax.lax.top_k(select, k)                               # [B,S,k]
    vals = jnp.take_along_axis(probs, idx, axis=-1)
    # Combine-weight semantics follow the checkpoint's HF config: DeepSeek-
    # MoE-16B/V2-Lite use raw top-k softmax probs (norm_topk_prob=false);
    # V3 renormalizes among the selected and scales by 2.5.
    if m.norm_topk_prob:
        vals = vals / (jnp.sum(vals, axis=-1, keepdims=True) + 1e-20)
    if m.routed_scaling_factor != 1.0:
        vals = vals * m.routed_scaling_factor
    sel = jnp.sum(jax.nn.one_hot(idx, E, dtype=probs.dtype), axis=-2)  # [B,S,E]
    f_e = jnp.mean(sel / k, axis=(0, 1))                            # [E]
    p_e = jnp.mean(probs, axis=(0, 1))                              # [E]
    aux = E * jnp.sum(f_e * p_e)

    grouped = (
        m.grouped_dispatch_min_tokens > 0
        and T >= m.grouped_dispatch_min_tokens
    )
    if grouped:
        out = _moe_grouped_dispatch(h, lp, cfg, vals, idx)
    else:
        combine = jnp.sum(
            jax.nn.one_hot(idx, E, dtype=vals.dtype) * vals[..., None],
            axis=-2,
        )                                                           # [B,S,E]
        combine = jnp.moveaxis(combine, -1, 0).astype(h.dtype)      # [E,B,S]

        def expert_step(acc, scanned):
            eg, eu, ed, c = scanned
            y = _mm(jax.nn.silu(_mm(h, eg)) * _mm(h, eu), ed)
            return acc + c[..., None] * y, None

        out, _ = jax.lax.scan(
            expert_step,
            jnp.zeros_like(h),
            (lp["eg"], lp["eu"], lp["ed"], combine),
        )
    if m.num_shared_experts:
        out = out + _mm(
            jax.nn.silu(_mm(h, lp["sg"])) * _mm(h, lp["su"]), lp["sd"]
        )
    return out, aux


def _moe_grouped_dispatch(
    h: jax.Array,           # [B, S, d]
    lp: Params,
    cfg: ModelConfig,
    vals: jax.Array,        # [B, S, k] combine weights (post-norm/scale)
    idx: jax.Array,         # [B, S, k] expert ids
) -> jax.Array:
    """Capacity-bucketed expert dispatch: scatter each (token, choice)
    assignment into its expert's [C] slot queue, run all experts as one
    batched einsum (MXU-friendly, eg/eu/ed stay tp-sharded on the expert
    intermediate dim), gather back weighted. Static shapes throughout —
    the position-in-expert comes from a cumulative count over the
    flattened assignment list, XLA's standard MoE formulation."""
    m = cfg.moe
    E, k = m.num_experts, m.num_experts_per_token
    B, S, d = h.shape
    T = B * S
    C = max(1, min(T, math.ceil(T * k / E * m.capacity_factor)))
    x = h.reshape(T, d)
    flat_e = idx.reshape(T * k)                       # token-major order
    flat_w = vals.reshape(T * k).astype(h.dtype)
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)        # [T*k, E]
    pos = jnp.sum(
        (jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1
    )                                                  # [T*k] slot in queue
    keep = pos < C
    dest = jnp.where(keep, flat_e * C + pos, E * C)    # E*C = drop sentinel
    token_of = jnp.arange(T * k) // k
    disp = jnp.zeros((E * C, d), h.dtype).at[dest].set(
        x[token_of], mode="drop"
    ).reshape(E, C, d)
    # Expert parallelism: pin the bucket and output layouts to the expert
    # axis so XLA partitions expert compute over ep and emits the token
    # all-to-all at the scatter/gather boundaries (no-op on ep=1 meshes).
    disp = _ep_constrain(disp, P("ep", None, None))
    up = jax.nn.silu(
        _ein("ecd,edf->ecf", disp, lp["eg"])
    ) * _ein("ecd,edf->ecf", disp, lp["eu"])
    y = _ein("ecf,efd->ecd", up, lp["ed"])             # [E, C, d]
    y = _ep_constrain(y, P("ep", None, None))
    y = y.reshape(E * C, d)
    # Gather each assignment's routed output; dropped slots contribute 0.
    safe = jnp.where(keep, dest, 0)
    per_pair = jnp.where(
        keep[:, None], y[safe], jnp.zeros_like(x[token_of])
    ) * flat_w[:, None]
    return jnp.sum(per_pair.reshape(T, k, d), axis=1).reshape(B, S, d)


# AttnFn: (normed hidden, layer params, whole k cache, whole v cache,
#          layer index) -> (attn out [B, S, q_size], k cache, v cache).
# The cache arrays keep their full [L, N, P, K, D] shape: attention ops
# address the layer's pages via the flat offset layer * N.
AttnFn = Callable[
    [jax.Array, Params, Any, Any, jax.Array], tuple[jax.Array, Any, Any]
]


def _run_stack(
    params: Params,
    cfg: ModelConfig,
    x: jax.Array,
    attn_fn: AttnFn,
    cache: Params | None,
    remat: bool = False,
    stacks: tuple[str, ...] | None = None,
) -> tuple[jax.Array, Params | None, jax.Array]:
    """Run the dense stack then (if configured) the MoE stack; returns
    (final hidden states, updated cache or None, summed MoE aux loss).

    The KV cache travels through the layer scan as part of the CARRY (one
    whole-cache array, layer-indexed by the scanned step counter), not as
    per-layer slices with stacked outputs: stacked scan outputs semantically
    copy the full cache every call (~GBs per decode step at serving
    shapes), while scatters into a loop carry update it in place."""
    Ld, Lm = _layer_split(cfg)

    def make_body(moe: bool):
        def body(carry, lp):
            x, aux, kc, vc, li = carry
            h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            attn, kc, vc = attn_fn(h, lp, kc, vc, li)
            x = x + _mm(attn, lp["wo"])
            h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            if moe:
                y, layer_aux = _moe_mlp(h, lp, cfg)
                x, aux = x + y, aux + layer_aux
            else:
                x = x + _mlp(h, lp)
            return (x, aux, kc, vc, li + 1), None
        return jax.checkpoint(body) if remat else body

    if cache is None:
        kc = vc = jnp.zeros((0,), x.dtype)  # pytree placeholder
    else:
        kc, vc = cache["k"], cache["v"]
    # The aux init inherits x's varying-manual-axes type via an O(1)
    # numeric no-op (one element, not a reduction — XLA cannot fold float
    # 0*x): under shard_map manual (parallel/pipeline.py) the scan body's
    # aux output is varying over the manual axes, and scan requires the
    # initial carry to match; outside manual contexts this is plain zero.
    aux0 = x.reshape(-1)[0].astype(jnp.float32) * 0.0
    carry = (x, aux0, kc, vc, jnp.int32(0))
    # stacks=None runs the full config-implied stack (missing keys raise
    # loudly); pipeline stages (parallel/pipeline.py) pass the subset they
    # own explicitly rather than relying on silent key-presence dispatch.
    if Ld and (stacks is None or "layers" in stacks):
        carry, _ = jax.lax.scan(make_body(False), carry, params["layers"])
    if Lm and (stacks is None or "moe_layers" in stacks):
        carry, _ = jax.lax.scan(make_body(True), carry, params["moe_layers"])
    x, aux, kc, vc, _ = carry
    if cache is None:
        return x, None, aux
    return x, {"k": kc, "v": vc}, aux


# -- forward passes ---------------------------------------------------------
def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, S] int32, right-padded
    lengths: jax.Array,      # [B] valid lengths
    cache: Params,           # paged cache pytree
    page_table: jax.Array,   # [B, MaxP]
    dtype: jnp.dtype = jnp.bfloat16,
    prefill_attn: Callable | None = None,  # e.g. parallel.ring (sp-sharded)
) -> tuple[jax.Array, Params]:
    """Full-sequence forward; writes KV into pages; returns (logits of the
    last valid position [B, V], updated cache). ``prefill_attn`` swaps the
    attention op — the engine passes the sp-sharded ring attention for
    long-context serving prefill (BASELINE config 4); the KV page writes
    stay on the pjit-partitioned scatter either way."""
    B, S = tokens.shape
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)
    x = params["embed"][tokens].astype(dtype)
    start = jnp.zeros((B,), jnp.int32)
    attn_op = prefill_attn or causal_prefill_attention

    def attn_fn(h, lp, kc, vc, li):
        if _latent_cache(cfg):
            # Fresh prefill attends MATERIALIZED (exact, composes with
            # the sp ring attention) but writes only the latent.
            q, k, v, latent = _qkv_mla(
                h, lp, cfg, cos, sin, with_latent=True
            )
            kc = write_pages(
                kc, latent, page_table, start, valid_len=lengths, layer=li
            )
        else:
            q, k, v = _qkv_rope(h, lp, cfg, cos, sin)
            kc, vc = write_kv_pages(
                kc, vc, k, v, page_table, start, valid_len=lengths, layer=li
            )
        attn = attn_op(q, k, v, lengths=lengths)
        return attn.reshape(B, S, -1), kc, vc

    x, cache, _ = _run_stack(params, cfg, x, attn_fn, cache)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.clip(lengths - 1, 0, S - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [B, D]
    logits = _lm_head(params, cfg, x_last)
    return logits, cache


def prefill_with_prefix(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, S] int32 TAIL tokens, right-padded
    start: jax.Array,        # [B] cached-prefix lengths
    lengths: jax.Array,      # [B] valid tail lengths
    cache: Params,
    page_table: jax.Array,   # [B, MaxP] (prefix pages + fresh tail pages)
    dtype: jnp.dtype = jnp.bfloat16,
) -> tuple[jax.Array, Params]:
    """Prefix-cache admission: forward only the tail, attending over the
    sequence's cached prefix pages + the tail KV written this call. Returns
    (last-tail-position logits [B, V], updated cache)."""
    B, S = tokens.shape
    positions = start[:, None] + jnp.arange(S)[None, :]
    cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)
    x = params["embed"][tokens].astype(dtype)

    def attn_fn(h, lp, kc, vc, li):
        if _latent_cache(cfg):
            q_lat, latent = _mla_latent_parts(h, lp, cfg, cos, sin)
            kc = write_pages(
                kc, latent, page_table, start, valid_len=lengths, layer=li
            )
            ctx = paged_prefix_attention(
                q_lat, kc, kc, page_table, start, lengths, layer=li
            )
            return _mla_latent_out(ctx, lp, cfg), kc, vc
        q, k, v = _qkv_rope(h, lp, cfg, cos, sin)
        kc, vc = write_kv_pages(
            kc, vc, k, v, page_table, start, valid_len=lengths, layer=li
        )
        attn = paged_prefix_attention(
            q, kc, vc, page_table, start, lengths, layer=li
        )
        return attn.reshape(B, S, -1), kc, vc

    x, cache, _ = _run_stack(params, cfg, x, attn_fn, cache)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.clip(lengths - 1, 0, S - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    logits = _lm_head(params, cfg, x_last)
    return logits, cache


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, S] int32 ragged rows, right-padded
    start: jax.Array,        # [B] tokens already in cache (write offset)
    q_lens: jax.Array,       # [B] valid row lengths (0 = inactive row)
    cache: Params,
    page_table: jax.Array,   # [B, MaxP]
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",  # ops.paged_attention_backend choice
    mesh=None,               # Mesh for the shard_mapped pallas-under-tp path
    weight_stream: str = "xla",  # xla | pallas-dma (quant_matmul_pallas)
) -> tuple[jax.Array, Params]:
    """The unified mixed prefill+decode forward: one program advances
    q_len=1 decode rows AND q_len=chunk prefill rows in the same batch, so
    chunked prefill rides the decode dispatch's weight stream instead of
    buying its own (the engine's ``step_mixed``; Sarathi-style
    piggybacking over Ragged Paged Attention, PAPERS.md). Same math as
    ``prefill_with_prefix`` — per-row write offset, causal attention
    inside the chunk over paged cache — but attention goes through the
    impl-dispatched ragged op (Pallas page streaming on TPU when enabled)
    and rows with q_lens == 0 are inert (no KV writes; garbage logits the
    caller discards). Returns (last-valid-position logits [B, V],
    updated cache)."""
    B, S = tokens.shape
    positions = start[:, None] + jnp.arange(S)[None, :]
    cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)
    x = params["embed"][tokens].astype(dtype)

    def attn_fn(h, lp, kc, vc, li):
        if _latent_cache(cfg):
            q_lat, latent = _mla_latent_parts(h, lp, cfg, cos, sin)
            kc = write_pages(
                kc, latent, page_table, start, valid_len=q_lens, layer=li
            )
            ctx = paged_ragged_attention_auto(
                q_lat, kc, kc, page_table, start, q_lens,
                impl=attn_impl, layer=li, mesh=mesh,
            )
            return _mla_latent_out(ctx, lp, cfg), kc, vc
        q, k, v = _qkv_rope(h, lp, cfg, cos, sin)
        kc, vc = write_kv_pages(
            kc, vc, k, v, page_table, start, valid_len=q_lens, layer=li
        )
        attn = paged_ragged_attention_auto(
            q, kc, vc, page_table, start, q_lens,
            impl=attn_impl, layer=li, mesh=mesh,
        )
        return attn.reshape(B, S, -1), kc, vc

    with weight_stream_scope(weight_stream):
        x, cache, _ = _run_stack(params, cfg, x, attn_fn, cache)
        x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        last = jnp.clip(q_lens - 1, 0, S - 1)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        logits = _lm_head(params, cfg, x_last)
    return logits, cache


def verify_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, S] int32 draft-chunk inputs
    start: jax.Array,        # [B] write offset (tokens already in cache)
    valid: jax.Array,        # [B] valid chunk lengths (KV writes + attn)
    cache: Params,
    page_table: jax.Array,   # [B, MaxP]
    dtype: jnp.dtype = jnp.bfloat16,
) -> tuple[jax.Array, Params]:
    """Speculative-decoding verify forward: process an S-token draft chunk
    per row in ONE pass, returning logits for EVERY chunk position
    [B, S, V] (``prefill_with_prefix`` with the last-position gather
    removed). Each position's argmax is the model's true greedy
    continuation given the chunk prefix before it — the acceptance test
    for prompt-lookup drafts. KV for all S positions is written at
    ``start``; rejected positions simply get overwritten by later real
    tokens, because the write offset only advances by the accepted count.
    Costs ~one decode step of HBM traffic (weights stream once per
    forward, the whole point of speculation)."""
    B, S = tokens.shape
    positions = start[:, None] + jnp.arange(S)[None, :]
    cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)
    x = params["embed"][tokens].astype(dtype)

    def attn_fn(h, lp, kc, vc, li):
        if _latent_cache(cfg):
            q_lat, latent = _mla_latent_parts(h, lp, cfg, cos, sin)
            kc = write_pages(
                kc, latent, page_table, start, valid_len=valid, layer=li
            )
            ctx = paged_prefix_attention(
                q_lat, kc, kc, page_table, start, valid, layer=li
            )
            return _mla_latent_out(ctx, lp, cfg), kc, vc
        q, k, v = _qkv_rope(h, lp, cfg, cos, sin)
        kc, vc = write_kv_pages(
            kc, vc, k, v, page_table, start, valid_len=valid, layer=li
        )
        attn = paged_prefix_attention(
            q, kc, vc, page_table, start, valid, layer=li
        )
        return attn.reshape(B, S, -1), kc, vc

    x, cache, _ = _run_stack(params, cfg, x, attn_fn, cache)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _lm_head(params, cfg, x)
    return logits, cache


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B] int32 (the latest sampled token per seq)
    lengths: jax.Array,      # [B] tokens already in cache (write offset)
    cache: Params,
    page_table: jax.Array,   # [B, MaxP]
    active: jax.Array,       # [B] bool; inactive slots skip the page write
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",  # xla | pallas | pallas-dma (paged_attention_backend)
    mesh=None,               # Mesh for the shard_mapped pallas-under-tp path
    weight_stream: str = "xla",  # xla | pallas-dma (quant_matmul_pallas)
) -> tuple[jax.Array, Params]:
    """One decode step for a batch of sequences; returns ([B, V] logits,
    updated cache)."""
    B = tokens.shape[0]
    positions = lengths[:, None]                       # [B, 1]
    cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)
    x = params["embed"][tokens[:, None]].astype(dtype)  # [B, 1, D]
    valid = active.astype(jnp.int32)                   # [B] 1 new token if active

    def attn_fn(h, lp, kc, vc, li):
        if _latent_cache(cfg):
            q_lat, latent = _mla_latent_parts(h, lp, cfg, cos, sin)
            kc = write_pages(
                kc, latent, page_table, lengths, valid_len=valid, layer=li
            )
            ctx = paged_decode_attention_auto(
                q_lat[:, 0], kc, kc, page_table, lengths + valid,
                impl=attn_impl, layer=li, mesh=mesh,
            )
            return _mla_latent_out(ctx[:, None], lp, cfg), kc, vc
        q, k, v = _qkv_rope(h, lp, cfg, cos, sin)
        kc, vc = write_kv_pages(
            kc, vc, k, v, page_table, lengths, valid_len=valid, layer=li
        )
        attn = paged_decode_attention_auto(
            q[:, 0], kc, vc, page_table, lengths + valid,
            impl=attn_impl, layer=li, mesh=mesh,
        )
        return attn.reshape(B, 1, -1), kc, vc

    with weight_stream_scope(weight_stream):
        x, cache, _ = _run_stack(params, cfg, x, attn_fn, cache)
        x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        logits = _lm_head(params, cfg, x[:, 0])
    return logits, cache


def _lm_head(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.tie_embeddings:
        return (x @ params["embed"].T.astype(x.dtype)).astype(jnp.float32)
    return _mm(x, params["lm_head"]).astype(jnp.float32)


def forward_full(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,
    dtype: jnp.dtype = jnp.bfloat16,
    remat: bool = False,
    return_aux: bool = False,
    prefill_attn: Callable | None = None,  # e.g. parallel.ring attention
) -> jax.Array:
    """All-positions logits [B, S, V] with vanilla causal attention and no
    cache — the ground-truth oracle for prefill/decode equivalence tests and
    the loss path for the training step. ``remat=True`` checkpoints the
    scanned layer body (recompute activations in backward: HBM for FLOPs).
    ``return_aux=True`` also returns the summed MoE load-balance loss
    (zero for dense models)."""
    B, S = tokens.shape
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)
    x = params["embed"][tokens].astype(dtype)
    attn_op = prefill_attn or causal_prefill_attention

    def attn_fn(h, lp, kc, vc, li):
        q, k, v = _qkv_rope(h, lp, cfg, cos, sin)
        attn = attn_op(q, k, v)
        return attn.reshape(B, S, -1), kc, vc

    x, _, aux = _run_stack(params, cfg, x, attn_fn, cache=None, remat=remat)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _lm_head(params, cfg, x)
    return (logits, aux) if return_aux else logits
