"""Checkpoint loading: HuggingFace safetensors -> stacked param pytree.

Maps the HF llama-family naming scheme (model.layers.N.self_attn.q_proj...)
onto this framework's scan-stacked layout (models/llama.py): per-layer weights
are transposed to [in, out] and stacked along a leading layer dim. Handles
single-file and index-sharded checkpoints. Supports Llama-3 and Qwen2.5
families (attention biases included when present).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from .config import ModelConfig


class CheckpointError(Exception):
    pass


def _open_shards(path: str):
    """Yield (name, tensor-loader) for every tensor across all shards."""
    from safetensors import safe_open

    if os.path.isfile(path):
        files = [path]
    else:
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.isfile(index):
            with open(index, "r", encoding="utf-8") as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            files = sorted({os.path.join(path, v) for v in weight_map.values()})
        else:
            files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith(".safetensors")
            )
    if not files:
        raise CheckpointError(f"no .safetensors files under {path}")
    tensors: dict[str, Any] = {}
    for file in files:
        fh = safe_open(file, framework="numpy")
        for name in fh.keys():
            tensors[name] = (fh, name)
    return tensors


def _get(tensors: dict[str, Any], name: str) -> np.ndarray:
    if name not in tensors:
        raise CheckpointError(f"missing tensor {name} in checkpoint")
    fh, key = tensors[name]
    return fh.get_tensor(key)


def _maybe(tensors: dict[str, Any], name: str) -> np.ndarray | None:
    if name not in tensors:
        return None
    fh, key = tensors[name]
    return fh.get_tensor(key)


def load_checkpoint(
    path: str, cfg: ModelConfig, dtype: Any = jnp.bfloat16
) -> dict[str, Any]:
    """Load an HF llama/qwen checkpoint into the stacked param layout."""
    tensors = _open_shards(path)
    L = cfg.num_layers

    def linear(name_fmt: str) -> jnp.ndarray:
        # HF stores [out, in]; we use [in, out]. Stack over layers.
        mats = [
            _get(tensors, name_fmt.format(i)).T for i in range(L)
        ]
        return jnp.asarray(np.stack(mats), dtype=dtype)

    def vector(name_fmt: str) -> jnp.ndarray:
        vecs = [_get(tensors, name_fmt.format(i)) for i in range(L)]
        return jnp.asarray(np.stack(vecs), dtype=dtype)

    layers: dict[str, Any] = {
        "attn_norm": vector("model.layers.{}.input_layernorm.weight"),
        "wq": linear("model.layers.{}.self_attn.q_proj.weight"),
        "wk": linear("model.layers.{}.self_attn.k_proj.weight"),
        "wv": linear("model.layers.{}.self_attn.v_proj.weight"),
        "wo": linear("model.layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": vector("model.layers.{}.post_attention_layernorm.weight"),
        "wg": linear("model.layers.{}.mlp.gate_proj.weight"),
        "wu": linear("model.layers.{}.mlp.up_proj.weight"),
        "wd": linear("model.layers.{}.mlp.down_proj.weight"),
    }
    if cfg.attn_bias:
        layers["bq"] = vector("model.layers.{}.self_attn.q_proj.bias")
        layers["bk"] = vector("model.layers.{}.self_attn.k_proj.bias")
        layers["bv"] = vector("model.layers.{}.self_attn.v_proj.bias")

    params: dict[str, Any] = {
        "embed": jnp.asarray(_get(tensors, "model.embed_tokens.weight"), dtype=dtype),
        "layers": layers,
        "final_norm": jnp.asarray(_get(tensors, "model.norm.weight"), dtype=dtype),
    }
    head = _maybe(tensors, "lm_head.weight")
    if cfg.tie_embeddings or head is None:
        if not cfg.tie_embeddings and head is None:
            raise CheckpointError(
                "checkpoint has no lm_head.weight but config does not tie embeddings"
            )
    else:
        params["lm_head"] = jnp.asarray(head.T, dtype=dtype)

    # Shape validation against the config.
    v, d = params["embed"].shape
    if v != cfg.vocab_size or d != cfg.hidden_size:
        raise CheckpointError(
            f"embed shape {(v, d)} does not match config "
            f"({cfg.vocab_size}, {cfg.hidden_size})"
        )
    return params


def save_checkpoint(path: str, params: dict[str, Any]) -> None:
    """Write params back out as a single HF-style safetensors file (testing
    and fine-tune export)."""
    from safetensors.numpy import save_file

    flat: dict[str, np.ndarray] = {}
    L = params["layers"]["wq"].shape[0]
    name_map = {
        "attn_norm": "model.layers.{}.input_layernorm.weight",
        "wq": "model.layers.{}.self_attn.q_proj.weight",
        "wk": "model.layers.{}.self_attn.k_proj.weight",
        "wv": "model.layers.{}.self_attn.v_proj.weight",
        "wo": "model.layers.{}.self_attn.o_proj.weight",
        "mlp_norm": "model.layers.{}.post_attention_layernorm.weight",
        "wg": "model.layers.{}.mlp.gate_proj.weight",
        "wu": "model.layers.{}.mlp.up_proj.weight",
        "wd": "model.layers.{}.mlp.down_proj.weight",
        "bq": "model.layers.{}.self_attn.q_proj.bias",
        "bk": "model.layers.{}.self_attn.k_proj.bias",
        "bv": "model.layers.{}.self_attn.v_proj.bias",
    }
    for key, fmt in name_map.items():
        if key not in params["layers"]:
            continue
        stacked = np.asarray(params["layers"][key].astype(jnp.float32))
        for i in range(L):
            mat = stacked[i]
            if mat.ndim == 2:
                mat = mat.T  # back to HF [out, in]
            flat[fmt.format(i)] = np.ascontiguousarray(mat)
    flat["model.embed_tokens.weight"] = np.asarray(
        params["embed"].astype(jnp.float32)
    )
    flat["model.norm.weight"] = np.asarray(params["final_norm"].astype(jnp.float32))
    if "lm_head" in params:
        flat["lm_head.weight"] = np.ascontiguousarray(
            np.asarray(params["lm_head"].astype(jnp.float32)).T
        )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    save_file(flat, path)
