"""Checkpoint loading: HuggingFace safetensors -> stacked param pytree.

Maps the HF llama-family naming scheme (model.layers.N.self_attn.q_proj...)
onto this framework's scan-stacked layout (models/llama.py): per-layer weights
are transposed to [in, out] and stacked along a leading layer dim. Handles
single-file and index-sharded checkpoints. Supports Llama-3 and Qwen2.5
families (attention biases included when present).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..utils.logger import get_logger
from .config import ModelConfig

log = get_logger("loader")


class CheckpointError(Exception):
    pass


def _open_shards(path: str):
    """Yield (name, tensor-loader) for every tensor across all shards."""
    from safetensors import safe_open

    if os.path.isfile(path):
        files = [path]
    else:
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.isfile(index):
            with open(index, "r", encoding="utf-8") as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            files = sorted({os.path.join(path, v) for v in weight_map.values()})
        else:
            files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith(".safetensors")
            )
    if not files:
        raise CheckpointError(f"no .safetensors files under {path}")
    tensors: dict[str, Any] = {}
    for file in files:
        fh = safe_open(file, framework="numpy")
        for name in fh.keys():
            tensors[name] = (fh, name)
    return tensors


def _get(tensors: dict[str, Any], name: str) -> np.ndarray:
    if name not in tensors:
        raise CheckpointError(f"missing tensor {name} in checkpoint")
    fh, key = tensors[name]
    return fh.get_tensor(key)


def _maybe(tensors: dict[str, Any], name: str) -> np.ndarray | None:
    if name not in tensors:
        return None
    fh, key = tensors[name]
    return fh.get_tensor(key)


def _stack_linear(tensors, name_fmt: str, ids: list[int], dtype) -> jnp.ndarray:
    """HF stores [out, in]; we use [in, out]. Stack over the given layers."""
    mats = [_get(tensors, name_fmt.format(i)).T for i in ids]
    return jnp.asarray(np.stack(mats), dtype=dtype)


def _rope_interleave_to_halfsplit(dr: int) -> np.ndarray:
    """Column permutation mapping HF DeepSeek's INTERLEAVED rope pair
    layout (dims 2i/2i+1 rotate together; modeling_deepseek de-interleaves
    activations with view(.., d//2, 2).transpose before rotate_half) onto
    this framework's half-split ``apply_rope`` convention (dim j pairs
    with j + d/2). Permuting the projection's output columns once at load
    is exactly equivalent to HF's runtime de-interleave."""
    return np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])


def _load_mla_attn_block(
    tensors, cfg: ModelConfig, layer_ids: list[int], dtype
) -> dict[str, Any]:
    """MLA attention weights (HF deepseek_v2/v3 naming) -> this
    framework's layout (models/llama._mla_attn_block):

    - ``kv_a_proj_with_mqa`` rows split into the kv latent (wdkv) and the
      shared rope key (wkr);
    - rope-part columns (of q and wkr) permuted from HF's interleaved
      pair order to the half-split order ``apply_rope`` expects;
    - ``o_proj`` columns (HF [d, H*dv]) expand to PADDED per-head rows
      [H*(dn+dr), d] — the pad rows multiply the v zero-padding and are
      zeroed here.
    """
    m = cfg.mla
    d = cfg.hidden_size
    H = cfg.num_heads
    dn, dr, dv = m.qk_nope_head_dim, m.qk_rope_head_dim, m.v_head_dim
    dq = dn + dr
    rkv = m.kv_lora_rank

    def vector(name_fmt: str) -> jnp.ndarray:
        vecs = [_get(tensors, name_fmt.format(i)) for i in layer_ids]
        return jnp.asarray(np.stack(vecs), dtype=dtype)

    def linear(name_fmt: str) -> jnp.ndarray:
        return _stack_linear(tensors, name_fmt, layer_ids, dtype)

    if not layer_ids:
        block: dict[str, Any] = {
            "attn_norm": jnp.zeros((0, d), dtype),
            "wdkv": jnp.zeros((0, d, rkv), dtype),
            "wkr": jnp.zeros((0, d, dr), dtype),
            "kv_norm": jnp.zeros((0, rkv), dtype),
            "wukv": jnp.zeros((0, rkv, H * (dn + dv)), dtype),
            "wo": jnp.zeros((0, H * dq, d), dtype),
            "mlp_norm": jnp.zeros((0, d), dtype),
        }
        if m.q_lora_rank:
            block["wdq"] = jnp.zeros((0, d, m.q_lora_rank), dtype)
            block["q_norm"] = jnp.zeros((0, m.q_lora_rank), dtype)
            block["wuq"] = jnp.zeros((0, m.q_lora_rank, H * dq), dtype)
        else:
            block["wq"] = jnp.zeros((0, d, H * dq), dtype)
        return block

    perm = _rope_interleave_to_halfsplit(dr)

    def fix_q_rope(w: np.ndarray) -> np.ndarray:
        """Permute each head's rope columns of a [in, H*dq] q projection."""
        w = w.reshape(w.shape[0], H, dq)
        return np.concatenate(
            [w[..., :dn], w[..., dn:][..., perm]], axis=-1
        ).reshape(w.shape[0], H * dq)

    block = {
        "attn_norm": vector("model.layers.{}.input_layernorm.weight"),
        "kv_norm": vector("model.layers.{}.self_attn.kv_a_layernorm.weight"),
        "wukv": linear("model.layers.{}.self_attn.kv_b_proj.weight"),
        "mlp_norm": vector("model.layers.{}.post_attention_layernorm.weight"),
    }
    def q_linear(hf_name: str) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([
                fix_q_rope(
                    _get(
                        tensors,
                        f"model.layers.{i}.self_attn.{hf_name}.weight",
                    ).T
                )
                for i in layer_ids
            ]),
            dtype=dtype,
        )

    if m.q_lora_rank:
        block["wdq"] = linear("model.layers.{}.self_attn.q_a_proj.weight")
        block["q_norm"] = vector(
            "model.layers.{}.self_attn.q_a_layernorm.weight"
        )
        block["wuq"] = q_linear("q_b_proj")
    else:
        block["wq"] = q_linear("q_proj")
    # kv_a_proj_with_mqa: HF [rkv + dr, d] -> ours [d, rkv] + [d, dr]
    # (rope columns permuted to half-split order).
    wdkv, wkr = [], []
    for i in layer_ids:
        w = _get(
            tensors, f"model.layers.{i}.self_attn.kv_a_proj_with_mqa.weight"
        ).T  # [d, rkv + dr]
        wdkv.append(w[:, :rkv])
        wkr.append(w[:, rkv:][:, perm])
    block["wdkv"] = jnp.asarray(np.stack(wdkv), dtype=dtype)
    block["wkr"] = jnp.asarray(np.stack(wkr), dtype=dtype)
    # o_proj: HF [d, H*dv] -> ours transposed [H*dv, d], expanded to
    # [H*(dn+dr), d] with zero pad rows per head.
    wo = []
    for i in layer_ids:
        w = _get(tensors, f"model.layers.{i}.self_attn.o_proj.weight").T
        w = w.reshape(H, dv, d)
        pad = np.zeros((H, dq - dv, d), w.dtype)
        wo.append(np.concatenate([w, pad], axis=1).reshape(H * dq, d))
    block["wo"] = jnp.asarray(np.stack(wo), dtype=dtype)
    return block


def _load_attn_block(
    tensors, cfg: ModelConfig, layer_ids: list[int], dtype
) -> dict[str, Any]:
    """Attention weights + norms for an explicit list of HF layer indices,
    stacked in that order. An empty id list (e.g. moe_layer_start=0) yields
    zero-length stacks matching init_params' shapes."""
    if cfg.mla is not None:
        return _load_mla_attn_block(tensors, cfg, layer_ids, dtype)
    d, q, kv = cfg.hidden_size, cfg.q_size, cfg.kv_size
    if not layer_ids:
        block = {
            "attn_norm": jnp.zeros((0, d), dtype),
            "wq": jnp.zeros((0, d, q), dtype),
            "wk": jnp.zeros((0, d, kv), dtype),
            "wv": jnp.zeros((0, d, kv), dtype),
            "wo": jnp.zeros((0, q, d), dtype),
            "mlp_norm": jnp.zeros((0, d), dtype),
        }
        if cfg.attn_bias:
            block["bq"] = jnp.zeros((0, q), dtype)
            block["bk"] = jnp.zeros((0, kv), dtype)
            block["bv"] = jnp.zeros((0, kv), dtype)
        if cfg.qk_norm:
            block["qn"] = jnp.zeros((0, cfg.head_dim_), dtype)
            block["kn"] = jnp.zeros((0, cfg.head_dim_), dtype)
        return block

    def linear(name_fmt: str) -> jnp.ndarray:
        return _stack_linear(tensors, name_fmt, layer_ids, dtype)

    def vector(name_fmt: str) -> jnp.ndarray:
        vecs = [_get(tensors, name_fmt.format(i)) for i in layer_ids]
        return jnp.asarray(np.stack(vecs), dtype=dtype)

    block: dict[str, Any] = {
        "attn_norm": vector("model.layers.{}.input_layernorm.weight"),
        "wq": linear("model.layers.{}.self_attn.q_proj.weight"),
        "wk": linear("model.layers.{}.self_attn.k_proj.weight"),
        "wv": linear("model.layers.{}.self_attn.v_proj.weight"),
        "wo": linear("model.layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": vector("model.layers.{}.post_attention_layernorm.weight"),
    }
    if cfg.attn_bias:
        block["bq"] = vector("model.layers.{}.self_attn.q_proj.bias")
        block["bk"] = vector("model.layers.{}.self_attn.k_proj.bias")
        block["bv"] = vector("model.layers.{}.self_attn.v_proj.bias")
    if cfg.qk_norm:
        # Qwen3 per-head q/k RMSNorm weights ([head_dim] each).
        block["qn"] = vector("model.layers.{}.self_attn.q_norm.weight")
        block["kn"] = vector("model.layers.{}.self_attn.k_norm.weight")
    return block


def load_checkpoint(
    path: str, cfg: ModelConfig, dtype: Any = jnp.bfloat16
) -> dict[str, Any]:
    """Load an HF llama/qwen/deepseek-moe checkpoint into the stacked param
    layout. MoE layers use the DeepSeek naming scheme: ``mlp.gate.weight``
    (router), ``mlp.experts.{e}.{gate,up,down}_proj.weight``, and fused
    ``mlp.shared_experts.{gate,up,down}_proj.weight``.

    This is the slow cold-start path: a host-side parse + transpose +
    restack of every tensor. ``Engine.from_snapshot`` bypasses it
    entirely — snapshot restore (serving/snapshot/restore.py) memory-maps
    leaves already in this stacked device layout."""
    t0 = time.perf_counter()
    tensors = _open_shards(path)
    L = cfg.num_layers
    Ld = cfg.moe_layer_start if cfg.moe is not None else L
    dense_ids, moe_ids = list(range(Ld)), list(range(Ld, L))

    def linear_ids(name_fmt: str, ids: list[int]) -> jnp.ndarray:
        return _stack_linear(tensors, name_fmt, ids, dtype)

    layers = _load_attn_block(tensors, cfg, dense_ids, dtype)
    f = cfg.intermediate_size
    if dense_ids:
        layers.update(
            {
                "wg": linear_ids("model.layers.{}.mlp.gate_proj.weight", dense_ids),
                "wu": linear_ids("model.layers.{}.mlp.up_proj.weight", dense_ids),
                "wd": linear_ids("model.layers.{}.mlp.down_proj.weight", dense_ids),
            }
        )
    else:
        d = cfg.hidden_size
        layers.update(
            {
                "wg": jnp.zeros((0, d, f), dtype),
                "wu": jnp.zeros((0, d, f), dtype),
                "wd": jnp.zeros((0, f, d), dtype),
            }
        )

    params: dict[str, Any] = {
        "embed": jnp.asarray(_get(tensors, "model.embed_tokens.weight"), dtype=dtype),
        "layers": layers,
        "final_norm": jnp.asarray(_get(tensors, "model.norm.weight"), dtype=dtype),
    }

    if moe_ids:
        E = cfg.moe.num_experts
        moe_layers = _load_attn_block(tensors, cfg, moe_ids, dtype)

        def experts(proj: str) -> jnp.ndarray:
            # [Lm, E, in, out]
            mats = [
                np.stack([
                    _get(
                        tensors,
                        f"model.layers.{i}.mlp.experts.{e}.{proj}.weight",
                    ).T
                    for e in range(E)
                ])
                for i in moe_ids
            ]
            return jnp.asarray(np.stack(mats), dtype=dtype)

        moe_layers.update(
            {
                "router": jnp.asarray(
                    np.stack([
                        _get(tensors, f"model.layers.{i}.mlp.gate.weight").T
                        for i in moe_ids
                    ]),
                    dtype=jnp.float32,
                ),
                "eg": experts("gate_proj"),
                "eu": experts("up_proj"),
                "ed": experts("down_proj"),
            }
        )
        if cfg.moe.scoring_func == "sigmoid":
            # V3 noaux_tc selection bias (HF e_score_correction_bias).
            moe_layers["router_bias"] = jnp.asarray(
                np.stack([
                    _get(
                        tensors,
                        f"model.layers.{i}.mlp.gate.e_score_correction_bias",
                    )
                    for i in moe_ids
                ]),
                dtype=jnp.float32,
            )
        if cfg.moe.num_shared_experts:
            moe_layers["sg"] = linear_ids(
                "model.layers.{}.mlp.shared_experts.gate_proj.weight", moe_ids
            )
            moe_layers["su"] = linear_ids(
                "model.layers.{}.mlp.shared_experts.up_proj.weight", moe_ids
            )
            moe_layers["sd"] = linear_ids(
                "model.layers.{}.mlp.shared_experts.down_proj.weight", moe_ids
            )
        params["moe_layers"] = moe_layers
    head = _maybe(tensors, "lm_head.weight")
    if cfg.tie_embeddings or head is None:
        if not cfg.tie_embeddings and head is None:
            raise CheckpointError(
                "checkpoint has no lm_head.weight but config does not tie embeddings"
            )
    else:
        params["lm_head"] = jnp.asarray(head.T, dtype=dtype)

    # Shape validation against the config.
    v, d = params["embed"].shape
    if v != cfg.vocab_size or d != cfg.hidden_size:
        raise CheckpointError(
            f"embed shape {(v, d)} does not match config "
            f"({cfg.vocab_size}, {cfg.hidden_size})"
        )
    log.info(
        "checkpoint %s parsed/restacked in %.1f s", path,
        time.perf_counter() - t0,
    )
    return params


_ATTN_NAME_MAP = {
    "attn_norm": "model.layers.{}.input_layernorm.weight",
    "wq": "model.layers.{}.self_attn.q_proj.weight",
    "wk": "model.layers.{}.self_attn.k_proj.weight",
    "wv": "model.layers.{}.self_attn.v_proj.weight",
    "wo": "model.layers.{}.self_attn.o_proj.weight",
    "mlp_norm": "model.layers.{}.post_attention_layernorm.weight",
    "bq": "model.layers.{}.self_attn.q_proj.bias",
    "bk": "model.layers.{}.self_attn.k_proj.bias",
    "bv": "model.layers.{}.self_attn.v_proj.bias",
    "qn": "model.layers.{}.self_attn.q_norm.weight",
    "kn": "model.layers.{}.self_attn.k_norm.weight",
}

_DENSE_MLP_NAME_MAP = {
    "wg": "model.layers.{}.mlp.gate_proj.weight",
    "wu": "model.layers.{}.mlp.up_proj.weight",
    "wd": "model.layers.{}.mlp.down_proj.weight",
}

_SHARED_NAME_MAP = {
    "sg": "model.layers.{}.mlp.shared_experts.gate_proj.weight",
    "su": "model.layers.{}.mlp.shared_experts.up_proj.weight",
    "sd": "model.layers.{}.mlp.shared_experts.down_proj.weight",
}

_EXPERT_NAME_MAP = {
    "eg": "model.layers.{}.mlp.experts.{}.gate_proj.weight",
    "eu": "model.layers.{}.mlp.experts.{}.up_proj.weight",
    "ed": "model.layers.{}.mlp.experts.{}.down_proj.weight",
}


def _dump_block(
    flat: dict[str, np.ndarray],
    block: dict[str, Any],
    name_map: dict[str, str],
    layer_offset: int,
) -> None:
    for key, fmt in name_map.items():
        if key not in block:
            continue
        stacked = np.asarray(block[key].astype(jnp.float32))
        for i in range(stacked.shape[0]):
            mat = stacked[i]
            if mat.ndim == 2:
                mat = mat.T  # back to HF [out, in]
            flat[fmt.format(i + layer_offset)] = np.ascontiguousarray(mat)


def _dump_mla_block(
    flat: dict[str, np.ndarray],
    block: dict[str, Any],
    layer_offset: int,
    cfg: ModelConfig,
) -> None:
    """Inverse of ``_load_mla_attn_block``: recombine wdkv/wkr into
    kv_a_proj_with_mqa and strip wo's pad rows back to [d, H*dv]."""
    m = cfg.mla
    H = cfg.num_heads
    dn, dr, dv = m.qk_nope_head_dim, m.qk_rope_head_dim, m.v_head_dim
    dq = dn + dr
    L = block["mlp_norm"].shape[0]
    name_map = {
        "attn_norm": "model.layers.{}.input_layernorm.weight",
        "mlp_norm": "model.layers.{}.post_attention_layernorm.weight",
        "kv_norm": "model.layers.{}.self_attn.kv_a_layernorm.weight",
        "wukv": "model.layers.{}.self_attn.kv_b_proj.weight",
        "wdq": "model.layers.{}.self_attn.q_a_proj.weight",
        "q_norm": "model.layers.{}.self_attn.q_a_layernorm.weight",
    }
    _dump_block(flat, block, name_map, layer_offset)
    # Rope columns back to HF's interleaved order (inverse of the load
    # permutation).
    inv = np.argsort(_rope_interleave_to_halfsplit(dr))

    def unfix_q_rope(w: np.ndarray) -> np.ndarray:
        w = w.reshape(w.shape[0], H, dq)
        return np.concatenate(
            [w[..., :dn], w[..., dn:][..., inv]], axis=-1
        ).reshape(w.shape[0], H * dq)

    q_key, q_name = (
        ("wuq", "q_b_proj") if "wuq" in block else ("wq", "q_proj")
    )
    qw = np.asarray(block[q_key].astype(jnp.float32))
    wdkv = np.asarray(block["wdkv"].astype(jnp.float32))
    wkr = np.asarray(block["wkr"].astype(jnp.float32))
    wo = np.asarray(block["wo"].astype(jnp.float32))
    for i in range(L):
        li = i + layer_offset
        flat[f"model.layers.{li}.self_attn.{q_name}.weight"] = (
            np.ascontiguousarray(unfix_q_rope(qw[i]).T)
        )
        flat[f"model.layers.{li}.self_attn.kv_a_proj_with_mqa.weight"] = (
            np.ascontiguousarray(
                np.concatenate([wdkv[i], wkr[i][:, inv]], axis=1).T
            )
        )
        unpadded = wo[i].reshape(H, dq, -1)[:, :dv].reshape(H * dv, -1)
        flat[f"model.layers.{li}.self_attn.o_proj.weight"] = (
            np.ascontiguousarray(unpadded.T)
        )


def save_checkpoint(
    path: str, params: dict[str, Any], cfg: ModelConfig | None = None
) -> None:
    """Write params back out as a single HF-style safetensors file (testing
    and fine-tune export). MoE stacks round-trip through the DeepSeek naming
    scheme ``load_checkpoint`` reads; MLA models additionally need ``cfg``
    (the pad/split geometry is not recoverable from shapes alone)."""
    from safetensors.numpy import save_file

    flat: dict[str, np.ndarray] = {}
    Ld = params["layers"]["mlp_norm"].shape[0]
    mla = cfg.mla if cfg is not None else None
    if "wdkv" in params["layers"] and mla is None:
        raise ValueError("saving an MLA checkpoint requires cfg")
    if mla is not None:
        _dump_mla_block(flat, params["layers"], 0, cfg)
        _dump_block(flat, params["layers"], _DENSE_MLP_NAME_MAP, 0)
    else:
        _dump_block(
            flat, params["layers"],
            {**_ATTN_NAME_MAP, **_DENSE_MLP_NAME_MAP}, 0,
        )
    if "moe_layers" in params:
        moe = params["moe_layers"]
        if mla is not None:
            _dump_mla_block(flat, moe, Ld, cfg)
            _dump_block(flat, moe, _SHARED_NAME_MAP, Ld)
        else:
            _dump_block(flat, moe, {**_ATTN_NAME_MAP, **_SHARED_NAME_MAP}, Ld)
        if "router_bias" in moe:
            rb = np.asarray(moe["router_bias"].astype(jnp.float32))
            for i in range(rb.shape[0]):
                flat[
                    f"model.layers.{i + Ld}.mlp.gate.e_score_correction_bias"
                ] = np.ascontiguousarray(rb[i])
        router = np.asarray(moe["router"].astype(jnp.float32))
        for i in range(router.shape[0]):
            flat[f"model.layers.{i + Ld}.mlp.gate.weight"] = (
                np.ascontiguousarray(router[i].T)
            )
        for key, fmt in _EXPERT_NAME_MAP.items():
            stacked = np.asarray(moe[key].astype(jnp.float32))
            for i in range(stacked.shape[0]):
                for e in range(stacked.shape[1]):
                    flat[fmt.format(i + Ld, e)] = np.ascontiguousarray(
                        stacked[i, e].T
                    )
    flat["model.embed_tokens.weight"] = np.asarray(
        params["embed"].astype(jnp.float32)
    )
    flat["model.norm.weight"] = np.asarray(params["final_norm"].astype(jnp.float32))
    if "lm_head" in params:
        flat["lm_head.weight"] = np.ascontiguousarray(
            np.asarray(params["lm_head"].astype(jnp.float32)).T
        )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    save_file(flat, path)
