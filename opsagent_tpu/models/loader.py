"""Checkpoint loading: HuggingFace safetensors -> stacked param pytree.

Maps the HF llama-family naming scheme (model.layers.N.self_attn.q_proj...)
onto this framework's scan-stacked layout (models/llama.py): per-layer weights
are transposed to [in, out] and stacked along a leading layer dim. Handles
single-file and index-sharded checkpoints. Supports Llama-3 and Qwen2.5
families (attention biases included when present).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from .config import ModelConfig


class CheckpointError(Exception):
    pass


def _open_shards(path: str):
    """Yield (name, tensor-loader) for every tensor across all shards."""
    from safetensors import safe_open

    if os.path.isfile(path):
        files = [path]
    else:
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.isfile(index):
            with open(index, "r", encoding="utf-8") as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            files = sorted({os.path.join(path, v) for v in weight_map.values()})
        else:
            files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith(".safetensors")
            )
    if not files:
        raise CheckpointError(f"no .safetensors files under {path}")
    tensors: dict[str, Any] = {}
    for file in files:
        fh = safe_open(file, framework="numpy")
        for name in fh.keys():
            tensors[name] = (fh, name)
    return tensors


def _get(tensors: dict[str, Any], name: str) -> np.ndarray:
    if name not in tensors:
        raise CheckpointError(f"missing tensor {name} in checkpoint")
    fh, key = tensors[name]
    return fh.get_tensor(key)


def _maybe(tensors: dict[str, Any], name: str) -> np.ndarray | None:
    if name not in tensors:
        return None
    fh, key = tensors[name]
    return fh.get_tensor(key)


def _stack_linear(tensors, name_fmt: str, ids: list[int], dtype) -> jnp.ndarray:
    """HF stores [out, in]; we use [in, out]. Stack over the given layers."""
    mats = [_get(tensors, name_fmt.format(i)).T for i in ids]
    return jnp.asarray(np.stack(mats), dtype=dtype)


def _load_attn_block(
    tensors, cfg: ModelConfig, layer_ids: list[int], dtype
) -> dict[str, Any]:
    """Attention weights + norms for an explicit list of HF layer indices,
    stacked in that order. An empty id list (e.g. moe_layer_start=0) yields
    zero-length stacks matching init_params' shapes."""
    d, q, kv = cfg.hidden_size, cfg.q_size, cfg.kv_size
    if not layer_ids:
        block = {
            "attn_norm": jnp.zeros((0, d), dtype),
            "wq": jnp.zeros((0, d, q), dtype),
            "wk": jnp.zeros((0, d, kv), dtype),
            "wv": jnp.zeros((0, d, kv), dtype),
            "wo": jnp.zeros((0, q, d), dtype),
            "mlp_norm": jnp.zeros((0, d), dtype),
        }
        if cfg.attn_bias:
            block["bq"] = jnp.zeros((0, q), dtype)
            block["bk"] = jnp.zeros((0, kv), dtype)
            block["bv"] = jnp.zeros((0, kv), dtype)
        return block

    def linear(name_fmt: str) -> jnp.ndarray:
        return _stack_linear(tensors, name_fmt, layer_ids, dtype)

    def vector(name_fmt: str) -> jnp.ndarray:
        vecs = [_get(tensors, name_fmt.format(i)) for i in layer_ids]
        return jnp.asarray(np.stack(vecs), dtype=dtype)

    block: dict[str, Any] = {
        "attn_norm": vector("model.layers.{}.input_layernorm.weight"),
        "wq": linear("model.layers.{}.self_attn.q_proj.weight"),
        "wk": linear("model.layers.{}.self_attn.k_proj.weight"),
        "wv": linear("model.layers.{}.self_attn.v_proj.weight"),
        "wo": linear("model.layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": vector("model.layers.{}.post_attention_layernorm.weight"),
    }
    if cfg.attn_bias:
        block["bq"] = vector("model.layers.{}.self_attn.q_proj.bias")
        block["bk"] = vector("model.layers.{}.self_attn.k_proj.bias")
        block["bv"] = vector("model.layers.{}.self_attn.v_proj.bias")
    return block


def load_checkpoint(
    path: str, cfg: ModelConfig, dtype: Any = jnp.bfloat16
) -> dict[str, Any]:
    """Load an HF llama/qwen/deepseek-moe checkpoint into the stacked param
    layout. MoE layers use the DeepSeek naming scheme: ``mlp.gate.weight``
    (router), ``mlp.experts.{e}.{gate,up,down}_proj.weight``, and fused
    ``mlp.shared_experts.{gate,up,down}_proj.weight``."""
    tensors = _open_shards(path)
    L = cfg.num_layers
    Ld = cfg.moe_layer_start if cfg.moe is not None else L
    dense_ids, moe_ids = list(range(Ld)), list(range(Ld, L))

    def linear_ids(name_fmt: str, ids: list[int]) -> jnp.ndarray:
        return _stack_linear(tensors, name_fmt, ids, dtype)

    layers = _load_attn_block(tensors, cfg, dense_ids, dtype)
    f = cfg.intermediate_size
    if dense_ids:
        layers.update(
            {
                "wg": linear_ids("model.layers.{}.mlp.gate_proj.weight", dense_ids),
                "wu": linear_ids("model.layers.{}.mlp.up_proj.weight", dense_ids),
                "wd": linear_ids("model.layers.{}.mlp.down_proj.weight", dense_ids),
            }
        )
    else:
        d = cfg.hidden_size
        layers.update(
            {
                "wg": jnp.zeros((0, d, f), dtype),
                "wu": jnp.zeros((0, d, f), dtype),
                "wd": jnp.zeros((0, f, d), dtype),
            }
        )

    params: dict[str, Any] = {
        "embed": jnp.asarray(_get(tensors, "model.embed_tokens.weight"), dtype=dtype),
        "layers": layers,
        "final_norm": jnp.asarray(_get(tensors, "model.norm.weight"), dtype=dtype),
    }

    if moe_ids:
        E = cfg.moe.num_experts
        moe_layers = _load_attn_block(tensors, cfg, moe_ids, dtype)

        def experts(proj: str) -> jnp.ndarray:
            # [Lm, E, in, out]
            mats = [
                np.stack([
                    _get(
                        tensors,
                        f"model.layers.{i}.mlp.experts.{e}.{proj}.weight",
                    ).T
                    for e in range(E)
                ])
                for i in moe_ids
            ]
            return jnp.asarray(np.stack(mats), dtype=dtype)

        moe_layers.update(
            {
                "router": jnp.asarray(
                    np.stack([
                        _get(tensors, f"model.layers.{i}.mlp.gate.weight").T
                        for i in moe_ids
                    ]),
                    dtype=jnp.float32,
                ),
                "eg": experts("gate_proj"),
                "eu": experts("up_proj"),
                "ed": experts("down_proj"),
            }
        )
        if cfg.moe.num_shared_experts:
            moe_layers["sg"] = linear_ids(
                "model.layers.{}.mlp.shared_experts.gate_proj.weight", moe_ids
            )
            moe_layers["su"] = linear_ids(
                "model.layers.{}.mlp.shared_experts.up_proj.weight", moe_ids
            )
            moe_layers["sd"] = linear_ids(
                "model.layers.{}.mlp.shared_experts.down_proj.weight", moe_ids
            )
        params["moe_layers"] = moe_layers
    head = _maybe(tensors, "lm_head.weight")
    if cfg.tie_embeddings or head is None:
        if not cfg.tie_embeddings and head is None:
            raise CheckpointError(
                "checkpoint has no lm_head.weight but config does not tie embeddings"
            )
    else:
        params["lm_head"] = jnp.asarray(head.T, dtype=dtype)

    # Shape validation against the config.
    v, d = params["embed"].shape
    if v != cfg.vocab_size or d != cfg.hidden_size:
        raise CheckpointError(
            f"embed shape {(v, d)} does not match config "
            f"({cfg.vocab_size}, {cfg.hidden_size})"
        )
    return params


_ATTN_NAME_MAP = {
    "attn_norm": "model.layers.{}.input_layernorm.weight",
    "wq": "model.layers.{}.self_attn.q_proj.weight",
    "wk": "model.layers.{}.self_attn.k_proj.weight",
    "wv": "model.layers.{}.self_attn.v_proj.weight",
    "wo": "model.layers.{}.self_attn.o_proj.weight",
    "mlp_norm": "model.layers.{}.post_attention_layernorm.weight",
    "bq": "model.layers.{}.self_attn.q_proj.bias",
    "bk": "model.layers.{}.self_attn.k_proj.bias",
    "bv": "model.layers.{}.self_attn.v_proj.bias",
}

_DENSE_MLP_NAME_MAP = {
    "wg": "model.layers.{}.mlp.gate_proj.weight",
    "wu": "model.layers.{}.mlp.up_proj.weight",
    "wd": "model.layers.{}.mlp.down_proj.weight",
}

_SHARED_NAME_MAP = {
    "sg": "model.layers.{}.mlp.shared_experts.gate_proj.weight",
    "su": "model.layers.{}.mlp.shared_experts.up_proj.weight",
    "sd": "model.layers.{}.mlp.shared_experts.down_proj.weight",
}

_EXPERT_NAME_MAP = {
    "eg": "model.layers.{}.mlp.experts.{}.gate_proj.weight",
    "eu": "model.layers.{}.mlp.experts.{}.up_proj.weight",
    "ed": "model.layers.{}.mlp.experts.{}.down_proj.weight",
}


def _dump_block(
    flat: dict[str, np.ndarray],
    block: dict[str, Any],
    name_map: dict[str, str],
    layer_offset: int,
) -> None:
    for key, fmt in name_map.items():
        if key not in block:
            continue
        stacked = np.asarray(block[key].astype(jnp.float32))
        for i in range(stacked.shape[0]):
            mat = stacked[i]
            if mat.ndim == 2:
                mat = mat.T  # back to HF [out, in]
            flat[fmt.format(i + layer_offset)] = np.ascontiguousarray(mat)


def save_checkpoint(path: str, params: dict[str, Any]) -> None:
    """Write params back out as a single HF-style safetensors file (testing
    and fine-tune export). MoE stacks round-trip through the DeepSeek naming
    scheme ``load_checkpoint`` reads."""
    from safetensors.numpy import save_file

    flat: dict[str, np.ndarray] = {}
    Ld = params["layers"]["wq"].shape[0]
    _dump_block(flat, params["layers"], {**_ATTN_NAME_MAP, **_DENSE_MLP_NAME_MAP}, 0)
    if "moe_layers" in params:
        moe = params["moe_layers"]
        _dump_block(flat, moe, {**_ATTN_NAME_MAP, **_SHARED_NAME_MAP}, Ld)
        router = np.asarray(moe["router"].astype(jnp.float32))
        for i in range(router.shape[0]):
            flat[f"model.layers.{i + Ld}.mlp.gate.weight"] = (
                np.ascontiguousarray(router[i].T)
            )
        for key, fmt in _EXPERT_NAME_MAP.items():
            stacked = np.asarray(moe[key].astype(jnp.float32))
            for i in range(stacked.shape[0]):
                for e in range(stacked.shape[1]):
                    flat[fmt.format(i + Ld, e)] = np.ascontiguousarray(
                        stacked[i, e].T
                    )
    flat["model.embed_tokens.weight"] = np.asarray(
        params["embed"].astype(jnp.float32)
    )
    flat["model.norm.weight"] = np.asarray(params["final_norm"].astype(jnp.float32))
    if "lm_head" in params:
        flat["lm_head.weight"] = np.ascontiguousarray(
            np.asarray(params["lm_head"].astype(jnp.float32)).T
        )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    save_file(flat, path)
