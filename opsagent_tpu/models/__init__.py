from .config import ModelConfig, get_config_preset, PRESETS

__all__ = ["ModelConfig", "get_config_preset", "PRESETS"]
