"""Weight-only int8 quantization for serving.

The decode step is HBM-bandwidth-bound: every step streams the full weight
set. Storing weights as int8 with per-output-channel symmetric scales
halves that traffic (and halves the footprint — Llama-3-8B drops from
~16 GB bf16, which does NOT fit a 16 GB v5e chip, to ~8 GB, which does).
Compute stays on the bf16 MXU path: XLA fuses the dequantize
(int8 -> bf16 multiply by scale) into the matmul operand read, so there is
no separate materialized dequantized copy.

Design: a ``QuantizedLinear`` pytree leaf-pair {q: int8 [..., in, out],
scale: [..., out]} that the model's matmul helper (``llama._mm``)
dispatches on — model code is otherwise unchanged, and the quantized tree
shards with the same PartitionSpecs (the scale follows its weight's output
axis). Per-channel symmetric scaling keeps greedy decoding faithful
(weight-only int8 is the standard near-lossless serving configuration; no
activation quantization).

The reference has no counterpart — its "model" is a remote HTTPS API
(reference pkg/llms/openai.go:69); quantization is part of the in-tree
serving engine that replaces it.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@jax.tree_util.register_pytree_node_class
class QuantizedLinear:
    """int8 weight + per-output-channel scale; acts as a matmul rhs."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q          # int8, [..., in, out]
        self.scale = scale  # float32, [..., 1, out]

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequantize(self) -> jax.Array:
        return self.q.astype(self.scale.dtype) * self.scale


def quantize_weight(w: jax.Array) -> QuantizedLinear:
    """Symmetric per-output-channel int8: scale = absmax / 127 over the
    input (contraction) axis, which is axis -2 of our [in, out] layout.
    Scales stay float32 — they are [..., 1, out] (a few MB even at 8B),
    and a bf16 scale would add ~0.4% multiplicative error per channel on
    top of the int8 rounding."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QuantizedLinear(q.astype(jnp.int8), scale.astype(jnp.float32))


# Weights worth quantizing: the big matmuls. Norm vectors, biases, and the
# f32 router stay exact (tiny, and routing is precision-sensitive).
_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "wg", "wu", "wd",
     "eg", "eu", "ed", "sg", "su", "sd", "lm_head",
     # MLA projections (models/llama._mla_attn_block); the low-rank
     # norms stay exact like other norm vectors.
     "wdq", "wuq", "wdkv", "wkr", "wukv"}
)


def quantize_params(params: dict[str, Any]) -> dict[str, Any]:
    """Quantize every large linear in the stacked param tree (embed stays
    in compute dtype: its gather reads one row per token, not the whole
    table, so int8 would save little and cost a per-token dequant).

    MUST run on host-resident weights for large models: the whole point
    is that the full-precision tree does not fit the chip — the engine
    loads/initializes under a CPU default device, quantizes there, and
    only then device_puts the int8 tree onto the mesh."""

    def walk(tree: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, leaf in tree.items():
            if isinstance(leaf, dict):
                out[key] = walk(leaf)
            elif key in _QUANT_KEYS:
                out[key] = quantize_weight(leaf)
            else:
                out[key] = leaf
        return out

    return walk(params)


def quantize_specs(specs: dict[str, Any]) -> dict[str, Any]:
    """PartitionSpec tree STRUCTURALLY matching ``quantize_params``' output
    (quantized leaves become QuantizedLinear nodes whose children are the
    weight's spec and the scale's spec, so jax.tree.map pairs them): the
    int8 weight keeps its spec; the scale broadcasts over the contraction
    axis (None) and shards with the weight's output axis."""

    def scale_spec(spec: P) -> P:
        # [..., in, out] weight -> [..., 1, out] scale: same rank; only
        # the -2 (contraction) entry must be unsharded.
        parts = list(spec)
        if len(parts) >= 2:
            parts[-2] = None
        return P(*parts)

    def walk(tree: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, leaf in tree.items():
            if isinstance(leaf, dict):
                out[key] = walk(leaf)
            elif key in _QUANT_KEYS:
                out[key] = QuantizedLinear(leaf, scale_spec(leaf))
            else:
                out[key] = leaf
        return out

    return walk(specs)
