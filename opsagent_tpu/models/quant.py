"""Weight-only int8 / int4 quantization for serving.

The decode step is HBM-bandwidth-bound: every step streams the full weight
set. Storing weights as int8 with per-output-channel symmetric scales
halves that traffic (and halves the footprint — Llama-3-8B drops from
~16 GB bf16, which does NOT fit a 16 GB v5e chip, to ~8 GB, which does);
int4 with per-(group, output-channel) scales halves it AGAIN (~4 GB),
roughly doubling the weight-streaming decode ceiling at the cost of more
rounding error (group-wise scaling — default group 128 along the
contraction axis — keeps that error local). Compute stays on the bf16
MXU path: XLA fuses the dequantize (intN -> bf16 multiply by scale) into
the matmul operand read, so there is no separate materialized
dequantized copy; int4 values travel two-nibbles-per-byte in
self-packed int8 (see QuantizedLinear4).

Design: a ``QuantizedLinear`` pytree leaf-pair {q: int8 [..., in, out],
scale: [..., out]} that the model's matmul helper (``llama._mm``)
dispatches on — model code is otherwise unchanged, and the quantized tree
shards with the same PartitionSpecs (the scale follows its weight's output
axis). Per-channel symmetric scaling keeps greedy decoding faithful
(weight-only int8 is the standard near-lossless serving configuration; no
activation quantization).

The reference has no counterpart — its "model" is a remote HTTPS API
(reference pkg/llms/openai.go:69); quantization is part of the in-tree
serving engine that replaces it.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class QuantizedBase:
    """Common shell of the quantized-weight pytree leaves: {q, scale}
    pair, flattening, and array-like shape accessors. Model code
    dispatches on THIS class (``llama._mm``/``_ein``/``_dense_weight``),
    so adding a new width cannot silently miss a dispatch site."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequantize(self) -> jax.Array:  # pragma: no cover - abstract
        raise NotImplementedError


@jax.tree_util.register_pytree_node_class
class QuantizedLinear(QuantizedBase):
    """int8 weight [..., in, out] + per-output-channel float32 scale
    [..., 1, out]; acts as a matmul rhs."""

    def dequantize(self) -> jax.Array:
        return self.q.astype(self.scale.dtype) * self.scale


def quantize_weight(w: jax.Array) -> QuantizedLinear:
    """Symmetric per-output-channel int8: scale = absmax / 127 over the
    input (contraction) axis, which is axis -2 of our [in, out] layout.
    Scales stay float32 — they are [..., 1, out] (a few MB even at 8B),
    and a bf16 scale would add ~0.4% multiplicative error per channel on
    top of the int8 rounding."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QuantizedLinear(q.astype(jnp.int8), scale.astype(jnp.float32))


INT4_GROUP = 128  # contraction-axis group size (GPTQ/AWQ convention)


@jax.tree_util.register_pytree_node_class
class QuantizedLinear4(QuantizedBase):
    """int4 weight + per-(contraction-group, output-channel) scale.

    ``q`` is int8 [..., in/2, out] with TWO int4 values packed per byte
    along the contraction axis (low nibble = even row, high nibble = odd
    row); ``scale`` is float32 [..., G, 1, out] with G = in // group.
    Self-packed int8 rather than native jnp.int4 because jit-argument
    resharding of sub-byte arrays recursively re-enters jit inside
    device_put (measured on-chip r04: RecursionError at the 8B int4
    bench's first prefill) — the byte-level HBM traffic is identical
    (4 bits/weight) and XLA fuses the unpack shifts into the consuming
    matmul's operand read. Dequantize unpacks, then reshapes the
    contraction axis into (G, group) so each group's scale broadcasts
    over its slice, exactly like the int8 path."""

    @property
    def shape(self):
        *lead, half, out = self.q.shape
        return (*lead, 2 * half, out)

    def dequantize(self) -> jax.Array:
        *lead, half, Out = self.q.shape
        In = 2 * half
        G = self.scale.shape[-3]
        # Arithmetic shifts sign-extend on int8: (p << 4) >> 4 recovers
        # the low nibble's signed value, p >> 4 the high nibble's.
        low = jax.lax.shift_right_arithmetic(
            jax.lax.shift_left(self.q, jnp.int8(4)), jnp.int8(4)
        )
        high = jax.lax.shift_right_arithmetic(self.q, jnp.int8(4))
        w = jnp.stack([low, high], axis=-2)  # [..., in/2, 2, out]
        w = w.astype(self.scale.dtype).reshape(*lead, G, In // G, Out)
        return (w * self.scale).reshape(*lead, In, Out)


def pack_int4(q: jax.Array) -> jax.Array:
    """[-8, 7]-valued int8 [..., in, out] -> packed int8 [..., in/2, out]
    (even rows in the low nibble, odd rows in the high). ``in`` must be
    even — every transformer contraction dim is."""
    *lead, In, Out = q.shape
    if In % 2:
        raise ValueError(f"int4 packing needs an even contraction dim, got {In}")
    q = q.astype(jnp.int8).reshape(*lead, In // 2, 2, Out)
    low = q[..., 0, :] & jnp.int8(0x0F)
    high = jax.lax.shift_left(q[..., 1, :], jnp.int8(4))
    return high | low


def _group_size(In: int, group: int) -> int:
    """Largest divisor of ``In`` that is <= ``group``: a contraction dim
    that 128 does not divide (e.g. 4544 -> 64) still gets fine-grained
    scales instead of silently collapsing to one whole-axis group (which
    is exactly the fidelity regime group-wise int4 exists to avoid).
    Dims with no divisor >= 16 fall back to the whole axis — per-element
    scales would cost more HBM than the int4 saves — with a warning."""
    for d in range(min(group, In), 0, -1):
        if In % d == 0:
            if d >= 16:
                return d
            break
    from ..utils.logger import get_logger

    get_logger("quant").warning(
        "int4 group scaling degraded to ONE whole-axis group for a "
        "%d-wide contraction axis (no divisor in [16, %d]); expect "
        "int8-without-groups-level rounding error on these weights",
        In, group,
    )
    return In


def quantize_weight4(w: jax.Array, group: int = INT4_GROUP) -> QuantizedLinear4:
    """Symmetric group-wise int4: the contraction axis splits into
    ``group``-sized slices (``_group_size`` adapts to non-multiple dims),
    scale = group absmax / 7, values clipped to the symmetric [-7, 7]
    range."""
    *lead, In, Out = w.shape
    g = _group_size(In, group) if group else In
    G = In // g
    wg = w.astype(jnp.float32).reshape(*lead, G, g, Out)
    absmax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)  # [..., G, 1, out]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(wg / scale), -7, 7)
    return QuantizedLinear4(
        pack_int4(q.astype(jnp.int8).reshape(*lead, In, Out)),
        scale.astype(jnp.float32),
    )


# Weights worth quantizing: the big matmuls. Norm vectors, biases, and the
# f32 router stay exact (tiny, and routing is precision-sensitive).
_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "wg", "wu", "wd",
     "eg", "eu", "ed", "sg", "su", "sd", "lm_head",
     # MLA projections (models/llama._mla_attn_block); the low-rank
     # norms stay exact like other norm vectors.
     "wdq", "wuq", "wdkv", "wkr", "wukv"}
)


def quantize_params(
    params: dict[str, Any], mode: str = "int8"
) -> dict[str, Any]:
    """Quantize every large linear in the stacked param tree (embed stays
    in compute dtype: its gather reads one row per token, not the whole
    table, so intN would save little and cost a per-token dequant).
    ``mode``: "int8" (per-output-channel) or "int4" (group-wise).

    MUST run on host-resident weights for large models: the whole point
    is that the full-precision tree does not fit the chip — the engine
    loads/initializes under a CPU default device, quantizes there, and
    only then device_puts the quantized tree onto the mesh."""
    quant = {"int8": quantize_weight, "int4": quantize_weight4}[mode]

    def walk(tree: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, leaf in tree.items():
            if isinstance(leaf, dict):
                out[key] = walk(leaf)
            elif key in _QUANT_KEYS:
                out[key] = quant(leaf)
            else:
                out[key] = leaf
        return out

    return walk(params)


def quantize_specs(
    specs: dict[str, Any], mode: str = "int8"
) -> dict[str, Any]:
    """PartitionSpec tree STRUCTURALLY matching ``quantize_params``' output
    (quantized leaves become QuantizedLinear/QuantizedLinear4 nodes whose
    children are the weight's spec and the scale's spec, so jax.tree.map
    pairs them): the intN weight keeps its spec; the scale broadcasts
    over the contraction axis and shards with the weight's output axis.
    int4 group scales REPLICATE over the grouped contraction axis too —
    they are tiny (In/group x Out floats), and sharding G would impose a
    divisibility constraint on every (model dim, tp) pair."""

    def scale_spec(spec: P) -> P:
        # [..., in, out] weight -> [..., 1, out] scale: same rank; only
        # the -2 (contraction) entry must be unsharded.
        parts = list(spec)
        if len(parts) >= 2:
            parts[-2] = None
        return P(*parts)

    def scale_spec4(spec: P) -> P:
        # [..., in, out] weight -> [..., G, 1, out] scale: rank + 1, the
        # G and broadcast axes unsharded, out follows the weight.
        parts = list(spec)
        return P(*parts[:-2], None, None, parts[-1])

    def walk(tree: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, leaf in tree.items():
            if isinstance(leaf, dict):
                out[key] = walk(leaf)
            elif key in _QUANT_KEYS:
                if mode == "int4":
                    out[key] = QuantizedLinear4(leaf, scale_spec4(leaf))
                else:
                    out[key] = QuantizedLinear(leaf, scale_spec(leaf))
            else:
                out[key] = leaf
        return out

    return walk(specs)
