"""Pipeline parallelism (the ``pp`` mesh axis): GPipe-style microbatch
pipelining of the layer stack for training.

The reference has no distributed layer at all (SURVEY.md §2.2); pp exists
in this framework so the train step scales across the slow links: the mesh
lays ``pp`` outermost (parallel/mesh.py), so stages map onto DCN across
hosts/slices while each stage's tp/sp collectives stay on intra-slice ICI
— activations cross the slow link once per stage boundary per microbatch,
which is the only traffic pattern that tolerates DCN latency.

tpu-first shape of the implementation:

- ``jax.shard_map`` manual ONLY over ``pp`` (``axis_names={'pp'}``): the
  pipeline schedule — who computes what, when activations move — is
  explicit ``ppermute``; everything else (dp batch sharding, tp Megatron
  splits, sp sequence sharding) stays on GSPMD auto-sharding inside the
  stage, exactly as in the non-pipelined step.
- The schedule is one ``lax.scan`` over M + PP - 1 ticks (static trip
  count — no data-dependent Python control flow). At tick t, stage s runs
  microbatch t - s; activations advance one stage per tick via a
  non-cyclic ``ppermute``. The carry IS the pipeline register between
  stages.
- Stage-local layers: the stacked layer arrays are sharded over ``pp`` on
  their leading (layer) axis (``param_specs_pp``), so each stage scans its
  own L/PP layers — the same single traced layer body as the non-pipelined
  path (models/llama._run_stack).
- The loss head runs replicated after the scan: only the last stage's
  collected activations are final-layer outputs; a scalar ``psum`` over
  ``pp`` selects its loss sums. Non-last stages collect their OWN stage
  outputs (mid-stack activations), compute a meaningless loss from them,
  and have it zeroed by the ``where`` before the psum. This is safe
  because those activations are finite — embeddings or zeros through a
  finite-preserving stack — so neither the discarded forward value nor
  its cotangent (0 * finite in the VJP) can produce NaN. Any schedule
  extension must preserve that finiteness invariant: 0 * inf is NaN.

GPipe (synchronous) rather than interleaved/1F1B: the bubble is
(PP-1)/(M+PP-1), shrinking with more microbatches, and synchronous
scheduling composes with ``jax.grad`` as plain autodiff through the scan —
no hand-written backward schedule.

MoE models (the DeepSeek-class layout, VERDICT r2 weak #7): the layer
stack is dense-then-moe (``moe_layer_start`` dense layers, then MoE). The
MoE stack — where the weight is — shards over ``pp`` ((L - ms) %% PP == 0
required); the small dense prefix stays REPLICATED and logically belongs
to stage 0 (every stage computes it each tick and a ``where`` keeps only
stage 0's result — wasted FLOPs proportional to the 1-3 prefix layers,
in exchange for no special-cased stage program). Expert weights keep
their ``ep``/``tp`` axes inside each stage (GSPMD auto-sharding), so
EP x PP x TP compose on one mesh. The router load-balance aux is
accumulated only over each stage's VALID (non-bubble) microbatches and
averaged back to the non-pipelined scale.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models import llama
from ..models.config import ModelConfig
from .ring import _local_ring_attention


def param_specs_pp(cfg: ModelConfig) -> Any:
    """``models.llama.param_specs`` with the pipelined stack's leading
    (layer) axis sharded over ``pp``: each pipeline stage holds only its
    own layers. Embedding/head/final-norm stay replicated over pp (stage 0
    embeds, the last stage projects; replication keeps the spec simple and
    the arrays are small next to the layer stack). For MoE models only the
    MoE stack pipelines; the small dense prefix stays replicated (it runs
    on stage 0 — see the module docstring)."""
    specs = llama.param_specs(cfg)

    def stage_shard(spec: P) -> P:
        return P("pp", *spec[1:])

    if "moe_layers" in specs:
        specs["moe_layers"] = {
            k: stage_shard(s) for k, s in specs["moe_layers"].items()
        }
    else:
        specs["layers"] = {
            k: stage_shard(s) for k, s in specs["layers"].items()
        }
    return specs


def make_pipeline_loss(
    cfg: ModelConfig,
    mesh: Mesh,
    microbatches: int,
    dtype: jnp.dtype = jnp.bfloat16,
    remat: bool = False,
    moe_aux_weight: float = 0.0,
) -> Callable:
    """Build ``loss_fn(params, tokens [B,S], loss_mask [B,S]) ->
    (loss, (ce, aux))`` running the layer stack as a PP-stage pipeline.
    Drop-in for the trainer's loss path; params must be sharded with
    ``param_specs_pp``. Requires B %% microbatches == 0 and (dense models)
    L %% PP == 0 / (MoE models) (L - moe_layer_start) %% PP == 0.
    """
    PP = mesh.shape["pp"]
    SP = mesh.shape["sp"]
    M = microbatches
    is_moe = cfg.moe is not None
    Ld, Lm = llama._layer_split(cfg)
    if is_moe:
        if Lm % PP:
            raise ValueError(
                f"moe layers {Lm} not divisible by pp={PP}"
            )
    elif cfg.num_layers % PP:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by pp={PP}"
        )

    def run_stage(stage_params: Any, x: jax.Array, cos, sin):
        """Run a slice of the stack; returns (x, summed MoE aux).

        With sp > 1 the stage's sequence dim is the LOCAL shard and
        attention runs the sp-axis ppermute ring (parallel/ring.py) —
        pipeline stages and the ring compose because both are manual
        axes of the same shard_map."""
        mb, S = x.shape[:2]

        def attn_fn(h, lp, kc, vc, li):
            q, k, v = llama._qkv_rope(h, lp, cfg, cos, sin)
            if SP > 1:
                lengths = jnp.full((mb,), S * SP, jnp.int32)
                attn = _local_ring_attention(q, k, v, lengths, axis="sp")
            else:
                from ..ops.attention import causal_prefill_attention

                attn = causal_prefill_attention(q, k, v)
            return attn.reshape(mb, S, -1), kc, vc

        x, _, aux = llama._run_stack(
            stage_params, cfg, x, attn_fn, cache=None, remat=remat,
            stacks=tuple(stage_params),
        )
        return x, aux

    def pipelined(params, tokens, loss_mask):
        # Inside shard_map manual over (pp, dp): tokens are the per-dp-shard
        # slice, so microbatching divides the LOCAL batch.
        B, S = tokens.shape
        if B % M:
            raise ValueError(
                f"per-dp batch {B} not divisible by microbatches {M}"
            )
        mb = B // M
        stage = jax.lax.axis_index("pp")
        is_last = stage == PP - 1

        # Positions are GLOBAL: with sp > 1 this stage sees the local
        # sequence shard [sp_idx*S, (sp_idx+1)*S) of the full sequence.
        sp_idx = jax.lax.axis_index("sp")
        positions = (sp_idx * S + jnp.arange(S))[None, :].repeat(mb, axis=0)
        from ..ops.rope import rope_table

        cos, sin = rope_table(positions, cfg.rope_dim_, cfg.rope_theta,
                          scaling=cfg.rope_scaling)

        # Embedding is replicated over pp: every stage computes the same
        # xs, only stage 0's enters the pipeline (the where below).
        xs = params["embed"][tokens].astype(dtype)
        xs = xs.reshape(M, mb, S, -1)

        d = xs.shape[-1]
        # pcast: the carry starts as constant zeros but becomes varying
        # over the manual axes inside the scan (each stage and dp shard
        # holds different activations); the varying-manual-axes type must
        # match between scan input and output.
        outs0 = jax.lax.pcast(
            jnp.zeros((M, mb, S, d), dtype), ("pp", "dp", "sp"), to="varying"
        )
        reg0 = jax.lax.pcast(
            jnp.zeros((mb, S, d), dtype), ("pp", "dp", "sp"), to="varying"
        )  # pipeline register
        aux0 = jax.lax.pcast(
            jnp.zeros((), jnp.float32), ("pp", "dp", "sp"), to="varying"
        )

        def tick(carry, t):
            reg, outs, aux_acc = carry
            x_in = jnp.where(
                stage == 0, xs[jnp.clip(t, 0, M - 1)], reg
            )
            if is_moe:
                # The replicated dense prefix logically belongs to stage
                # 0: every stage computes it (Ld is 1-3 layers — cheap
                # next to the stage's Lm/PP MoE layers) and the where
                # keeps only stage 0's result, so all stages run one
                # uniform program. Prefix activations are finite, so the
                # discarded branch preserves the finiteness invariant.
                if Ld:
                    xd, _ = run_stage(
                        {"layers": params["layers"]}, x_in, cos, sin
                    )
                    x_in = jnp.where(stage == 0, xd, x_in)
                h, aux_t = run_stage(
                    {"moe_layers": params["moe_layers"]}, x_in, cos, sin
                )
            else:
                h, aux_t = run_stage(
                    {"layers": params["layers"]}, x_in, cos, sin
                )
            # Router aux only from REAL microbatches: during warmup/drain
            # ticks a stage chews zeros (bubble), whose routing stats
            # would pollute the load-balance signal.
            mb_idx = t - stage
            aux_acc = aux_acc + jnp.where(
                (mb_idx >= 0) & (mb_idx < M), aux_t, 0.0
            )
            # Advance the register one stage (non-cyclic: the last
            # stage's h leaves the pipeline into outs instead).
            reg = jax.lax.ppermute(
                h, "pp", [(i, i + 1) for i in range(PP - 1)]
            )
            out_idx = t - (PP - 1)
            valid = (out_idx >= 0) & (out_idx < M)
            outs = outs.at[jnp.where(valid, out_idx, M)].set(
                h, mode="drop"
            )
            return (reg, outs, aux_acc), None

        (_, outs, aux_acc), _ = jax.lax.scan(
            tick, (reg0, outs0, aux0), jnp.arange(M + PP - 1)
        )

        # Loss head, replicated: only the last stage's outs are final-layer
        # activations; a scalar psum over pp selects its sums. Other
        # stages' outs hold their own mid-stack activations — finite, so
        # the where-discarded loss (and its 0-scaled cotangent) stays
        # finite too. See the module docstring's finiteness invariant.
        x = outs.reshape(B, S, d)
        x = llama.rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        logits = llama._lm_head(params, cfg, x)
        if SP > 1:
            # Next-token shift ACROSS the sp shard boundary: the target
            # of this shard's last position is the NEXT shard's first
            # token, fetched with one ppermute (the true last global
            # position has no target and is masked out).
            shift = [(j, j - 1) for j in range(1, SP)]
            nxt_tok = jax.lax.ppermute(tokens[:, :1], "sp", shift)
            nxt_msk = jax.lax.ppermute(
                loss_mask[:, :1].astype(jnp.float32), "sp", shift
            )
            last_shard = sp_idx == SP - 1
            targets = jnp.concatenate([tokens[:, 1:], nxt_tok], axis=1)
            msk = jnp.concatenate(
                [loss_mask[:, 1:].astype(jnp.float32),
                 jnp.where(last_shard, 0.0, nxt_msk)], axis=1
            )
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, targets[..., None], axis=-1
            )[..., 0]
        else:
            logz = jax.nn.logsumexp(logits[:, :-1], axis=-1)
            gold = jnp.take_along_axis(
                logits[:, :-1], tokens[:, 1:][..., None], axis=-1
            )[..., 0]
            msk = loss_mask[:, 1:].astype(jnp.float32)
        nll_sum = jnp.sum((logz - gold) * msk)
        tok_cnt = jnp.sum(msk)
        sums = jnp.where(
            is_last, jnp.stack([nll_sum, tok_cnt]), jnp.zeros((2,))
        )
        # Global token-mean: over the pipeline (pick the last stage's
        # sums), dp shards (each saw its own batch slice), and sp shards
        # (each saw its own sequence slice).
        sums = jax.lax.psum(sums, ("pp", "dp", "sp"))
        ce = sums[0] / jnp.maximum(sums[1], 1.0)
        # Aux back to the non-pipelined scale: each of the M microbatches
        # contributed its own per-layer routing stats (vs ONE whole-batch
        # stat in the unpipelined step), and dp/sp shards each counted
        # their slice — mean over all of them.
        # Static sizes from the enclosing mesh: jax.lax.axis_size only
        # exists in newer jax than this container ships (0.4.37).
        aux = jax.lax.psum(aux_acc, ("pp", "dp", "sp")) / (
            M * mesh.shape["dp"] * mesh.shape["sp"]
        )
        return ce + moe_aux_weight * aux, (ce, aux)

    base_specs = llama.param_specs(cfg)
    param_in_specs = {
        "embed": P(),
        "final_norm": P(),
    }
    if is_moe:
        # Dense prefix replicated over pp; MoE stack pp-sharded on its
        # leading (layer) axis. ep/tp stay on GSPMD auto-sharding.
        if "layers" in base_specs:
            param_in_specs["layers"] = {
                k: P() for k in base_specs["layers"]
            }
        param_in_specs["moe_layers"] = {
            k: P("pp") for k in base_specs["moe_layers"]
        }
    else:
        param_in_specs["layers"] = {
            k: P("pp") for k in base_specs["layers"]
        }
    if not cfg.tie_embeddings:
        param_in_specs["lm_head"] = P()

    # Manual over pp, dp AND sp (tp/ep stay on GSPMD auto-sharding inside
    # the stage): dp must be manual here because XLA's SPMD partitioner
    # cannot yet mix an auto dp batch dimension with manual-pp collectives
    # (its AllReduceAlongShardingDims hits a device-group CHECK); sp is
    # manual so the stage can run the ring-attention ppermute over it.
    # Manual dp/sp is the same math — shard_map's transpose inserts the
    # gradient psum for the replicated params, exactly what GSPMD would
    # emit.
    return jax.shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(param_in_specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(P(), (P(), P())),
        axis_names={"pp", "dp", "sp"},
    )
