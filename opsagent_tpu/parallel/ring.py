"""Ring attention: causal self-attention with the sequence sharded over the
``sp`` mesh axis (context parallelism).

The reference has no long-context capability at all — it *shrinks* context
instead (observation truncation at 1024 tokens, reference
pkg/assistants/simple.go:495 and pkg/llms/tokens.go:128-144). Here long
sequences are first-class: each device holds S/sp of the sequence; K/V
shards rotate around the ring via ``ppermute`` (XLA lowers it onto the ICI
neighbor links) while every device accumulates flash-attention-style online
softmax statistics for its local queries. Peak memory is O(S/sp) per device
and the K/V transfer overlaps with the block attention compute — the
standard blockwise-parallel/ring formulation (PAPERS.md).

Layout contract (matching ``models.llama`` shardings):
- q: [B, S, H, D] sharded P(dp, sp, tp, None) — heads tensor-parallel
- k/v: [B, S, K, D] sharded P(dp, sp, tp, None)
- out: like q

Causality is resolved by GLOBAL position: device i's queries occupy
[i·S_l, (i+1)·S_l); at ring step s it holds the K/V block of device
(i−s) mod sp, masked with ``k_pos <= q_pos``. Whole blocks that are
entirely future still pay their block compute (simplicity over a skip
heuristic) — for the decode-vs-prefill balance this framework targets the
prefill ring is not the steady-state bottleneck.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _local_ring_attention(
    q: jax.Array,        # [B, S_l, H, D] local shard
    k: jax.Array,        # [B, S_l, K, D]
    v: jax.Array,        # [B, S_l, K, D]
    lengths: jax.Array,  # [B] valid GLOBAL lengths (right padding beyond)
    axis: str,
    sp: int,             # static axis size (mesh.shape[axis]): the ring
                         # step count and perm table need a Python int,
                         # and jax.lax.axis_size only exists in newer jax
                         # than this container ships (0.4.37)
) -> jax.Array:
    idx = jax.lax.axis_index(axis)
    B, S_l, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = D ** -0.5
    # Operands stay in the input dtype (bf16 on TPU) — the MXU accumulates
    # in f32 via preferred_element_type; only softmax statistics are f32.
    qg = (q * jnp.asarray(scale, q.dtype)).reshape(B, S_l, K, G, D)
    q_pos = idx * S_l + jnp.arange(S_l)                    # [S_l]

    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def step(s, carry):
        k_blk, v_blk, m, l, acc = carry
        src = (idx - s) % sp
        k_pos = src * S_l + jnp.arange(S_l)                # [S_l]
        scores = jnp.einsum(
            "bskgd,btkd->bkgst", qg, k_blk,
            preferred_element_type=jnp.float32,
        )                                                   # [B,K,G,S_l,T]
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None, :, :]
        # Ragged batches: positions past a sequence's length are padding —
        # mask them out of every ring step by GLOBAL key position, which is
        # what lets the serving prefill path shard right-padded bucketed
        # prompts over sp.
        valid = (k_pos[None, :] < lengths[:, None])[:, None, None, None, :]
        mask = jnp.logical_and(mask, valid)
        scores = jnp.where(mask, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(scores - m_new)                    # [B,K,G,S_l,T]
        l_new = alpha * l + jnp.sum(probs, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bkgst,btkd->bkgsd", probs.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        # Rotate K/V to the next device; skip on the last step (the block
        # would only be rotated home).
        k_blk, v_blk = jax.lax.cond(
            s < sp - 1,
            lambda kv: (
                jax.lax.ppermute(kv[0], axis, perm),
                jax.lax.ppermute(kv[1], axis, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return k_blk, v_blk, m_new, l_new, acc_new

    # Derive the initial accumulators from q (not fresh constants) so they
    # carry q's varying-manual-axes type — the new shard_map's VMA tracking
    # rejects a scan whose carry starts unvarying but becomes varying.
    acc0 = jnp.moveaxis(qg, 1, 3).astype(jnp.float32) * 0.0  # [B,K,G,S_l,D]
    l0 = acc0[..., :1]
    m0 = l0 + NEG_INF
    _, _, m, l, acc = jax.lax.fori_loop(0, sp, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)                      # [B,K,G,S_l,D]
    out = jnp.moveaxis(out, 3, 1).reshape(B, S_l, H, D)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, axis: str = "sp"
) -> Callable[..., jax.Array]:
    """Build a drop-in replacement for ``causal_prefill_attention`` that
    runs ring attention over ``axis``, for both the lengths-free training/
    oracle form and RAGGED right-padded batches (serving prefill: each
    sequence masks by its own global length inside every ring step).
    Heads stay tensor-parallel over "tp"; batch over "dp"."""
    spec = P("dp", "sp", "tp", None)
    len_spec = P("dp")  # lengths replicated over sp/tp, batch over dp
    local = functools.partial(
        _local_ring_attention, axis=axis, sp=int(mesh.shape[axis])
    )
    try:
        from jax import shard_map

        mapped = shard_map(
            local, mesh=mesh,
            in_specs=(spec, spec, spec, len_spec), out_specs=spec,
        )
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        mapped = shard_map(
            local, mesh=mesh,
            in_specs=(spec, spec, spec, len_spec), out_specs=spec,
            check_rep=False,
        )

    def ring_attn(q, k, v, lengths=None):
        if lengths is None:
            lengths = jnp.full((q.shape[0],), q.shape[1], jnp.int32)
        return mapped(q, k, v, jnp.asarray(lengths, jnp.int32))

    return ring_attn
