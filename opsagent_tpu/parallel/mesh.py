"""Device mesh and sharding utilities: the distributed communication
backend of the framework.

The reference has no distributed layer at all (SURVEY.md section 2.2); this
module is the foundation of the new framework's TPU story: a named
``jax.sharding.Mesh`` with axes

- ``pp``  — pipeline parallel (GPipe layer stages, parallel/pipeline.py),
- ``dp``  — data/batch parallel (concurrent agent sessions),
- ``sp``  — sequence/context parallel (long-context prefill, ring attention),
- ``ep``  — expert parallel (MoE expert dimension; the grouped dispatch's
  per-expert buckets shard over it, so expert weights AND expert compute
  scale out — the DeepSeek-V3-class configuration),
- ``tp``  — tensor parallel (attention heads / MLP hidden, over ICI).

Axis ORDER encodes the network topology: outer axes map to the slower
links. Across hosts/slices, ``init_distributed()`` then
``make_mesh(dp=jax.process_count(), tp=per_host_devices)`` lays pp/dp on
DCN while sp/tp stay on intra-slice ICI — the placement "How to Scale
Your Model" prescribes: gradient/batch traffic tolerates DCN latency,
per-layer collectives (all-reduce at the row-parallel matmuls, ppermute
in the ring) do not.

All model code expresses placement as ``PartitionSpec`` trees over these
axis names; XLA inserts the collectives (psum / all-gather /
reduce-scatter / ppermute) from the shardings — there is no hand-written
NCCL-style backend, because the XLA runtime IS the collective backend.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshAxes:
    pp: str = "pp"
    dp: str = "dp"
    tp: str = "tp"
    sp: str = "sp"
    ep: str = "ep"


AXES = MeshAxes()


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join a multi-host JAX runtime (the DCN half of the comm backend).

    Call once per host before building meshes; afterwards ``jax.devices()``
    spans every host and ``make_mesh`` shards across them transparently.
    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) and to TPU-pod metadata when
    launched by the TPU runtime (all-None on a pod slice). Returns the
    process count. No-op (returns 1) when neither arguments nor env are
    present — single-host runs need no coordinator."""
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    npxs = num_processes if num_processes is not None else (
        int(os.environ["JAX_NUM_PROCESSES"])
        if "JAX_NUM_PROCESSES" in os.environ else None
    )
    pid = process_id if process_id is not None else (
        int(os.environ["JAX_PROCESS_ID"])
        if "JAX_PROCESS_ID" in os.environ else None
    )
    if (
        addr is None and npxs is None and pid is None
        and not os.environ.get("TPU_WORKER_ID")
    ):
        return 1
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=npxs, process_id=pid
    )
    return jax.process_count()


def make_mesh(
    tp: int | None = None,
    dp: int = 1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: list[Any] | None = None,
) -> Mesh:
    """Build a (pp, dp, sp, ep, tp) mesh. ``tp=None`` uses all remaining
    devices. Axis order puts pp/dp outermost so they land on the slowest
    links (DCN across slices) and ep/tp innermost (ICI — the MoE
    all-to-all and the Megatron all-reduces are the latency-critical
    collectives)."""
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if tp is None or tp <= 0:
        if n % (pp * dp * sp * ep) != 0:
            raise ValueError(
                f"{n} devices not divisible by pp*dp*sp*ep="
                f"{pp * dp * sp * ep}"
            )
        tp = n // (pp * dp * sp * ep)
    need = pp * dp * sp * ep * tp
    if need > n:
        raise ValueError(
            f"mesh pp={pp} dp={dp} sp={sp} ep={ep} tp={tp} needs {need} "
            f"devices, have {n}"
        )
    grid = np.array(devs[:need]).reshape(pp, dp, sp, ep, tp)
    return Mesh(grid, (AXES.pp, AXES.dp, AXES.sp, AXES.ep, AXES.tp))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_params(params: Any, specs: Any, mesh: Mesh) -> Any:
    """Place a parameter pytree onto the mesh according to a matching pytree
    of PartitionSpecs (device_put handles resharding/replication)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def spec_tree_shardings(specs: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
