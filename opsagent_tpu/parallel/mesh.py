"""Device mesh and sharding utilities.

The reference has no distributed layer at all (SURVEY.md section 2.2); this
module is the foundation of the new framework's TPU story: a named
``jax.sharding.Mesh`` with axes

- ``dp``  — data/batch parallel (concurrent agent sessions),
- ``tp``  — tensor parallel (attention heads / MLP hidden, over ICI),
- ``sp``  — sequence/context parallel (long-context prefill, ring attention).

All model code expresses placement as ``PartitionSpec`` trees over these axis
names; XLA inserts the collectives (psum / all-gather / reduce-scatter) from
the shardings — there is no hand-written NCCL-style backend to port.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshAxes:
    dp: str = "dp"
    tp: str = "tp"
    sp: str = "sp"


AXES = MeshAxes()


def make_mesh(
    tp: int | None = None,
    dp: int = 1,
    sp: int = 1,
    devices: list[Any] | None = None,
) -> Mesh:
    """Build a (dp, sp, tp) mesh. ``tp=None`` uses all remaining devices.

    On a single host this is the v5e slice over ICI; across hosts
    ``jax.distributed.initialize`` extends the same mesh over DCN with dp/pp
    as the outer (slow) axes, which is why dp is the leading mesh dim.
    """
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if tp is None or tp <= 0:
        if n % (dp * sp) != 0:
            raise ValueError(f"{n} devices not divisible by dp*sp={dp * sp}")
        tp = n // (dp * sp)
    need = dp * sp * tp
    if need > n:
        raise ValueError(f"mesh dp={dp} sp={sp} tp={tp} needs {need} devices, have {n}")
    grid = np.array(devs[:need]).reshape(dp, sp, tp)
    return Mesh(grid, (AXES.dp, AXES.sp, AXES.tp))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_params(params: Any, specs: Any, mesh: Mesh) -> Any:
    """Place a parameter pytree onto the mesh according to a matching pytree
    of PartitionSpecs (device_put handles resharding/replication)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def spec_tree_shardings(specs: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
