from .mesh import make_mesh, MeshAxes, shard_params, replicate

__all__ = ["make_mesh", "MeshAxes", "shard_params", "replicate"]
