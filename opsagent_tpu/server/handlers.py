"""HTTP handlers.

Capability parity with the reference's pkg/handlers/: login (auth.go:25-72),
execute with its 4-stage tolerant response parse and tools_history
reconstruction (execute.go:106-444), perf stats/reset (perf.go:12-39), and
version (version.go:8-13). The reference's analyze/diagnose handlers are
placeholder stubs (analyze.go:25-27, diagnose.go:26-28); here they are
implemented for real over the workflow/agent layer.
"""

from __future__ import annotations

import asyncio
import contextvars
import json
from typing import Any

from aiohttp import web

from .. import VERSION, obs
from ..agent.prompts import DIAGNOSE_SYSTEM_PROMPT, EXECUTE_SYSTEM_PROMPT_CN
from ..agent.react import assistant_with_config
from ..llm.client import ChatClient, LLMError
from ..tools import ToolPrompt
from ..utils.globalstore import get_global
from ..utils.jsonrepair import extract_field, parse_json
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats, trace_func
from .jwtauth import issue_token

log = get_logger("server")

DEFAULT_USERNAME = "admin"
DEFAULT_PASSWORD = "novastar"
DEFAULT_MODEL = "gpt-4"
SERVER_MAX_TOKENS = 8192
SERVER_MAX_ITERATIONS = 5


# -- auth -------------------------------------------------------------------
async def login(request: web.Request) -> web.Response:
    try:
        body = await request.json()
    except json.JSONDecodeError:
        return web.json_response({"error": "invalid JSON body"}, status=400)
    username = body.get("username", "")
    password = body.get("password", "")
    expected_user = get_global("username", DEFAULT_USERNAME)
    expected_pass = get_global("password", DEFAULT_PASSWORD)
    if username != expected_user or password != expected_pass:
        return web.json_response({"error": "invalid credentials"}, status=401)
    key = get_global("jwtKey", "")
    if not key:
        return web.json_response({"error": "server JWT key not configured"}, status=500)
    token = issue_token(username, key)
    return web.json_response({"token": token, "expires_in": 24 * 3600})


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": VERSION})


# -- execute ----------------------------------------------------------------
def _tools_history(chat_history: list[dict[str, Any]]) -> list[dict[str, str]]:
    """Reconstruct the tool-call history by re-parsing the user messages the
    ReAct loop marshaled (reference execute.go:224-244)."""
    out: list[dict[str, str]] = []
    for msg in chat_history:
        if msg.get("role") != "user":
            continue
        try:
            tp = ToolPrompt.from_json(msg.get("content") or "")
        except (ValueError, TypeError):
            continue
        if tp.action.name:
            out.append(
                {
                    "name": tp.action.name,
                    "input": tp.action.input,
                    "observation": tp.observation,
                }
            )
    return out


def _parse_agent_response(
    response: str,
    tools_history: list[dict[str, str]],
    show_thought: bool,
) -> dict[str, Any]:
    """4-stage tolerant parse of the agent's final response (reference
    execute.go:250-404): strict JSON -> regex field extraction -> cleaned JSON
    -> generic map -> raw passthrough."""

    def ok(
        message: str,
        thought: str = "",
        question: str = "",
        action: Any = None,
        observation: str = "",
        raw: bool = False,
    ) -> dict[str, Any]:
        data: dict[str, Any] = {"message": message, "status": "success"}
        if raw:
            data["raw_response"] = True
        if show_thought:
            data["thought"] = thought
            data["question"] = question
            data["action"] = action if action is not None else {}
            data["observation"] = observation
            data["tools_history"] = tools_history
        return data

    # Stage 1: strict JSON with a final_answer.
    try:
        obj = json.loads(response)
        if isinstance(obj, dict) and obj.get("final_answer"):
            tp = ToolPrompt.from_dict(obj)
            return ok(
                tp.final_answer, tp.thought, tp.question,
                {"name": tp.action.name, "input": tp.action.input}, tp.observation,
            )
    except (json.JSONDecodeError, ValueError):
        pass

    # Stage 2: regex field extraction from JSON-ish text.
    final = extract_field(response, "final_answer")
    if final:
        return ok(
            final,
            extract_field(response, "thought"),
            extract_field(response, "question"),
            extract_field(response, "action"),
            extract_field(response, "observation"),
        )

    # Stage 3: repair then parse (parse_json retries with clean_json itself).
    try:
        obj = parse_json(response)
        if isinstance(obj, dict):
            if obj.get("final_answer"):
                tp = ToolPrompt.from_dict(obj)
                return ok(
                    tp.final_answer, tp.thought, tp.question,
                    {"name": tp.action.name, "input": tp.action.input}, tp.observation,
                )
            # Stage 4: generic map — surface whatever fields exist.
            msg = obj.get("message") or obj.get("answer") or obj.get("content")
            if isinstance(msg, str) and msg:
                return ok(msg, str(obj.get("thought") or ""))
    except ValueError:
        pass

    # Stage 5: raw passthrough.
    return ok(response, raw=True)


async def execute(request: web.Request) -> web.Response:
    perf = get_perf_stats()
    stop = trace_func("execute_total")
    try:
        api_key = request.headers.get("X-API-Key", "")
        if not api_key and not get_global("allowAnonymousLLM", False):
            return web.json_response({"error": "Missing API Key"}, status=400)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON body"}, status=400)
        instructions = body.get("instructions", "")
        if not instructions:
            return web.json_response({"error": "instructions is required"}, status=400)

        show_thought_q = request.query.get("show-thought", "")
        if show_thought_q != "":
            show_thought = show_thought_q == "true"
        else:
            show_thought = bool(get_global("showThought", False))

        model = body.get("currentModel") or DEFAULT_MODEL
        base_url = body.get("baseUrl") or ""
        messages = [
            {"role": "system", "content": EXECUTE_SYSTEM_PROMPT_CN},
            {"role": "user", "content": instructions},
        ]
        # Root the request's span tree on the ingress request ID (minted
        # by logging_middleware) and run the agent INSIDE that context:
        # run_in_executor does not propagate contextvars by itself, so the
        # worker thread gets an explicit copy — the ReAct loop's llm_turn
        # and tool_exec spans land under this root.
        rid = request.get("request_id") or obs.new_request_id()

        def run_traced() -> tuple[str, list[dict[str, Any]]]:
            with obs.trace_request(rid):
                return assistant_with_config(
                    model,
                    messages,
                    SERVER_MAX_TOKENS,
                    True,
                    True,
                    SERVER_MAX_ITERATIONS,
                    api_key,
                    base_url,
                )

        ctx = contextvars.copy_context()
        try:
            response, history = await asyncio.get_running_loop().run_in_executor(
                None, lambda: ctx.run(run_traced)
            )
        except LLMError as e:
            return web.json_response(
                {"error": f"agent failed: {e}", "status": "error",
                 "request_id": rid},
                status=500,
            )
        tools_history = _tools_history(history)
        with perf.timer("execute_response_parse"):
            data = _parse_agent_response(response, tools_history, show_thought)
        data["request_id"] = rid
        return web.json_response(data)
    finally:
        stop()


# -- analyze / diagnose -----------------------------------------------------
async def analyze(request: web.Request) -> web.Response:
    """Fetch the live object and run the analysis workflow (the reference's
    handler is a TODO stub, pkg/handlers/analyze.go:25-27 — implemented here)."""
    try:
        body = await request.json()
    except json.JSONDecodeError:
        return web.json_response({"error": "invalid JSON body"}, status=400)
    resource = body.get("resource", "pod")
    name = body.get("name", "")
    namespace = body.get("namespace", "default")
    if not name:
        return web.json_response({"error": "name is required"}, status=400)
    model = body.get("currentModel") or DEFAULT_MODEL
    api_key = request.headers.get("X-API-Key", "")
    base_url = body.get("baseUrl") or ""

    def run() -> str:
        from ..k8s import get_yaml
        from ..workflows import analysis_flow

        manifest = get_yaml(resource, name, namespace)
        client = ChatClient(api_key=api_key, base_url=base_url)
        return analysis_flow(model, manifest, client=client)

    try:
        result = await asyncio.get_running_loop().run_in_executor(None, run)
    except Exception as e:  # noqa: BLE001 - surfaced as HTTP error
        return web.json_response({"error": str(e), "status": "error"}, status=500)
    return web.json_response({"message": result, "status": "success"})


async def diagnose(request: web.Request) -> web.Response:
    """Diagnose a pod with the ReAct loop (the reference's handler is a TODO
    stub, pkg/handlers/diagnose.go:26-28 — implemented here)."""
    try:
        body = await request.json()
    except json.JSONDecodeError:
        return web.json_response({"error": "invalid JSON body"}, status=400)
    pod = body.get("pod") or body.get("name") or ""
    namespace = body.get("namespace", "default")
    if not pod:
        return web.json_response({"error": "pod is required"}, status=400)
    model = body.get("currentModel") or DEFAULT_MODEL
    api_key = request.headers.get("X-API-Key", "")
    base_url = body.get("baseUrl") or ""
    messages = [
        {"role": "system", "content": DIAGNOSE_SYSTEM_PROMPT},
        {
            "role": "user",
            "content": f"Diagnose the Pod '{pod}' in namespace '{namespace}'.",
        },
    ]
    try:
        response, history = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: assistant_with_config(
                model, messages, SERVER_MAX_TOKENS, True, True,
                SERVER_MAX_ITERATIONS, api_key, base_url,
            ),
        )
    except LLMError as e:
        return web.json_response(
            {"error": f"diagnose failed: {e}", "status": "error"}, status=500
        )
    data = _parse_agent_response(response, _tools_history(history), False)
    return web.json_response(data)


# -- perf / observability -----------------------------------------------------
async def perf_stats(request: web.Request) -> web.Response:
    return web.json_response({"stats": get_perf_stats().get_stats()})


async def perf_reset(request: web.Request) -> web.Response:
    get_perf_stats().reset()
    return web.json_response({"status": "reset"})


async def metrics(request: web.Request) -> web.Response:
    """Prometheus text-format exposition (the serving engine mounts its
    own /metrics; co-hosted deployments scrape either — one process-wide
    registry)."""
    return web.Response(
        text=obs.metrics_text(), content_type="text/plain", charset="utf-8"
    )


async def trace_get(request: web.Request) -> web.Response:
    """The span tree of one request (by the X-Request-Id echoed on every
    response / the request_id field of execute responses)."""
    t = obs.get_trace(request.match_info["request_id"])
    if t is None:
        return web.json_response({"error": "unknown request_id"}, status=404)
    return web.json_response(t)


async def timeline_get(request: web.Request) -> web.Response:
    """The assembled lifecycle timeline of one request (trace spans +
    flight events -> non-overlapping phases, goodput split, attributable
    events; obs/timeline.py). JWT-guarded like /api/trace: phases carry
    tool names and the events can carry request-derived attrs."""
    tl = obs.timeline.assemble(request.match_info["request_id"])
    if tl is None:
        return web.json_response({"error": "unknown request_id"}, status=404)
    return web.json_response(tl)


async def memory_profile(request: web.Request) -> web.Response:
    """GET /api/debug/memory — dump the device memory profile (pprof
    format: which buffers hold HBM right now) into the operator's
    profile dir. Guarded exactly like /api/debug/profile: JWT via the
    /api/ prefix, and the destination is operator-configured only."""
    import os
    import time as _time

    from ..utils.profiling import profile_dir, save_device_memory_profile

    logdir = profile_dir()
    if not logdir:
        return web.json_response(
            {"error": "profiling not enabled: start the server with "
                      "--profile-dir (or set OPSAGENT_PROFILE_DIR)"},
            status=403,
        )
    stamp = _time.strftime("%Y%m%dT%H%M%S", _time.gmtime())
    path = os.path.join(logdir, f"memory-{stamp}.prof")
    try:
        os.makedirs(logdir, exist_ok=True)
        await asyncio.get_running_loop().run_in_executor(
            None, save_device_memory_profile, path
        )
    except Exception as e:  # noqa: BLE001 - surfaced to the caller
        return web.json_response({"error": str(e)}, status=500)
    return web.json_response({"status": "saved", "path": path})


async def flight_get(request: web.Request) -> web.Response:
    """The flight recorder's event ring (newest last): admissions,
    dispatch compositions, tool executions, compiles, anomalies.
    ``?n=`` caps the event count, ``?kind=`` filters by event kind."""
    try:
        n = int(request.query["n"]) if "n" in request.query else None
    except ValueError:
        return web.json_response({"error": "n must be an integer"}, status=400)
    rec = obs.flight.get_recorder()
    return web.json_response({
        **rec.stats(),
        "events": rec.snapshot(n=n, kind=request.query.get("kind")),
    })


async def slo_get(request: web.Request) -> web.Response:
    """Declared SLO verdicts (pass/fail + burn rate), evaluated live from
    the same histograms ``/metrics`` exposes."""
    return web.json_response(obs.slo.evaluate())


async def history_get(request: web.Request) -> web.Response:
    """GET /api/metrics/history?series=a,b&since=300&step=10 — recent
    numeric telemetry from the in-process TelemetryHistory rings (1 s /
    10 s / 60 s tiers). Public like /metrics: series are numbers only."""
    try:
        kwargs = obs.history.parse_query(request.query)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response(obs.history.query(**kwargs))


async def profile_capture(request: web.Request) -> web.Response:
    """POST /api/debug/profile?seconds=N — capture a jax.profiler device
    trace around live traffic (requires --profile-dir /
    $OPSAGENT_PROFILE_DIR; see serving/api.py for the engine-side twin)."""
    from ..utils.profiling import timed_capture

    try:
        seconds = float(request.query.get("seconds", "5"))
    except ValueError:
        return web.json_response(
            {"error": "seconds must be a number"}, status=400
        )
    try:
        logdir = await asyncio.get_running_loop().run_in_executor(
            None, timed_capture, seconds
        )
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    except RuntimeError as e:
        return web.json_response({"error": str(e)}, status=403)
    except Exception as e:  # noqa: BLE001 - already tracing / bad dir
        return web.json_response({"error": str(e)}, status=409)
    return web.json_response(
        {"status": "captured", "seconds": seconds, "logdir": logdir}
    )
