"""HS256 JWT encode/decode with no external dependency.

Capability parity with the reference's pkg/handlers/auth.go:42-61 and
pkg/middleware/jwt.go:18-63 (HS256 tokens, 24h expiry, key from the global
store, username claim).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any


class JWTError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def encode(payload: dict[str, Any], key: str) -> str:
    header = {"alg": "HS256", "typ": "JWT"}
    signing_input = (
        _b64url(json.dumps(header, separators=(",", ":")).encode())
        + "."
        + _b64url(json.dumps(payload, separators=(",", ":")).encode())
    )
    sig = hmac.new(key.encode(), signing_input.encode(), hashlib.sha256).digest()
    return signing_input + "." + _b64url(sig)


def decode(token: str, key: str) -> dict[str, Any]:
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
    except ValueError as e:
        raise JWTError("malformed token") from e
    signing_input = f"{header_b64}.{payload_b64}"
    expected = hmac.new(key.encode(), signing_input.encode(), hashlib.sha256).digest()
    if not hmac.compare_digest(expected, _unb64url(sig_b64)):
        raise JWTError("invalid signature")
    try:
        header = json.loads(_unb64url(header_b64))
        payload = json.loads(_unb64url(payload_b64))
    except (ValueError, json.JSONDecodeError) as e:
        raise JWTError("malformed token body") from e
    if header.get("alg") != "HS256":
        raise JWTError(f"unsupported alg {header.get('alg')}")
    exp = payload.get("exp")
    if exp is not None and time.time() > float(exp):
        raise JWTError("token expired")
    return payload


def issue_token(username: str, key: str, ttl_seconds: int = 24 * 3600) -> str:
    now = int(time.time())
    return encode({"username": username, "iat": now, "exp": now + ttl_seconds}, key)
