"""HTTP router and middleware.

Capability parity with the reference's pkg/api/router.go: recovery + logging
middleware (router.go:29-30), permissive CORS including the X-API-Key header
(router.go:33-42), request/response debug logging (router.go:45-75), global
OPTIONS 204 (router.go:78-80), and the route table (router.go:82-106), plus
the JWT middleware (pkg/middleware/jwt.go).
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from aiohttp import web

from .. import obs
from ..utils.globalstore import get_global
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats
from . import handlers
from .jwtauth import JWTError, decode

log = get_logger("api")

_CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, PUT, DELETE, OPTIONS",
    "Access-Control-Allow-Headers": (
        "Origin, Content-Type, Content-Length, Accept-Encoding, "
        "Authorization, X-API-Key, X-Requested-With"
    ),
}

Handler = Callable[[web.Request], Awaitable[web.StreamResponse]]

# /api/slo and /api/metrics/history are public like /metrics: read-only
# health summaries / numeric series a CI gate, prober, or `opsagent top`
# hits without credentials. The flight ring and profile capture stay
# behind the JWT — event attrs can carry request payloads.
PUBLIC_PATHS = {
    "/login", "/api/version", "/healthz", "/metrics", "/api/slo",
    "/api/metrics/history",
}


@web.middleware
async def cors_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    if request.method == "OPTIONS":
        return web.Response(status=204, headers=_CORS_HEADERS)
    resp = await handler(request)
    resp.headers.update(_CORS_HEADERS)
    return resp


@web.middleware
async def recovery_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except Exception:  # noqa: BLE001 - recovery boundary
        log.exception("panic in handler %s %s", request.method, request.path)
        return web.json_response({"error": "internal server error"}, status=500)


@web.middleware
async def logging_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    # Request ID at ingress: honor a client/proxy-supplied X-Request-Id,
    # mint one otherwise. Handlers root their trace span tree on it
    # (handlers.execute), and the response echoes it so clients can fetch
    # GET /api/trace/{id} afterwards.
    rid = request.headers.get("X-Request-Id") or obs.new_request_id()
    request["request_id"] = rid
    perf = get_perf_stats()
    t0 = time.perf_counter()
    with perf.timer(f"http.{request.method}.{request.path}"):
        resp = await handler(request)
    dt = time.perf_counter() - t0
    status = getattr(resp, "status", 0)
    obs.HTTP_REQUESTS.inc(
        method=request.method, path=request.path, status=str(status)
    )
    obs.HTTP_LATENCY_SECONDS.observe(dt, path=request.path)
    try:
        resp.headers["X-Request-Id"] = rid
    except Exception:  # noqa: BLE001 - prepared stream responses
        pass
    log.info(
        "%s %s -> %d",
        request.method,
        request.path,
        status,
        extra={"fields": {"remote": request.remote, "request_id": rid}},
    )
    return resp


@web.middleware
async def jwt_middleware(request: web.Request, handler: Handler) -> web.StreamResponse:
    if request.method == "OPTIONS" or request.path in PUBLIC_PATHS:
        return await handler(request)
    if request.path.startswith("/api/"):
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return web.json_response(
                {"error": "missing or malformed Authorization header"}, status=401
            )
        key = get_global("jwtKey", "")
        if not key:
            # Never verify with an empty HMAC key — that would let anyone
            # forge tokens signed with "".
            return web.json_response(
                {"error": "server JWT key not configured"}, status=500
            )
        try:
            claims = decode(auth[len("Bearer ") :], key)
        except JWTError as e:
            return web.json_response({"error": f"invalid token: {e}"}, status=401)
        request["username"] = claims.get("username", "")
    return await handler(request)


def build_app() -> web.Application:
    app = web.Application(
        middlewares=[
            recovery_middleware,
            cors_middleware,
            logging_middleware,
            jwt_middleware,
        ],
        client_max_size=16 * 1024 * 1024,
    )
    app.router.add_post("/login", handlers.login)
    app.router.add_get("/api/version", handlers.version)
    app.router.add_get("/healthz", handlers.version)
    app.router.add_post("/api/execute", handlers.execute)
    app.router.add_post("/api/diagnose", handlers.diagnose)
    app.router.add_post("/api/analyze", handlers.analyze)
    app.router.add_get("/api/perf/stats", handlers.perf_stats)
    app.router.add_post("/api/perf/reset", handlers.perf_reset)
    app.router.add_get("/metrics", handlers.metrics)
    app.router.add_get("/api/trace/{request_id}", handlers.trace_get)
    app.router.add_get("/api/timeline/{request_id}", handlers.timeline_get)
    app.router.add_get("/api/debug/flight", handlers.flight_get)
    app.router.add_get("/api/debug/memory", handlers.memory_profile)
    app.router.add_get("/api/slo", handlers.slo_get)
    app.router.add_get("/api/metrics/history", handlers.history_get)
    app.router.add_post("/api/debug/profile", handlers.profile_capture)
    return app


def run_server(host: str = "0.0.0.0", port: int = 8080) -> None:
    app = build_app()
    # Continuous SLO evaluation behind GET /api/slo and the
    # opsagent_slo_* scrape gauges.
    obs.slo.get_watchdog().start()
    # Telemetry history sampler behind GET /api/metrics/history.
    obs.history.get_history().start()

    async def _announce(_: web.Application) -> None:
        # Logged from on_startup so the line appears only once the socket is
        # actually bound (readiness signal for scripts tailing the log).
        log.info("opsagent server listening on %s:%d", host, port)

    app.on_startup.append(_announce)
    web.run_app(app, host=host, port=port, print=None)


async def start_background(host: str, port: int) -> web.AppRunner:
    """Start the server inside an existing event loop (used by tests and by
    co-hosting with the serving engine)."""
    app = build_app()
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner
