"""``opsagent audit-fanout`` — run one cluster-scale audit fan-out.

Builds an in-process fleet (N replicas of one engine config behind a
FleetRouter with the fleet-global KV directory on), generates the seeded
synthetic cluster, and runs the plan/scatter/reduce pipeline over it:
the CLI form of the ``audit-fanout`` bench stage, for poking at fan-out
behavior (prefix-hit rate, shed/retry containment, reduce determinism)
without the bench harness around it.

Prints the deterministic report to stdout (``--json`` for the canonical
byte form the tests compare) and the run's serving-side stats to stderr.
Exit 0 when every child audited ok, 1 when any child degraded to a
``finding_unavailable`` row.
"""

from __future__ import annotations

import json
import sys


def run_audit_fanout(
    model: str = "tiny-test",
    resources: int = 64,
    seed: int = 0,
    issue_fraction: float = 0.25,
    replicas: int = 2,
    max_inflight: int = 8,
    max_tokens: int = 16,
    flight_sample: int = 0,
    as_json: bool = False,
    out: str = "",
) -> int:
    """CLI body (jax imports deferred so ``--help`` stays instant)."""
    from dataclasses import replace as dc_replace

    import jax
    import jax.numpy as jnp

    from ..agent.fanout import FanoutConfig, SynthCluster, run_audit
    from ..serving.api import ServingStack
    from ..serving.engine import Engine, EngineConfig
    from ..serving.fleet.router import FleetRouter

    on_tpu = jax.default_backend() == "tpu"
    cfg = EngineConfig(
        model=model,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        max_batch_size=8,
        page_size=16,
        num_pages=2048,
        max_pages_per_seq=64,
        prefill_buckets=(64, 128, 256),
        decode_block=8,
        offload=True,
    )
    router = FleetRouter(sticky=False)
    stacks = []
    try:
        for i in range(max(1, replicas)):
            stack = ServingStack(Engine(dc_replace(cfg)))
            stacks.append(stack)
            stack.engine.warmup("sessions")
            router.add_local(stack, f"fanout-r{i}")
        cluster = SynthCluster(
            resources=resources, seed=seed, issue_fraction=issue_fraction,
        )
        rep = run_audit(router, cluster, FanoutConfig(
            max_inflight=max_inflight,
            max_tokens=max_tokens,
            flight_sample=flight_sample,
        ))
    finally:
        for stack in stacks:
            stack.close()
    if out:
        with open(out, "w") as f:
            f.write(rep.canonical + "\n")
    if as_json:
        print(rep.canonical)
    else:
        print(json.dumps(rep.report, indent=2))
    stats = dict(rep.stats)
    stats["recall"] = rep.recall(cluster)
    print(json.dumps({"fanout_stats": stats}), file=sys.stderr)
    return 0 if stats["outcomes"].get("ok", 0) == resources else 1
