"""``opsagent top`` — a live fleet cockpit over the public read-only
observability endpoints, curses-free (plain ANSI) so it runs anywhere a
terminal does and renders frame-bounded into a file for tests.

One frame per poll interval, assembled from:

- ``GET /api/fleet`` — the replica table (role, health-breaker state,
  queue depth, batch occupancy, MFU, clock skew). Absent (404 /
  connection refused against a bare engine server) the cockpit degrades
  to a single-node view.
- ``GET /api/slo`` — per-class SLO rows (attainment, burn, p95s) from
  the ``classes`` block ``obs.slo.evaluate()`` folds in.
- ``GET /api/metrics/history`` — the telemetry time machine; sparklines
  of decode rate and per-class completions over the last ~2 minutes.
- ``GET /api/fleet/flight?kind=anomaly`` — the fleet flight ledger's
  anomaly tail (replica-tagged, skew-corrected).

No third-party deps: urllib + ANSI escapes. When ``out`` is not a TTY
(piped, or the test harness) the screen-clear escapes are suppressed and
frames are separated by a rule line instead.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, TextIO
from urllib.parse import quote
from urllib.request import urlopen

SPARK = " ▁▂▃▄▅▆▇█"
_CLEAR = "\x1b[2J\x1b[H"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RED = "\x1b[31m"
_GREEN = "\x1b[32m"
_YELLOW = "\x1b[33m"
_RESET = "\x1b[0m"

# decode rate + per-class completion rates drive the sparklines; 10 s
# buckets over 2 minutes keeps a frame to ~12 cells per row.
_SPARK_WINDOW_S = 120.0
_SPARK_STEP_S = 10.0


def _fetch(url: str, timeout_s: float = 5.0) -> dict[str, Any] | None:
    try:
        with urlopen(url, timeout=timeout_s) as resp:  # noqa: S310
            return json.loads(resp.read().decode("utf-8"))
    except Exception:  # noqa: BLE001 - cockpit degrades per-panel
        return None


def sparkline(points: list[list[float]], width: int = 12) -> str:
    """[[ts, value], ...] -> a fixed-width unicode sparkline (newest
    right-aligned; empty cells pad the left)."""
    vals = [float(p[1]) for p in points][-width:]
    if not vals:
        return "·" * width
    hi = max(vals)
    cells = ""
    for v in vals:
        if hi <= 0:
            cells += SPARK[1]
        else:
            idx = 1 + int(round((len(SPARK) - 2) * (v / hi)))
            cells += SPARK[max(1, min(len(SPARK) - 1, idx))]
    return cells.rjust(width, " ")


class _Palette:
    """ANSI codes, or empty strings when color is off."""

    def __init__(self, enabled: bool):
        self.bold = _BOLD if enabled else ""
        self.dim = _DIM if enabled else ""
        self.red = _RED if enabled else ""
        self.green = _GREEN if enabled else ""
        self.yellow = _YELLOW if enabled else ""
        self.reset = _RESET if enabled else ""

    def health(self, state: str) -> str:
        if state == "healthy":
            return f"{self.green}{state}{self.reset}"
        if state == "suspect":
            return f"{self.yellow}{state}{self.reset}"
        return f"{self.red}{state}{self.reset}"

    def verdict(self, ok: Any) -> str:
        if ok is True:
            return f"{self.green}PASS{self.reset}"
        if ok is False:
            return f"{self.red}FAIL{self.reset}"
        return f"{self.dim}-{self.reset}"


def _replica_rows(fleet: dict[str, Any], p: _Palette) -> list[str]:
    rows = [
        f"{'replica':<16} {'role':<8} {'health':<9} {'queue':>5} "
        f"{'occ':>6} {'mfu':>6} {'skew':>9} {'slo':>6}"
    ]
    for r in fleet.get("replicas", []):
        load = r.get("load", {}) or {}
        running = load.get("running", 0)
        cap = r.get("capacity", 0) or 0
        occ = f"{running}/{cap}" if cap else str(running)
        mfu = load.get("mfu")
        mfu_s = f"{float(mfu) * 100:5.1f}%" if mfu is not None else "     -"
        skew = r.get("clock_offset_s", 0.0) or 0.0
        slo = (r.get("slo") or {}).get("pass")
        health = r.get("health", "healthy")
        # ANSI codes break f-string width padding; pad the raw text.
        rows.append(
            f"{r.get('id', '?'):<16} {r.get('role', '?'):<8} "
            f"{health:<9} {load.get('queued', 0):>5} "
            f"{occ:>6} {mfu_s:>6} {skew * 1e3:>+7.1f}ms "
            f"{'PASS' if slo is True else 'FAIL' if slo is False else '-':>6}"
        )
    return rows


def _class_rows(
    slo: dict[str, Any], hist: dict[str, Any] | None, p: _Palette
) -> list[str]:
    classes = (slo or {}).get("classes", [])
    rows = [
        f"{'class':<12} {'reqs':>6} {'attain':>7} {'burn5m':>7} "
        f"{'ttft p95':>9} {'itl p95':>8}  trend"
    ]
    series = (hist or {}).get("series", {})
    for c in classes:
        cls = c.get("class", "?")
        att = c.get("attainment")
        w5 = (c.get("windows") or {}).get("5m", {})
        burn = w5.get("burn_rate")
        ttft = c.get("ttft_p95_ms")
        itl = c.get("itl_p95_ms")
        spark = sparkline(
            _series_points(series, f"class.{cls}.completed")
        )
        rows.append(
            f"{cls:<12} {c.get('requests', 0):>6} "
            f"{(f'{att * 100:5.1f}%' if att is not None else '    -'):>7} "
            f"{(f'{burn:5.2f}' if burn is not None else '    -'):>7} "
            f"{(f'{ttft:7.1f}ms' if ttft is not None else '       -'):>9} "
            f"{(f'{itl:6.1f}ms' if itl is not None else '      -'):>8}  "
            f"{spark}"
        )
    if not classes:
        rows.append(f"{p.dim}(no classified traffic yet){p.reset}")
    return rows


def _series_points(
    series: dict[str, Any], name: str
) -> list[list[float]]:
    """Points for ``name``; on a router payload the local series sits
    unprefixed and remote replicas as ``{rid}:{name}`` — sum the lot so
    the sparkline is fleet-wide."""
    merged: dict[float, float] = {}
    for key, body in series.items():
        if key != name and not key.endswith(f":{name}"):
            continue
        for ts, v in body.get("points", []):
            merged[ts] = merged.get(ts, 0.0) + v
    return [[ts, merged[ts]] for ts in sorted(merged)]


def _series_last(
    series: dict[str, Any], name: str, agg: str = "sum"
) -> float | None:
    """Latest value of ``name`` across the local and ``{rid}:``-prefixed
    replica series (sum for counts, max for rates/ratios); None when no
    series carries a point."""
    vals: list[float] = []
    for key, body in series.items():
        if key != name and not key.endswith(f":{name}"):
            continue
        points = body.get("points", [])
        if points:
            vals.append(float(points[-1][1]))
    if not vals:
        return None
    return max(vals) if agg == "max" else sum(vals)


def _fanout_row(hist: dict[str, Any] | None, p: _Palette) -> list[str]:
    """One cockpit row for audit fan-outs (agent/fanout): active count,
    children done/planned of the newest fan-out, shared-prefix hit rate,
    and the child completion trend — all via the history sampler."""
    series = (hist or {}).get("series", {})
    active = _series_last(series, "fanout.active")
    done = _series_last(series, "fanout.children_done")
    planned = _series_last(series, "fanout.children_planned")
    hit = _series_last(series, "fanout.prefix_hit_rate", agg="max")
    if not any(v for v in (active, done, planned)):
        return [f"{p.dim}(no fan-outs observed){p.reset}"]
    spark = sparkline(_series_points(series, "fanout.children"))
    return [
        f"{'active':<8} {'children':>12} {'prefix hit':>11}  trend",
        f"{int(active or 0):<8} "
        f"{f'{int(done or 0)}/{int(planned or 0)}':>12} "
        f"{(f'{hit * 100:9.1f}%' if hit is not None else '         -'):>11}"
        f"  {spark}",
    ]


def _anomaly_rows(
    flight: dict[str, Any] | None, p: _Palette, n: int = 5
) -> list[str]:
    events = (flight or {}).get("events", [])[-n:]
    if not events:
        return [f"{p.dim}(no anomalies in the ledger){p.reset}"]
    rows = []
    for e in events:
        wall = e.get("wall_corrected", e.get("wall", 0.0))
        age = max(0.0, time.time() - wall)
        rows.append(
            f"{age:>6.1f}s ago  {e.get('replica', e.get('source', '?')):<12} "
            f"{e.get('reason', '?'):<20} {e.get('request_id', '')}"
        )
    return rows


def render_frame(
    fleet: dict[str, Any] | None,
    slo: dict[str, Any] | None,
    hist: dict[str, Any] | None,
    flight: dict[str, Any] | None,
    p: _Palette,
) -> str:
    lines: list[str] = []
    stamp = time.strftime("%H:%M:%S")
    spark = sparkline(
        _series_points((hist or {}).get("series", {}), "decode_tokens"),
        width=24,
    )
    lines.append(
        f"{p.bold}opsagent top{p.reset}  {stamp}   "
        f"decode tok/s trend: {spark}"
    )
    lines.append("")
    if fleet is not None:
        lines.append(f"{p.bold}replicas{p.reset}")
        lines.extend(_replica_rows(fleet, p))
    else:
        lines.append(
            f"{p.bold}replicas{p.reset}  "
            f"{p.dim}(no /api/fleet — single-node view){p.reset}"
        )
    lines.append("")
    lines.append(f"{p.bold}slo classes{p.reset}")
    lines.extend(_class_rows(slo or {}, hist, p))
    lines.append("")
    lines.append(f"{p.bold}audit fan-out{p.reset}")
    lines.extend(_fanout_row(hist, p))
    lines.append("")
    lines.append(f"{p.bold}anomaly tail{p.reset}")
    lines.extend(_anomaly_rows(flight, p))
    return "\n".join(lines) + "\n"


def run_top(
    url: str,
    interval_s: float = 2.0,
    frames: int = 0,
    out: TextIO | None = None,
    color: bool | None = None,
) -> int:
    """Poll ``url`` and render cockpit frames until interrupted (or for
    ``frames`` frames when positive — the test/scripting mode). Returns
    0 when at least one endpoint answered, 1 when nothing ever did."""
    out = out if out is not None else sys.stdout
    tty = bool(getattr(out, "isatty", lambda: False)())
    p = _Palette(color if color is not None else tty)
    base = url.rstrip("/")
    rendered = 0
    got_data = False
    wanted = ",".join(
        ["decode_tokens"]
        + [f"class.{c}.completed"
           for c in ("interactive", "batch", "background")]
        + [f"fanout.{s}" for s in (
            "active", "children_planned", "children_done",
            "prefix_hit_rate", "children",
        )]
    )
    hist_q = (
        f"?since={_SPARK_WINDOW_S}&step={_SPARK_STEP_S}"
        f"&series={quote(wanted)}"
    )
    try:
        while True:
            fleet = _fetch(base + "/api/fleet")
            slo = _fetch(base + "/api/slo")
            hist = _fetch(base + "/api/metrics/history" + hist_q)
            flight = _fetch(base + "/api/fleet/flight?kind=anomaly&n=16")
            got_data = got_data or any(
                x is not None for x in (fleet, slo, hist, flight)
            )
            frame = render_frame(fleet, slo, hist, flight, p)
            if tty:
                out.write(_CLEAR + frame)
            else:
                if rendered:
                    out.write("-" * 72 + "\n")
                out.write(frame)
            out.flush()
            rendered += 1
            if frames and rendered >= frames:
                break
            time.sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return 0 if got_data else 1
