"""``opsagent slo-check`` — the CLI face of the SLO watchdog, usable as
a bench/CI gate.

Three sources, checked in this order:

- ``--url http://host:port`` — fetch ``GET /api/slo`` from a running
  server (agent server, serving engine, or the fleet router — the
  router aggregates every replica's verdicts with replica-prefixed
  names, so one breached replica breaches the fleet check);
- ``--bench BENCH.json`` — read the ``extra.slo`` verdicts bench.py folds
  into its result line (accepts a single JSON object or a JSONL file —
  the last line carrying ``extra.slo`` wins);
- neither — evaluate the declared SLOs against THIS process's metrics
  registry (useful after an in-process bench/library run).

Exit codes: 0 = every evaluated SLO passes, 1 = at least one breach,
2 = no verdicts available (unreachable server, empty registry, bench
line without ``extra.slo``) — distinct so CI can tell "failing" from
"not measured".
"""

from __future__ import annotations

import json
import sys
from typing import Any


def _fetch_url(url: str, timeout_s: float = 10.0) -> dict[str, Any]:
    from urllib.request import urlopen

    base = url.rstrip("/")
    if not base.endswith("/api/slo"):
        base += "/api/slo"
    with urlopen(base, timeout=timeout_s) as resp:  # noqa: S310 - operator URL
        return json.loads(resp.read().decode("utf-8"))


def _read_bench(path: str) -> dict[str, Any] | None:
    """The last ``extra.slo`` block in a BENCH json/jsonl file."""
    found: dict[str, Any] | None = None
    with open(path) as f:
        text = f.read()
    lines = [ln for ln in text.splitlines() if ln.strip()]
    for ln in lines:
        try:
            d = json.loads(ln)
        except json.JSONDecodeError:
            continue
        slo = d.get("extra", {}).get("slo") if isinstance(d, dict) else None
        if slo:
            found = slo
    if found is None and len(lines) != 1:
        # Maybe a pretty-printed single JSON document.
        try:
            d = json.loads(text)
            found = d.get("extra", {}).get("slo")
        except (json.JSONDecodeError, AttributeError):
            pass
    return found


def _format(verdicts: dict[str, Any]) -> str:
    rows = [
        f"{'slo':<22} {'value':>12} {'target':>10} {'burn':>7}  verdict"
    ]
    for v in verdicts.get("slos", []):
        value = v.get("value")
        burn = v.get("burn_rate")
        ok = v.get("pass")
        verdict = "PASS" if ok else ("FAIL" if ok is False else "NO DATA")
        rows.append(
            f"{v.get('name', '?'):<22} "
            f"{value if value is not None else '-':>12} "
            f"{v.get('target', '-'):>10} "
            f"{burn if burn is not None else '-':>7}  "
            f"{verdict} ({v.get('unit', '')})"
        )
    return "\n".join(rows)


def _format_class(c: dict[str, Any]) -> str:
    rows = [
        f"{'window':<10} {'requests':>9} {'attainment':>11} {'burn':>7}"
    ]
    att = c.get("attainment")
    rows.append(
        f"{'overall':<10} {c.get('requests', 0):>9} "
        f"{(f'{att * 100:.2f}%' if att is not None else '-'):>11} "
        f"{'-':>7}"
    )
    for name, w in (c.get("windows") or {}).items():
        watt = w.get("attainment")
        burn = w.get("burn_rate")
        rows.append(
            f"{name:<10} {w.get('requests', 0):>9} "
            f"{(f'{watt * 100:.2f}%' if watt is not None else '-'):>11} "
            f"{(f'{burn:.2f}' if burn is not None else '-'):>7}"
        )
    return "\n".join(rows)


def _check_class(verdicts: dict[str, Any], slo_class: str) -> int:
    """Gate one SLO class: breach when the class burns its error budget
    faster than 1x in any history window (or its overall attainment is
    under target). Exit codes match the global gate: 0/1/2."""
    classes = {
        c.get("class"): c for c in verdicts.get("classes", [])
    }
    c = classes.get(slo_class)
    if c is None or not c.get("requests"):
        print(
            f"slo-check: class {slo_class!r} has no traffic yet",
            file=sys.stderr,
        )
        return 2
    print(f"slo-check: class {slo_class}")
    print(_format_class(c))
    burned = [
        name
        for name, w in (c.get("windows") or {}).items()
        if (w.get("burn_rate") or 0.0) > 1.0 and w.get("requests")
    ]
    att = c.get("attainment")
    target = 1.0 - float(verdicts.get("error_budget", 0.01) or 0.01)
    if burned:
        print(
            f"slo-check: BREACH: class {slo_class} burn>1x over "
            f"{', '.join(sorted(burned))}",
            file=sys.stderr,
        )
        return 1
    if att is not None and att < target:
        print(
            f"slo-check: BREACH: class {slo_class} attainment "
            f"{att * 100:.2f}% < {target * 100:.2f}%",
            file=sys.stderr,
        )
        return 1
    return 0


def run_slo_check(url: str = "", bench: str = "", slo_class: str = "") -> int:
    try:
        if url:
            verdicts = _fetch_url(url)
        elif bench:
            verdicts = _read_bench(bench)
            if verdicts is None:
                print(
                    f"slo-check: {bench} carries no extra.slo block",
                    file=sys.stderr,
                )
                return 2
        else:
            from .. import obs

            verdicts = obs.slo.evaluate()
    except Exception as e:  # noqa: BLE001 - CI gate: report, exit 2
        print(f"slo-check: unavailable: {e}", file=sys.stderr)
        return 2
    if slo_class:
        return _check_class(verdicts, slo_class)
    fleet = verdicts.get("fleet")
    if fleet:
        print(
            f"slo-check: fleet rollup over {fleet.get('replicas', 0)} "
            f"replica(s)"
        )
    print(_format(verdicts))
    slos = verdicts.get("slos", [])
    if not slos or all(v.get("pass") is None for v in slos):
        print("slo-check: no SLO has data yet", file=sys.stderr)
        return 2
    failed = [v["name"] for v in slos if v.get("pass") is False]
    if failed:
        print(f"slo-check: BREACH: {', '.join(failed)}", file=sys.stderr)
        return 1
    return 0
