"""``opsagent perf-check`` — the perf-regression gate.

Compares a fresh bench jsonl (the output of ``python bench.py`` redirected
to a file, or a ``tpu_results_*/bench.jsonl``) against the committed
baseline (``BENCH_r*_local.jsonl``, newest round by default) metric by
metric, with per-metric noise tolerances, and exits nonzero on
regression — so a HydraServe-style cold-start PR (ROADMAP item 4) or an
int4 kernel change (item 1) is caught by CI instead of by the next TPU
bench round.

Semantics:

- Rows match by their ``metric`` string (e.g.
  ``paged_decode_throughput[bench-8b,int8,B=32,tpu]``). Metrics present
  on only one side are reported but never gate (a new stage is not a
  regression; a skipped stage is the budget's business, not this
  gate's).
- When a file carries several rows of one metric (re-runs, the
  cold-restart probe re-using the 1B preset), the BEST row per side is
  compared — max for higher-is-better units, min for lower-is-better —
  so a deliberately-slow probe row can never mask or fake a regression.
- Direction comes from the unit: ``tok/s/chip`` (and any ``*/s*`` unit)
  is higher-better; ``ms``/``s`` are lower-better. Each row's
  ``extra.p50_ttft_ms`` is additionally gated as ``<metric> p50_ttft``
  (lower-better) when both sides carry it.
- Tolerance: relative, default 10 % (``DEFAULT_TOLERANCE``), overridable
  globally (``--tolerance 0.15``) or per metric substring via a JSON
  file (``--tolerances tol.json`` -> ``{"sessions": 0.2}``; longest
  matching substring wins). TTFT comparisons default looser
  (``DEFAULT_TTFT_TOLERANCE``, 25 %): latency percentiles are noisier
  than throughput means.

Exit codes mirror ``slo-check``: 0 = pass, 1 = regression, 2 = nothing
comparable (missing/empty files, zero overlapping metrics). This module
is deliberately jax-free so CI can run it on any box.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any

DEFAULT_TOLERANCE = 0.10
DEFAULT_TTFT_TOLERANCE = 0.25

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def load_rows(path: str) -> list[dict[str, Any]]:
    """Parse one jsonl (or single-json) file into result rows (dicts with
    a ``metric`` key); unparseable lines are skipped like bench_summary."""
    rows: list[dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "metric" in d and "value" in d:
                rows.append(d)
    return rows


def fetch_rows(url: str, timeout_s: float = 10.0) -> list[dict[str, Any]]:
    """Live result rows from a running fleet router: GET
    /api/fleet/bench returns bench-line-shaped rows assembled from every
    replica's current SLO values, so the same compare() that gates bench
    jsonl files gates a running fleet. A bare router base URL gets the
    path appended; a URL already naming a path is fetched as-is."""
    from urllib.request import urlopen

    base = url.rstrip("/")
    if not base.endswith("/api/fleet/bench"):
        base += "/api/fleet/bench"
    with urlopen(base, timeout=timeout_s) as resp:  # noqa: S310 - operator URL
        data = json.loads(resp.read().decode("utf-8"))
    if not isinstance(data, list):
        return []
    return [
        d for d in data
        if isinstance(d, dict) and "metric" in d and "value" in d
    ]


def default_baseline() -> str | None:
    """The newest committed BENCH_r*_local.jsonl in the repo root."""
    paths = sorted(glob.glob(os.path.join(_REPO, "BENCH_r*_local.jsonl")))
    return paths[-1] if paths else None


def _higher_better(unit: str) -> bool:
    u = (unit or "").lower()
    if u in (
        "ms", "s", "seconds", "failed_requests", "errors",
        "request_ready_s", "ms/turn", "overhead_pct", "audit_latency_s",
    ):
        return False
    # tok/s/chip and friends — including prefix_hit_rate (a fan-out
    # whose children start re-prefilling the shared prefix regresses).
    return True


def _series(rows: list[dict[str, Any]]) -> dict[str, dict[str, Any]]:
    """{comparison name: {value, higher_better}} — best row per metric,
    plus the p50-TTFT sub-series where the extras carry one."""
    out: dict[str, dict[str, Any]] = {}
    for d in rows:
        name = d["metric"]
        try:
            value = float(d["value"])
        except (TypeError, ValueError):
            continue
        hb = _higher_better(d.get("unit", ""))
        cur = out.get(name)
        if cur is None or (value > cur["value"]) == hb:
            out[name] = {"value": value, "higher_better": hb}
        ttft = (d.get("extra") or {}).get("p50_ttft_ms")
        if ttft is not None:
            tname = f"{name} p50_ttft"
            try:
                tval = float(ttft)
            except (TypeError, ValueError):
                continue
            if tval <= 0:
                continue  # "0.0" = not measured in that mode
            tcur = out.get(tname)
            if tcur is None or tval < tcur["value"]:
                out[tname] = {
                    "value": tval, "higher_better": False, "ttft": True,
                }
    return out


def _tolerance_for(
    name: str, entry: dict[str, Any],
    tolerance: float | None, per_metric: dict[str, float] | None,
) -> float:
    if per_metric:
        hits = [k for k in per_metric if k in name]
        if hits:
            return float(per_metric[max(hits, key=len)])
    if tolerance is not None:
        return float(tolerance)
    return (
        DEFAULT_TTFT_TOLERANCE if entry.get("ttft") else DEFAULT_TOLERANCE
    )


def compare(
    current_rows: list[dict[str, Any]],
    baseline_rows: list[dict[str, Any]],
    tolerance: float | None = None,
    per_metric: dict[str, float] | None = None,
) -> dict[str, Any]:
    """The gate's pure core: per-metric verdicts + the overall one.
    A metric regresses when it moved past its noise tolerance in the bad
    direction (relative to the baseline value)."""
    cur = _series(current_rows)
    base = _series(baseline_rows)
    verdicts: list[dict[str, Any]] = []
    regressions = 0
    compared = 0
    for name in sorted(set(cur) | set(base)):
        c, b = cur.get(name), base.get(name)
        if c is None or b is None:
            verdicts.append({
                "metric": name,
                "status": "current_only" if b is None else "baseline_only",
            })
            continue
        compared += 1
        tol = _tolerance_for(name, c, tolerance, per_metric)
        hb = b["higher_better"]
        bv, cv = b["value"], c["value"]
        if bv == 0:
            ratio = None
            bad = False
        else:
            ratio = cv / bv
            bad = (ratio < 1.0 - tol) if hb else (ratio > 1.0 + tol)
        verdicts.append({
            "metric": name,
            "status": "regression" if bad else "ok",
            "baseline": bv,
            "current": cv,
            "ratio": None if ratio is None else round(ratio, 4),
            "tolerance": tol,
            "direction": "higher_better" if hb else "lower_better",
        })
        if bad:
            regressions += 1
    return {
        "compared": compared,
        "regressions": regressions,
        "pass": None if compared == 0 else regressions == 0,
        "verdicts": verdicts,
    }


def format_report(report: dict[str, Any]) -> str:
    lines = [
        f"{'metric':62s} {'baseline':>10s} {'current':>10s} "
        f"{'ratio':>7s} {'tol':>5s}  verdict"
    ]
    for v in report["verdicts"]:
        if "baseline" not in v:
            lines.append(f"{v['metric'][:62]:62s} {'—':>10s} {'—':>10s} "
                         f"{'—':>7s} {'—':>5s}  {v['status']}")
            continue
        ratio = v["ratio"]
        lines.append(
            f"{v['metric'][:62]:62s} {v['baseline']:>10.1f} "
            f"{v['current']:>10.1f} "
            f"{'—' if ratio is None else f'{ratio:.3f}':>7s} "
            f"{v['tolerance']:>5.0%}  "
            f"{'REGRESSION' if v['status'] == 'regression' else 'ok'}"
        )
    if report["pass"] is None:
        lines.append("perf-check: NO COMPARABLE METRICS (exit 2)")
    elif report["pass"]:
        lines.append(
            f"perf-check: PASS ({report['compared']} metrics compared)"
        )
    else:
        lines.append(
            f"perf-check: FAIL ({report['regressions']} regression(s) "
            f"over {report['compared']} compared metrics)"
        )
    return "\n".join(lines)


def run_perf_check(
    current: str,
    baseline: str = "",
    tolerance: float | None = None,
    tolerances_file: str = "",
) -> int:
    """CLI body. Exit 0 pass, 1 regression, 2 nothing comparable."""
    import sys

    baseline = baseline or (default_baseline() or "")
    if not baseline or not os.path.exists(baseline):
        print("perf-check: no baseline jsonl found", file=sys.stderr)
        return 2
    from_url = current.startswith(("http://", "https://"))
    if not from_url and (not current or not os.path.exists(current)):
        print(f"perf-check: current file not found: {current!r}",
              file=sys.stderr)
        return 2
    per_metric = None
    if tolerances_file:
        try:
            with open(tolerances_file) as f:
                per_metric = {k: float(v) for k, v in json.load(f).items()}
        except (OSError, ValueError, AttributeError) as e:
            print(f"perf-check: bad --tolerances file: {e}", file=sys.stderr)
            return 2
    if from_url:
        try:
            current_rows = fetch_rows(current)
        except Exception as e:  # noqa: BLE001 - CI gate: report, exit 2
            print(f"perf-check: fleet unreachable: {e}", file=sys.stderr)
            return 2
    else:
        current_rows = load_rows(current)
    report = compare(
        current_rows, load_rows(baseline),
        tolerance=tolerance, per_metric=per_metric,
    )
    print(f"perf-check: current={current} baseline={baseline}")
    print(format_report(report))
    if report["pass"] is None:
        return 2
    return 0 if report["pass"] else 1


def main(argv: list[str]) -> int:
    """scripts/perf_gate.py entrypoint (argparse kept here so the script
    stays a two-line shim)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="perf_gate",
        description="compare a fresh bench jsonl against the committed "
                    "baseline; exit 1 on regression",
    )
    p.add_argument(
        "current",
        help="fresh bench jsonl (result lines), or a running fleet "
             "router's base URL (http://...: live rows are fetched from "
             "GET /api/fleet/bench)",
    )
    p.add_argument(
        "--baseline", default="",
        help="baseline jsonl (default: newest BENCH_r*_local.jsonl)",
    )
    p.add_argument(
        "--tolerance", type=float, default=None,
        help=f"global relative tolerance (default "
             f"{DEFAULT_TOLERANCE:.0%}, TTFT {DEFAULT_TTFT_TOLERANCE:.0%})",
    )
    p.add_argument(
        "--tolerances", default="",
        help="JSON file of {metric substring: tolerance} overrides",
    )
    a = p.parse_args(argv)
    return run_perf_check(
        a.current, baseline=a.baseline, tolerance=a.tolerance,
        tolerances_file=a.tolerances,
    )
